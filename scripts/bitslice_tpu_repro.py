#!/usr/bin/env python
"""Reproduce / bisect the bitslice XLA engine's TPU compile failure.

Round-4 bench probe died with `remote_compile: HTTP 500:
tpu_compile_helper subprocess exit code 1` at the 256 MiB probe size
(BENCH_r04.json tail; VERDICT r4 missing #3). This script runs the
bitslice CTR path at escalating sizes, each in its own subprocess (the
axon worker can crash and take the parent's PJRT client with it —
axon-tpu-pitfalls rule 5), and prints one JSON line per size.

    python scripts/bitslice_tpu_repro.py              # default ladder
    python scripts/bitslice_tpu_repro.py --sizes 1,16 # MiB subset
    OT_BITSLICE_UNROLL=1 python scripts/bitslice_tpu_repro.py ...
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def child(mib: float, op: str) -> int:
    import numpy as np
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from our_tree_tpu.models import aes as aes_mod
    from our_tree_tpu.models.aes import AES
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.resilience import watchdog
    from our_tree_tpu.utils import packing

    dev = jax.devices()[0]
    assert dev.platform != "cpu", "need the real chip"
    pallas_aes.apply_stored_knobs(dev)

    nbytes = int(mib * (1 << 20))
    a = AES(bytes(range(16)))
    host = np.random.default_rng(1337).integers(0, 256, nbytes, dtype=np.uint8)
    # Watchdog-guarded device contact (armed via OT_DISPATCH_DEADLINE;
    # the repro's caller budget is the backstop either way).
    with watchdog.deadline(watchdog.default_deadline_s(),
                           what="bitslice repro staging"):
        words = jax.device_put(
            jnp.asarray(packing.np_bytes_to_words(host)))
        nonce = np.frombuffer(bytes(range(16)), np.uint8)
        ctr_be = jax.device_put(
            jnp.asarray(packing.np_bytes_to_words(nonce).byteswap()))

    if op == "ctr":
        fn = jax.jit(lambda w: aes_mod.ctr_crypt_words(
            w, ctr_be, a.rk_enc, a.nr, "bitslice"))
    else:
        fn = jax.jit(lambda w: aes_mod.ecb_encrypt_words(
            w, a.rk_enc, a.nr, "bitslice"))
    with watchdog.deadline(watchdog.default_deadline_s(),
                           what="bitslice repro compile+run"):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(words))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(words))
        run_s = time.perf_counter() - t0
    digest = int(np.asarray(out).ravel().view(np.uint32).sum(dtype=np.uint32))
    print(json.dumps({
        "mib": mib, "op": op, "ok": True,
        "compile_s": round(compile_s, 1), "run_s": round(run_s, 4),
        "gbps": round(nbytes / run_s / 1e9, 2), "digest": f"{digest:#010x}",
        "unroll": os.environ.get("OT_BITSLICE_UNROLL", ""),
    }), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="1,16,64,256")
    ap.add_argument("--op", default="ctr")
    ap.add_argument("--timeout", type=float, default=600)
    ap.add_argument("--child-mib", type=float, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.child_mib is not None:
        return child(args.child_mib, args.op)

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _devlock_loader import load_devlock, load_resilience

    # Parse the whole ladder up front: a malformed token must fail the run
    # before any device work, not crash the failure-reporting path later.
    sizes = [float(s) for s in args.sizes.split(",")]

    devlock = load_devlock()
    # Shared deadline-guarded child runner (resilience/isolate.py):
    # timeout, process-group SIGKILL, and outcome classification in one
    # place instead of a third hand-rolled copy.
    reisolate = load_resilience("isolate")
    rc_all = 0
    with devlock.hold(wait_budget_s=600.0):
        for mib in sizes:
            tag = f"bitslice {args.op} {mib:g} MiB"
            print(f"## {tag}", flush=True)
            r = reisolate.run_child(
                [sys.executable, os.path.abspath(__file__),
                 "--child-mib", str(mib), "--op", args.op],
                args.timeout, name=f"bitslice-repro:{mib:g}MiB")
            sys.stdout.write(r.out)
            if r.kind == "timeout":
                rc_all = 1
                print(json.dumps({"mib": mib, "ok": False,
                                  "rc": "timeout"}), flush=True)
            elif r.kind == "crash":
                rc_all = 1
                tail = r.err.strip().splitlines()[-12:]
                print(json.dumps({"mib": mib, "ok": False, "rc": r.rc,
                                  "stderr_tail": tail}), flush=True)
    return rc_all


if __name__ == "__main__":
    sys.exit(main())
