#!/usr/bin/env python
"""Per-size tile tuning: does a buffer-size bucket prefer its own tile?

VERDICT r4 #7: the flat tuned tile (utils/ranking knob "tile") was chosen
at one probe size; small buffers might prefer a different grid shape. This
sweep measures CTR GB/s for tiles x sizes on the live chip (tune_tpu's
chained-difference child, one subprocess per cell — tile is an import-time
constant) and persists `tile_by_mib` entries ONLY for buckets whose winner
beats the stored flat tile by a real margin; otherwise it reports the
documented null result. Run alone (single-tenant tunnel).

    python scripts/tune_tile_sizes.py                # 1,8,64 MiB x tiles
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _devlock_loader import load_devlock, load_ranking, load_resilience  # noqa: E402
import tune_tpu  # noqa: E402  (CHILD snippet + default mirrors)

#: A per-size override must beat the flat tile by this factor to be
#: persisted — chained-difference run-to-run spread at small sizes is a
#: few percent, and a map entry costs every later reader a compile key.
MARGIN = 1.05


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", default="1,8,64")
    ap.add_argument("--tiles", default="128,256,512,1024")
    ap.add_argument("--engine", default="auto",
                    help="engine per cell; 'auto' resolves the persisted "
                         "ranking winner in the child")
    ap.add_argument("--timeout", type=float, default=420.0)
    ap.add_argument("--dry-run", action="store_true",
                    help="measure and report; do not persist")
    args = ap.parse_args()

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    sizes = [float(s) for s in args.sizes_mb.split(",") if s]
    tiles = [int(t) for t in args.tiles.split(",") if t]
    devlock = load_devlock()
    ranking = load_ranking()

    cells: dict[float, dict[int, float]] = {}
    digests: dict[float, set] = {}
    platforms = set()
    with devlock.hold(wait_budget_s=900.0,
                      on_wait=lambda p: print(f"# waiting for {p}",
                                              file=sys.stderr)):
        for mib in sizes:
            nbytes = int(mib * (1 << 20)) // 16 * 16
            # Chain sizing follows harness/bench.py:_chain_k's rule: ~2 GiB
            # of chained work so per-pass noise (ms jitter / k) is well
            # under the 5% persist margin — the 512 MiB cap measurably
            # inflated 1-100 MiB best-of rows 10-15% (PERF.md ledger #13).
            k = max(4, min(2048, (2048 << 20) // nbytes))
            for tile in tiles:
                env = dict(os.environ, OT_PALLAS_TILE=str(tile))
                code = tune_tpu.CHILD % {"repo": REPO, "nbytes": nbytes,
                                         "iters": k, "engine": args.engine}
                tag = f"size={mib:g}MiB tile={tile:<5}"
                out = load_resilience("isolate").run_child(
                    [sys.executable, "-u", "-c", code], env=env,
                    timeout_s=args.timeout, name=f"tile:{tag.strip()}")
                if out.kind == "timeout":
                    print(f"{tag} ->  TIMEOUT", flush=True)
                elif not out.ok:
                    msg = out.err.strip().splitlines()
                    print(f"{tag} ->  FAILED "
                          f"({msg[-1] if msg else 'no stderr'})", flush=True)
                else:
                    r = json.loads(out.out.strip().splitlines()[-1])
                    cells.setdefault(mib, {})[tile] = r["gbps"]
                    digests.setdefault(mib, set()).add(r["digest"])
                    platforms.add(r.get("platform", "unknown"))
                    print(f"{tag} ->  {r['gbps']:7.3f} GB/s  "
                          f"digest={r['digest']:#010x}", flush=True)

    bad = [m for m, d in digests.items() if len(d) > 1]
    if bad:
        print(f"WARNING: digests disagree within size(s) {bad} — a tile "
              "computed different ciphertext; not persisting",
              file=sys.stderr)
        return 1
    if not cells or len(platforms) != 1:
        print("# nothing measured on a single platform; not persisting")
        return 1
    platform = platforms.pop()
    stored = ranking.knobs(platform)
    flat_tile = stored.get("tile", tune_tpu._DEFAULT_TILE)

    overrides = {}
    for mib in sorted(cells):
        row = cells[mib]
        best_tile = max(row, key=row.get)
        base = row.get(flat_tile)
        verdict = f"winner tile={best_tile} ({row[best_tile]:.3f} GB/s)"
        if base is None:
            verdict += f"; flat tile={flat_tile} not measured — skipping"
        elif best_tile != flat_tile and row[best_tile] > MARGIN * base:
            # ceil, not truncate: a 1.5 MiB measurement must label a
            # bucket that COVERS 1.5 MiB ("<=2"), and a sub-MiB size must
            # not produce the key "0" (invalid, and _valid_tile_by_mib is
            # all-or-nothing on read — one bad key drops the whole map).
            overrides[str(max(1, math.ceil(mib)))] = best_tile
            verdict += (f" beats flat tile={flat_tile} ({base:.3f}) by "
                        f"{row[best_tile] / base:.2f}x -> persist")
        else:
            verdict += (f"; flat tile={flat_tile} ({base:.3f}) within "
                        f"{MARGIN:.2f}x -> null result, no override")
        print(f"# {mib:g} MiB: {verdict}")

    if not overrides:
        print("# NULL RESULT: no size bucket beats the flat tile by "
              f">{MARGIN:.2f}x; tile_by_mib left unset")
        return 0
    if args.dry_run:
        print(f"# dry run: would persist tile_by_mib={overrides}")
        return 0
    # store_knobs REPLACES the knob record — carry the flat knobs through
    # so the per-size map lands beside them, not instead of them.
    merged = {k: v for k, v in stored.items() if k in ("tile", "mc")}
    merged["tile_by_mib"] = overrides
    if ranking.store_knobs(platform, merged, "tile-size-sweep",
                           int(max(sizes) * (1 << 20))):
        print(f"# persisted tile_by_mib={overrides} beside {merged}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
