#!/usr/bin/env python
"""Measured VPU ceiling: streamed u32 bitwise-op microbenchmark (Pallas).

docs/PERF.md's roofline divides the AES number by an ESTIMATED VPU issue
rate (2-4 T-u32-ops/s — "the exact issue rate per op mix isn't public"),
which makes the quoted "11-20% of ceiling" a 2x-wide claim. This pins the
denominator by measuring it: a Pallas kernel streams VMEM-tiled u32 data
through a chain of XOR/AND ops — the exact op mix of the bitsliced AES
round (ops/bitslice.py) — and reports achieved u32-ops/s.

Two regimes, same kernel:
  - compute-bound: CHAIN=128 dependent ops per element. HBM traffic is
    amortized 128x, so the number is the VPU issue ceiling for this mix.
  - stream-bound: CHAIN=1. One read + one write per 2 ops; the number is
    HBM bandwidth expressed in ops (sanity floor, not the ceiling).

The chain is a two-variable nonlinear feedback (a, b = b, a ^ (b & K))
so neither XLA nor Mosaic can algebraically collapse it; one iteration
costs exactly 2 vector ops (XOR + AND). Timing is bench.py's chained
methodology (T(1+K)-T(1) with a carry perturbation and sum-digest
readback) so per-call overhead and async-dispatch artifacts cancel.

The reference never measured its hardware ceiling at all — its numbers
are -O0 builds (Makefile:13, aes-modes/Makefile:15) with no roofline
anywhere; this script exists so docs/PERF.md can say "X% of MEASURED".

Run on TPU via the recover_watch plan; runs CPU/interpreter for tests
(OT_VPU_BYTES / OT_VPU_ITERS shrink it).
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from our_tree_tpu.utils.platform import pin_cpu_if_requested

NBYTES = int(os.environ.get("OT_VPU_BYTES", 64 << 20))
ITERS = int(os.environ.get("OT_VPU_ITERS", 8))
TILE = 512  # lanes per grid step; sized like pallas_aes.TILE


def _chain_kernel(x_ref, o_ref, *, chain: int, ilp: int = 1):
    import jax
    import jax.numpy as jnp

    x = x_ref[...]
    # `ilp` INDEPENDENT two-variable feedback chains (distinct constants),
    # interleavable by the compiler across the VPU's parallel ALUs. ilp=1
    # serializes on a 2-op dependency and measures the single-chain issue
    # rate — which round 4's AES kernels exceeded by ~70% (the round
    # circuit has abundant ILP), so the ilp>1 regimes exist to measure
    # the saturated rate the roofline actually needs.
    st = tuple((x ^ jnp.uint32((0x9E3779B9 * (2 * i + 1)) & 0xFFFFFFFF),
                x ^ jnp.uint32((0x85EBCA6B * (2 * i + 1)) & 0xFFFFFFFF))
               for i in range(ilp))

    def body(_, st):
        return tuple((b, a ^ (b & jnp.uint32(0xC2B2AE35))) for a, b in st)

    st = jax.lax.fori_loop(0, chain, body, st)
    acc = None
    for a, b in st:
        acc = (a ^ b) if acc is None else acc ^ a ^ b
    o_ref[...] = acc


@functools.lru_cache(None)
def _build(chain: int, lanes: int, tile: int, interpret: bool,
           ilp: int = 1):
    import jax
    from jax.experimental import pallas as pl

    spec = pl.BlockSpec((8, tile), lambda i: (0, i))
    return jax.jit(lambda x: pl.pallas_call(
        functools.partial(_chain_kernel, chain=chain, ilp=ilp),
        grid=(lanes // tile,),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x))


def chained_time(fn, x, iters=ITERS):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chainrun(x, k):
        def body(_, acc):
            return jnp.sum(fn(x ^ acc), dtype=jnp.uint32)

        return jax.lax.fori_loop(jnp.uint32(0), k, body, jnp.uint32(0))

    def run(k):
        t0 = time.perf_counter()
        int(chainrun(x, jnp.uint32(k)))
        return time.perf_counter() - t0

    run(1)
    t1 = min(run(1) for _ in range(2))
    tk = min(run(1 + iters) for _ in range(2))
    return max(tk - t1, 1e-9) / iters


def main() -> int:
    pin_cpu_if_requested()
    import jax
    import jax.numpy as jnp

    from our_tree_tpu.ops.pallas_aes import _interpret
    from our_tree_tpu.resilience import watchdog

    n = NBYTES // 4
    lanes = max(n // 8, TILE)
    lanes -= lanes % TILE
    n = lanes * 8
    interpret = _interpret()
    with watchdog.deadline(watchdog.default_deadline_s(),
                           what="vpu ceiling staging"):
        x = jax.device_put(
            jnp.arange(n, dtype=jnp.uint32).reshape(8, lanes))
    dev = jax.devices()[0]
    print(f"# {n * 4 >> 20} MiB u32, shape (8, {lanes}), tile={TILE}, "
          f"device={dev.platform}/{dev.device_kind}, interpret={interpret}")

    out = {"platform": dev.platform, "device_kind": dev.device_kind,
           "bytes": n * 4}
    for name, chain, ilp in (("stream", 1, 1), ("compute", 128, 1),
                             ("compute-ilp4", 128, 4),
                             ("compute-ilp8", 128, 8)):
        fn = _build(chain, lanes, TILE, interpret, ilp)
        t = chained_time(fn, x)
        # Exact per-element count (ADVICE r4 #4): 2 ops (XOR+AND) per chain
        # step per independent chain, + 2*ilp init XORs, + the tree-free
        # reduction's 1 + 2*(ilp-1) = 2*ilp-1 XORs.
        ops = n * (ilp * 2 * chain + 2 * ilp + 2 * ilp - 1)
        gbps = n * 8 / t / 1e9  # one u32 read + one write per element
        print(f"{name:12s} chain={chain:4d} ilp={ilp}: {t * 1e3:8.2f} ms  "
              f"{ops / t / 1e12:6.3f} T-u32-ops/s  ({gbps:6.1f} GB/s mem)")
        out[name] = {"chain": chain, "ilp": ilp, "sec": t,
                     "t_ops_per_s": ops / t / 1e12, "mem_gb_per_s": gbps}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
