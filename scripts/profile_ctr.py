#!/usr/bin/env python
"""Component timing for the north-star CTR path (run on TPU).

Separates: full models/aes.py CTR path, the fused Pallas kernel alone
(planes pre-made), plane transposition, counter materialisation — so
optimization effort goes where the time is.

Timing uses bench.py's chained methodology: K iterations chained inside
one jit via a carry that perturbs the input (so XLA cannot hoist/CSE the
work) and a scalar sum-digest readback (so completion is real even on
async/tunnelled platforms where block_until_ready returns early); the
reported time is T(1+K) - T(1), cancelling per-call overhead.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from our_tree_tpu.models import aes as aes_mod
from our_tree_tpu.models.aes import AES
from our_tree_tpu.ops import bitslice, pallas_aes
from our_tree_tpu.utils import packing

NBYTES = int(os.environ.get("OT_PROF_BYTES", 128 << 20))
ITERS = int(os.environ.get("OT_PROF_ITERS", 5))


def chained_time(fn, x, *rest, iters=ITERS):
    """T(1+iters) - T(1) for out = fn(x ^ acc, *rest), acc = sum(out)."""

    @jax.jit
    def chain(x, k, *rest):
        def body(_, acc):
            out = fn(x ^ acc, *rest)
            return jnp.sum(out, dtype=jnp.uint32)

        return jax.lax.fori_loop(jnp.uint32(0), k, body, jnp.uint32(0))

    def run(k):
        t0 = time.perf_counter()
        int(chain(x, jnp.uint32(k), *rest))
        return time.perf_counter() - t0

    run(1)
    t1 = min(run(1) for _ in range(2))
    tk = min(run(1 + iters) for _ in range(2))
    return max(tk - t1, 1e-9) / iters


def report(name, t, gb=None):
    rate = f"  {gb/t:7.2f} GB/s" if gb else ""
    print(f"{name:28s}: {t*1e3:8.2f} ms{rate}")


def main():
    a = AES(bytes(range(16)))
    host = np.random.default_rng(1337).integers(0, 256, NBYTES, dtype=np.uint8)
    host_words = packing.np_bytes_to_words(host)
    flat = jax.device_put(jnp.asarray(host_words))          # dense layout
    words = jax.device_put(jnp.asarray(host_words.reshape(-1, 4)))  # padded
    nonce = np.frombuffer(bytes(range(16)), np.uint8)
    ctr_be = jax.device_put(jnp.asarray(packing.np_bytes_to_words(nonce).byteswap()))
    n = words.shape[0]
    gb = NBYTES / 1e9
    # The raw _*_planes_pallas helpers are called below with pre-made plane
    # tiles and no padding of their own, so pad the block batch exactly the
    # way every production entry point does (_lane_pad_and_tile) — the
    # kernel-alone timings then run at the production tile choice instead
    # of a shrunken ad-hoc one, and any OT_PROF_BYTES value is legal.
    pad, tile = pallas_aes._lane_pad_and_tile(n)
    kwords = words
    if pad:
        kwords = jnp.concatenate(
            [words, jnp.zeros((pad, 4), words.dtype)], axis=0)
    print(f"# {NBYTES >> 20} MiB, {n} blocks, tile={tile}, "
          f"device={jax.devices()[0].platform}")

    t = chained_time(
        lambda c, w, rk: aes_mod.ctr_crypt_words(w, c, rk, 10), ctr_be, flat,
        a.rk_enc)
    report("full ctr (flat boundary)", t, gb)

    t = chained_time(
        lambda c, w, rk: aes_mod.ctr_crypt_words(w, c, rk, 10), ctr_be, words,
        a.rk_enc)
    report("full ctr ((N,4) boundary)", t, gb)

    # Kernel-alone components run on the padded batch (kwords), matching the
    # block count and tile the production entry points hand the kernels.
    idx = jnp.arange(n + pad, dtype=jnp.uint32)
    t = chained_time(lambda c: aes_mod.ctr_le_blocks(c, idx), ctr_be)
    report("counter materialisation", t)

    t = chained_time(bitslice.to_planes, kwords)
    report("to_planes (one stream)", t)

    planes = jax.jit(bitslice.to_planes)(kwords)
    t = chained_time(bitslice.from_planes, planes)
    report("from_planes", t)

    ctr_le = jax.jit(lambda c: aes_mod.ctr_le_blocks(c, idx))(ctr_be)
    ctr_planes = jax.jit(bitslice.to_planes)(ctr_le)
    kp = jax.jit(lambda rk: bitslice.key_planes(rk, 10))(a.rk_enc)
    t = chained_time(
        lambda cp, dp, kp: pallas_aes._ctr_planes_pallas(cp, dp, kp, nr=10,
                                                         tile=tile),
        ctr_planes, planes, kp)
    report("fused CTR kernel alone", t, gb)

    t = chained_time(
        lambda dp, kp: pallas_aes._crypt_planes_pallas(dp, kp, nr=10,
                                                       decrypt=False,
                                                       tile=tile),
        planes, kp)
    report("ecb kernel alone", t, gb)

    t = chained_time(
        lambda dp, kp: pallas_aes._crypt_planes_pallas(dp, kp, nr=10,
                                                       decrypt=True,
                                                       tile=tile),
        planes, kp)
    report("ecb decrypt kernel alone", t, gb)

    # Grouped-transpose ("pallas-gt") components: the relayout that replaces
    # to/from_planes, and the kernels that run the SWAR ladder in VMEM.
    t = chained_time(
        lambda c, w, rk: aes_mod.ctr_crypt_words(w, c, rk, 10, "pallas-gt"),
        ctr_be, flat, a.rk_enc)
    report("full ctr (pallas-gt)", t, gb)

    # The group/ungroup relayouts cannot be timed standalone: the chained
    # digest is a permutation-invariant sum, so XLA deletes a bare
    # transpose entirely (sum∘perm == sum). Their cost is the difference
    # between "full ctr (pallas-gt)" and "ctr-gt kernel alone" — the
    # pallas_call is opaque to XLA, so relayouts feeding it are real.
    grouped = jax.jit(bitslice.group_words)(kwords)
    base = jax.jit(pallas_aes._base_bit_masks)(ctr_be)
    t = chained_time(
        lambda g, b, kp: pallas_aes._ctr_gen_planes_pallas(
            g, b, kp, nr=10, tile=tile, layout="grouped"),
        grouped, base, kp)
    report("ctr-gt kernel alone", t, gb)

    # Same kernel with the Boyar–Peralta S-box circuit (engine
    # "pallas-gt-bp"): the difference vs "ctr-gt kernel alone" is the
    # measured value of the 217→162-unit round-arithmetic cut with
    # everything else held identical — the cleanest view of the tower/BP
    # A/B, uncontaminated by boundary relayouts.
    t = chained_time(
        lambda g, b, kp: pallas_aes._ctr_gen_planes_pallas(
            g, b, kp, nr=10, tile=tile, layout="grouped", sbox="bp"),
        grouped, base, kp)
    report("ctr-gt-bp kernel alone", t, gb)

    # Dense (128, W) boundary components ("pallas-dense"): same kernel
    # structure as gt minus the grouped layout's 2x sublane-padding tax.
    # full-vs-kernel-alone difference = the dense relayout's cost; the
    # gt-vs-dense kernel-alone difference = the padding tax + ladder-form
    # scheduling delta, the A/B the layout decision rides on.
    t = chained_time(
        lambda c, w, rk: aes_mod.ctr_crypt_words(w, c, rk, 10,
                                                 "pallas-dense"),
        ctr_be, flat, a.rk_enc)
    report("full ctr (pallas-dense)", t, gb)

    dense = jax.jit(bitslice.dense_words)(kwords)
    t = chained_time(
        lambda d, b, kp: pallas_aes._ctr_gen_planes_pallas(
            d, b, kp, nr=10, tile=tile, layout="dense"),
        dense, base, kp)
    report("ctr-dense kernel alone", t, gb)

    t = chained_time(
        lambda d, b, kp: pallas_aes._ctr_gen_planes_pallas(
            d, b, kp, nr=10, tile=tile, layout="dense", sbox="bp"),
        dense, base, kp)
    report("ctr-dense-bp kernel alone", t, gb)


if __name__ == "__main__":
    sys.exit(main())
