#!/usr/bin/env python
"""Component timing for the north-star CTR path (run on TPU).

Separates: full models/aes.py CTR paths (per engine), the fused Pallas
kernels alone (planes pre-made), plane transposition, counter
materialisation — so optimization effort goes where the time is.

Timing uses bench.py's chained methodology: K iterations chained inside
one jit via a carry that perturbs the input (so XLA cannot hoist/CSE the
work) and a scalar sum-digest readback (so completion is real even on
async/tunnelled platforms where block_until_ready returns early); the
reported time is T(1+K) - T(1), cancelling per-call overhead.

Each component runs in its OWN sequential subprocess (the smoke_tpu /
tune_tpu pattern): the first hardware run of this profile crashed the
axon TPU worker on its first component ("TPU worker process crashed or
restarted ... kernel fault", round 4), and a PJRT client whose worker
died cannot recover in-process — every later component would have
reported the same UNAVAILABLE. Isolated children turn one crash into one
CRASHED row while the other 12 components still measure; the per-child
setup cost (re-staging the buffer) is seconds against a wedge-resistant
profile. The parent stays jax-free and holds the devlock for manual runs
(under the watcher the plan's own marker already serializes).
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NBYTES = int(os.environ.get("OT_PROF_BYTES", 128 << 20))
ITERS = int(os.environ.get("OT_PROF_ITERS", 5))

#: Component registry: name -> human label. Order = report order; the
#: engine-reference rows using the T-table/XLA paths go LAST so a crash
#: there (the observed axon worker fault) cannot shadow the kernel rows.
COMPONENTS = [
    ("ctr-flat-auto", "full ctr (flat, production engine)"),
    ("ctr-gt-full", "full ctr (pallas-gt)"),
    ("ctr-dense-full", "full ctr (pallas-dense)"),
    ("counter-mat", "counter materialisation"),
    ("to-planes", "to_planes (one stream)"),
    ("from-planes", "from_planes"),
    ("ctr-kernel", "fused CTR kernel alone"),
    ("ecb-kernel", "ecb kernel alone"),
    ("ecb-dec-kernel", "ecb decrypt kernel alone"),
    ("ctr-gt-kernel", "ctr-gt kernel alone"),
    ("ctr-gt-bp-kernel", "ctr-gt-bp kernel alone"),
    ("ctr-dense-kernel", "ctr-dense kernel alone"),
    ("ctr-dense-bp-kernel", "ctr-dense-bp kernel alone"),
    ("ctr-flat-jnp", "full ctr (flat, jnp T-table ref)"),
    ("ctr-n4-jnp", "full ctr ((N,4), jnp T-table ref)"),
]


class _ChildTimeout(Exception):
    """A component child hit its deadline (the isolate runner SIGKILLed
    its group) — reported as a TIMEOUT row, never a crash."""


def child(component: str) -> int:
    """Measure ONE component and print a JSON line. With
    ``OT_PROF_CAPTURE`` set (the parent's ``--capture`` flag), the
    measurement runs inside the repo's ONE capture seam
    (our_tree_tpu/obs/profiler.py — the same window serve's
    /profilez and harness.bench --profile arm): the jax trace + window
    summary land in the OT_TRACE_DIR run layout, one capture per
    component child, `obs.report --profile` joins them."""
    if os.environ.get("OT_PROF_CAPTURE"):
        sys.path.insert(0, REPO)
        from our_tree_tpu.obs import profiler as profiler_mod

        with profiler_mod.sweep_capture(armed_by="cli"):
            return _child_measure(component)
    return _child_measure(component)


def _child_measure(component: str) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from our_tree_tpu.models import aes as aes_mod
    from our_tree_tpu.models.aes import AES
    from our_tree_tpu.ops import bitslice, pallas_aes
    from our_tree_tpu.resilience import watchdog
    from our_tree_tpu.utils import packing

    # Profile the PRODUCTION config: stored tuned knobs (tile/MC) applied
    # exactly like bench.py / TpuBackend / resolve_engine("auto") do.
    pallas_aes.apply_stored_knobs()

    def chained_time(fn, x, *rest, iters=ITERS):
        """T(1+iters) - T(1) for out = fn(x ^ acc, *rest), acc = sum(out)."""

        @jax.jit
        def chain(x, k, *rest):
            def body(_, acc):
                out = fn(x ^ acc, *rest)
                return jnp.sum(out, dtype=jnp.uint32)

            return jax.lax.fori_loop(jnp.uint32(0), k, body, jnp.uint32(0))

        def run(k):
            t0 = time.perf_counter()
            int(chain(x, jnp.uint32(k), *rest))
            return time.perf_counter() - t0

        run(1)
        t1 = min(run(1) for _ in range(2))
        tk = min(run(1 + iters) for _ in range(2))
        return max(tk - t1, 1e-9) / iters

    a = AES(bytes(range(16)))
    host = np.random.default_rng(1337).integers(0, 256, NBYTES, dtype=np.uint8)
    host_words = packing.np_bytes_to_words(host)
    # Watchdog-guarded staging (armed via OT_DISPATCH_DEADLINE; the
    # parent's per-component timeout is the backstop either way).
    with watchdog.deadline(watchdog.default_deadline_s(),
                           what="profile input staging"):
        flat = jax.device_put(jnp.asarray(host_words))      # dense layout
        words = jax.device_put(
            jnp.asarray(host_words.reshape(-1, 4)))         # padded
        nonce = np.frombuffer(bytes(range(16)), np.uint8)
        ctr_be = jax.device_put(
            jnp.asarray(packing.np_bytes_to_words(nonce).byteswap()))
    n = words.shape[0]
    # The raw _*_planes_pallas helpers are called below with pre-made plane
    # tiles and no padding of their own, so pad the block batch exactly the
    # way every production entry point does (_lane_pad_and_tile) — the
    # kernel-alone timings then run at the production tile choice instead
    # of a shrunken ad-hoc one, and any OT_PROF_BYTES value is legal.
    pad, tile = pallas_aes._lane_pad_and_tile(n)
    kwords = words
    if pad:
        kwords = jnp.concatenate(
            [words, jnp.zeros((pad, 4), words.dtype)], axis=0)

    def full_ctr(engine):
        return chained_time(
            lambda c, w, rk: aes_mod.ctr_crypt_words(w, c, rk, 10, engine),
            ctr_be, flat, a.rk_enc)

    engine = None
    if component == "ctr-flat-auto":
        engine = aes_mod.resolve_engine("auto")
        t = full_ctr(engine)
    elif component == "ctr-gt-full":
        t = full_ctr("pallas-gt")
    elif component == "ctr-dense-full":
        t = full_ctr("pallas-dense")
    elif component == "ctr-flat-jnp":
        t = full_ctr("jnp")
    elif component == "ctr-n4-jnp":
        t = chained_time(
            lambda c, w, rk: aes_mod.ctr_crypt_words(w, c, rk, 10, "jnp"),
            ctr_be, words, a.rk_enc)
    elif component == "counter-mat":
        idx = jnp.arange(n + pad, dtype=jnp.uint32)
        t = chained_time(lambda c: aes_mod.ctr_le_blocks(c, idx), ctr_be)
    elif component == "to-planes":
        t = chained_time(bitslice.to_planes, kwords)
    elif component == "from-planes":
        planes = jax.jit(bitslice.to_planes)(kwords)
        t = chained_time(bitslice.from_planes, planes)
    else:
        # Kernel-alone components: pre-made inputs, pallas_call only.
        idx = jnp.arange(n + pad, dtype=jnp.uint32)
        kp = jax.jit(lambda rk: bitslice.key_planes(rk, 10))(a.rk_enc)
        if component == "ctr-kernel":
            ctr_le = jax.jit(lambda c: aes_mod.ctr_le_blocks(c, idx))(ctr_be)
            ctr_planes = jax.jit(bitslice.to_planes)(ctr_le)
            planes = jax.jit(bitslice.to_planes)(kwords)
            t = chained_time(
                lambda cp, dp, kp: pallas_aes._ctr_planes_pallas(
                    cp, dp, kp, nr=10, tile=tile,
                    mc=pallas_aes.MC_LOWERING),
                ctr_planes, planes, kp)
        elif component in ("ecb-kernel", "ecb-dec-kernel"):
            planes = jax.jit(bitslice.to_planes)(kwords)
            t = chained_time(
                lambda dp, kp: pallas_aes._crypt_planes_pallas(
                    dp, kp, nr=10, decrypt=(component == "ecb-dec-kernel"),
                    tile=tile, mc=pallas_aes.MC_LOWERING),
                planes, kp)
        elif component in ("ctr-gt-kernel", "ctr-gt-bp-kernel",
                           "ctr-dense-kernel", "ctr-dense-bp-kernel"):
            layout = "grouped" if "gt" in component else "dense"
            sbox = "bp" if "-bp-" in component else None
            pre = (bitslice.group_words if layout == "grouped"
                   else bitslice.dense_words)
            x = jax.jit(pre)(kwords)
            base = jax.jit(pallas_aes._base_bit_masks)(ctr_be)
            t = chained_time(
                lambda g, b, kp: pallas_aes._ctr_gen_planes_pallas(
                    g, b, kp, nr=10, tile=tile, layout=layout, sbox=sbox,
                    mc=pallas_aes.MC_LOWERING),
                x, base, kp)
        else:
            print(json.dumps({"component": component,
                              "error": "unknown component"}))
            return 2
    d = jax.devices()[0]
    print(json.dumps({"component": component, "sec": t, "tile": tile,
                      "mc": pallas_aes.MC_LOWERING, "engine": engine,
                      "platform": d.platform,
                      "device_kind": getattr(d, "device_kind", None)}))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--component", help="(internal) run one component")
    ap.add_argument("--timeout", type=float,
                    default=float(os.environ.get("OT_PROF_TIMEOUT", 240.0)),
                    help="per-component subprocess timeout (healthy "
                         "children finish in ~60-90s incl. compile)")
    ap.add_argument("--budget", type=float,
                    default=float(os.environ.get("OT_PROF_BUDGET", 1500.0)),
                    help="total wall budget; children that would not fit "
                         "are SKIPPED with partial rows reported — sized "
                         "under recover_watch's 1800s outer kill so a "
                         "wedged tunnel yields partial data, not a "
                         "SIGKILLed step retried from scratch")
    ap.add_argument("--capture", action="store_true",
                    help="wrap each component child in the shared "
                         "obs/profiler.py capture window (requires "
                         "OT_TRACE_DIR): jax trace + per-window summary "
                         "in the run layout, joined by "
                         "`obs.report --profile`")
    args = ap.parse_args()
    if args.capture:
        # Children inherit the environment through the isolate spawn;
        # the capture itself stays inside the one profiler seam.
        os.environ["OT_PROF_CAPTURE"] = "1"
    if args.component:
        return child(args.component)

    from _devlock_loader import load_devlock, load_resilience

    gb = NBYTES / 1e9
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    devlock = load_devlock()
    failures = successes = 0
    t_start = time.monotonic()
    header_done = False
    with devlock.hold(wait_budget_s=900.0,
                      on_wait=lambda p: print(f"# waiting for {p}",
                                              file=sys.stderr)):
        print(f"# {NBYTES >> 20} MiB, iters={ITERS}, one subprocess per "
              f"component, {args.timeout:.0f}s each within a "
              f"{args.budget:.0f}s budget")
        for name, label in COMPONENTS:
            left = args.budget - (time.monotonic() - t_start)
            if left < min(args.timeout, 120.0):
                print(f"{label:36s}: SKIPPED (budget exhausted, "
                      f"{left:.0f}s left)", flush=True)
                continue
            try:
                out = load_resilience("isolate").run_child(
                    [sys.executable, "-u", os.path.abspath(__file__),
                     "--component", name],
                    timeout_s=min(args.timeout, left),
                    name=f"profile:{name}",
                )
                if out.kind == "timeout":
                    raise _ChildTimeout
                if not out.ok:
                    err_lines = out.err.strip().splitlines()
                    raise RuntimeError(
                        err_lines[-1] if err_lines
                        else f"rc={out.rc}, empty stderr")
                r = json.loads(out.out.strip().splitlines()[-1])
                t = r["sec"]
                if not header_done:
                    # Provenance once, from the first successful child —
                    # the hwlog artifact must say which config measured.
                    print(f"# tile={r.get('tile')} mc={r.get('mc')} "
                          f"device={r.get('platform')}/"
                          f"{r.get('device_kind')}", flush=True)
                    header_done = True
                eng = f" [{r['engine']}]" if r.get("engine") else ""
                # GB/s only for rows that stream the whole buffer.
                rate = (f"  {gb / t:7.2f} GB/s"
                        if not name.startswith(("counter-",)) else "")
                print(f"{label:36s}: {t * 1e3:8.2f} ms{rate}{eng}",
                      flush=True)
                successes += 1
            except _ChildTimeout:
                failures += 1
                print(f"{label:36s}: TIMEOUT ({args.timeout:.0f}s)",
                      flush=True)
            except Exception as e:
                failures += 1
                print(f"{label:36s}: CRASHED ({str(e)[:160]})", flush=True)
    # Partial success is success: the rows that measured are the artifact.
    # But zero measured rows is failure even with zero "failures" — a
    # wedged first child can eat the whole budget via timeout=min(timeout,
    # left) and leave every later component SKIPPED (ADVICE r4 #3).
    return 0 if successes else 1


if __name__ == "__main__":
    sys.exit(main())
