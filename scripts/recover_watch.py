#!/usr/bin/env python
"""Tunnel-recovery watcher: probe the device, then run the measurement plan.

The build host reaches its one TPU chip through a tunnel that wedges if a
jax process dies mid-device-op (PJRT client init then blocks indefinitely
for every later process, sometimes for hours, until the far side recovers).
Nothing local can unwedge it — so this watcher probes `jax.devices()` in a
throwaway subprocess on an interval and, the moment init succeeds, runs the
full queued hardware measurement plan:

  1. headline bench (probe-selected engine)
  2. 1 GiB BASELINE-metric bench (pallas-gt)
  3. Mosaic compile smoke, full kernel matrix      (scripts/smoke_tpu.py)
  4. tile x MC x S-box x engine tuning sweep       (scripts/tune_tpu.py)
  5. component profile                             (scripts/profile_ctr.py)
  6. results.<host>.tpu sweep corpus               (harness.bench --default-out)

Each step's full stdout+stderr (including the bench JSON lines) lands in
<plan-dir>/<step>.log; the corpus step additionally writes the repo's
results/results.<host>.tpu file itself.

Steps run strictly sequentially (one jax process at a time — the tunnel is
single-tenant; see utils/devlock.py). Every child gets an INTERNAL deadline
(OT_BENCH_DEADLINE / per-config timeouts) below this script's outer timeout,
so children exit by themselves; the outer kill is a last resort against a
hang that is itself evidence the tunnel wedged again — in which case the
watcher returns to probing and resumes the plan from the failed step.

    python scripts/recover_watch.py [--probe-interval 780] [--budget-h 10]

Logs to --plan-dir (default /tmp/ot_plan); prints one status line per event.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def probe(timeout_s: float) -> bool:
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        return True
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        return False


def plan():
    """(name, argv, extra_env, outer_timeout_s) for each step, in order."""
    py = sys.executable
    harness = [py, "-m", "our_tree_tpu.harness.bench"]
    return [
        ("bench_headline", [py, os.path.join(REPO, "bench.py")],
         {"OT_BENCH_DEADLINE": "1100"}, 1400),
        ("bench_1gib", [py, os.path.join(REPO, "bench.py")],
         {"OT_BENCH_DEADLINE": "1100",
          "OT_BENCH_BYTES": str(1 << 30),
          "OT_BENCH_ENGINE": "pallas-gt"}, 1400),
        ("smoke", [py, os.path.join(REPO, "scripts", "smoke_tpu.py")],
         {}, 4 * 3600),
        ("tune", [py, os.path.join(REPO, "scripts", "tune_tpu.py"),
                  "--bytes", str(128 << 20), "--iters", "3",
                  "--tiles", "1024,2048", "--mc", "perm,roll",
                  "--sbox", "tower,bp", "--engines", "pallas,pallas-gt",
                  "--timeout", "700"],
         {}, 4 * 3600),
        ("profile", [py, os.path.join(REPO, "scripts", "profile_ctr.py")],
         {}, 1800),
        ("corpus", harness + ["--backend", "tpu", "--default-out"],
         {}, 2 * 3600),
    ]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe-interval", type=float, default=780.0,
                    help="seconds between probes while wedged (~13 min)")
    ap.add_argument("--probe-timeout", type=float, default=180.0)
    ap.add_argument("--budget-h", type=float, default=10.0,
                    help="give up after this many hours")
    ap.add_argument("--plan-dir", default="/tmp/ot_plan")
    ap.add_argument("--start-step", type=int, default=0,
                    help="resume the plan from this step index")
    args = ap.parse_args()

    os.makedirs(args.plan_dir, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    deadline = time.time() + args.budget_h * 3600
    steps = plan()
    idx = args.start_step

    while idx < len(steps) and time.time() < deadline:
        if not probe(args.probe_timeout):
            print(f"# wedged; next step={steps[idx][0]}; sleeping "
                  f"{args.probe_interval:.0f}s", flush=True)
            time.sleep(args.probe_interval)
            continue
        name, argv, env, outer = steps[idx]
        log = os.path.join(args.plan_dir, f"{name}.log")
        print(f"# tunnel live -> running {name} (log: {log})", flush=True)
        t0 = time.time()
        # Append: a step retried after a re-wedge must not truncate the
        # previous attempt's partial output — that log is the evidence of
        # what was running when the wedge hit.
        with open(log, "a") as fh:
            fh.write(f"## attempt at {time.strftime('%F %T')}\n")
            fh.flush()
            try:
                rc = subprocess.run(
                    argv, env=dict(os.environ, **env), cwd=REPO,
                    stdout=fh, stderr=subprocess.STDOUT,
                    timeout=min(outer, max(deadline - time.time(), 60)),
                ).returncode
            except subprocess.TimeoutExpired:
                rc = "timeout"
        print(f"# {name}: rc={rc} in {time.time() - t0:.0f}s", flush=True)
        if rc == "timeout":
            continue  # evidence of a re-wedge: back to probing, same step
        idx += 1  # non-zero rc is the step's own failure, not a wedge:
        #           its log has the story; the plan moves on
    done = idx >= len(steps)
    print(f"PLAN {'COMPLETE' if done else f'ABANDONED at step {idx}'}",
          flush=True)
    return 0 if done else 1


if __name__ == "__main__":
    sys.exit(main())
