#!/usr/bin/env python
"""Tunnel-recovery watcher: probe the device, then run the measurement plan.

The build host reaches its one TPU chip through a tunnel that wedges if a
jax process dies mid-device-op (PJRT client init then blocks indefinitely
for every later process, sometimes for hours, until the far side recovers).
Nothing local can unwedge it — so this watcher probes `jax.devices()` in a
throwaway subprocess on an interval and, the moment init succeeds, runs the
full queued hardware measurement plan:

  1. headline bench (probe-selected engine)
  2. 1 GiB BASELINE-metric bench
  3. ECB-decrypt bench (inverse circuit's only hardware number)
  4. Mosaic compile smoke, full kernel matrix      (scripts/smoke_tpu.py)
  5. tile x MC x S-box x engine tuning sweep       (scripts/tune_tpu.py)
  6. component profile                             (scripts/profile_ctr.py)
  7. measured VPU ceiling microbench               (scripts/vpu_ceiling.py)
  8. 2 GiB chunk-streamed CTR rehearsal            (harness.bench --stream-chunk-mb)
  9. results.<host>.tpu sweep corpus               (harness.bench --default-out)

Besides the per-step logs, every probe attempt and step outcome is appended
to the COMMITTED ledger docs/hwlogs/probes.log — a wedged round is then
verifiable from git history, not just claimed (VERDICT r3 missing #2).

Each step's full stdout+stderr (including the bench JSON lines) lands in
<plan-dir>/<step>.log; the corpus step additionally writes the repo's
results/results.<host>.tpu file itself.

Steps run strictly sequentially (one jax process at a time — the tunnel is
single-tenant; see utils/devlock.py). Every child gets an INTERNAL deadline
(OT_BENCH_DEADLINE / per-config timeouts) below this script's outer timeout,
so children exit by themselves; the outer kill is a last resort against a
hang that is itself evidence the tunnel wedged again — in which case the
watcher returns to probing and resumes the plan from the failed step.

    python scripts/recover_watch.py [--probe-interval 780] [--budget-h 10]

Logs to --plan-dir (default /tmp/ot_plan); prints one status line per event.
Completed steps are checkpointed through the shared sweep journal
(resilience.journal, ``--journal``; default ``<plan-dir>/plan.jsonl``):
a watcher restarted after a container death resumes at the first
unfinished step with no hand-carried ``--start-step`` index, and a
changed plan invalidates the record instead of replaying into the wrong
steps.
"""
from __future__ import annotations

import argparse
import os
import shutil
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
from _devlock_loader import load_devlock, load_resilience  # noqa: E402

repolicy = load_resilience("policy")
rejournal = load_resilience("journal")
reisolate = load_resilience("isolate")


class _Busy(Exception):
    """Another live job holds the devlock; poll again soon (the marker can
    clear any time, so the cadence is tighter than the wedge probe's)."""

    retry_delay_s = 60.0


class _Wedged(Exception):
    """The tunnel probe failed; wait a full probe interval (delay set per
    instance from --probe-interval)."""

    def __init__(self, msg: str, interval_s: float):
        super().__init__(msg)
        self.retry_delay_s = interval_s


class _ReWedged(Exception):
    """A plan step hit its outer timeout — evidence the tunnel wedged
    mid-step. Re-probe immediately (the probe itself gates the retry)."""

    retry_delay_s = 0.0


#: The probe must EXECUTE something, not just init: a half-recovered tunnel
#: passes PJRT client init and then blocks forever on the first transfer or
#: execute (observed round 2: init at 5 s, then 23 min of silence until the
#: outer kill). A tiny device_put + compute + readback classifies that state
#: as wedged, so the watcher keeps probing instead of launching a plan step
#: that can only burn its timeout.
#:
#: Tradeoff, accepted deliberately: on timeout the child is killed
#: mid-device-op — the very action the module docstring names as the wedge
#: trigger. On an already-wedged tunnel that changes nothing; the risk case
#: is a tunnel that is merely SLOW, which the generous default timeout
#: (240 s for an op that takes <30 s healthy, init included) is sized to
#: protect. An init-only probe has the same kill-mid-init exposure and
#: cannot detect the half-recovered state at all.
_PROBE_SRC = (
    "import sys, jax, jax.numpy as jnp;"
    "x = jax.device_put(jnp.arange(64, dtype=jnp.uint32));"
    # not an assert: PYTHONOPTIMIZE/-O would strip it, silently degrading
    # the probe to transfer-only
    "sys.exit(0 if int((x ^ jnp.uint32(7)).sum()) == 2016 else 1)"
)


def probe(timeout_s: float) -> tuple[bool, float]:
    """(alive, wall_seconds). Latency is evidence either way: a healthy
    probe completes <30 s; 'wedged at timeout' vs 'failed fast' (e.g. an
    import error) are different diagnoses and the ledger should tell.
    One deadline-guarded throwaway child through the shared runner —
    the group kill matters here too (a wedged PJRT init can hold a
    helper subprocess of its own); output is captured and dropped."""
    r = reisolate.run_child([sys.executable, "-c", _PROBE_SRC],
                            timeout_s, name="recover-probe")
    return r.ok, r.wall_s


#: The committed probe ledger (VERDICT r3 missing #2): every probe attempt,
#: step run, and watcher start/exit gets one line here, in the repo, so a
#: round spent wedged is verifiable from git history rather than prose.
#: Append-only by design — the file is the round's outage evidence.
LEDGER = os.path.join(REPO, "docs", "hwlogs", "probes.log")


def ledger(event: str, **kv) -> None:
    try:
        os.makedirs(os.path.dirname(LEDGER), exist_ok=True)
        line = time.strftime("%Y-%m-%dT%H:%M:%S%z") + f" {event}" + "".join(
            f" {k}={v}" for k, v in kv.items())
        with open(LEDGER, "a") as fh:
            fh.write(line + "\n")
    except OSError as e:  # never let evidence-keeping kill the watcher
        print(f"# ledger write failed: {e}", flush=True)


def plan():
    """(name, argv, extra_env, outer_timeout_s) for each step, in order."""
    py = sys.executable
    harness = [py, "-m", "our_tree_tpu.harness.bench"]
    return [
        ("bench_headline", [py, os.path.join(REPO, "bench.py")],
         {"OT_BENCH_DEADLINE": "1100"}, 1400),
        # Probe-selected engine (not pinned): the probe stage ranks the
        # registered engines — including the pallas-gt-bp S-box variant —
        # so the 1 GiB BASELINE metric lands on the measured winner.
        ("bench_1gib", [py, os.path.join(REPO, "bench.py")],
         {"OT_BENCH_DEADLINE": "1100",
          "OT_BENCH_BYTES": str(1 << 30)}, 1400),
        # The inverse circuit's throughput (VERDICT r2 #4): the same
        # chained methodology on ECB decrypt — CTR is symmetric, so this
        # is the only way the decrypt direction gets a hardware number.
        ("bench_ecbdec", [py, os.path.join(REPO, "bench.py")],
         {"OT_BENCH_DEADLINE": "1100", "OT_BENCH_OP": "ecb-dec"}, 1400),
        ("smoke", [py, os.path.join(REPO, "scripts", "smoke_tpu.py")],
         {}, 4 * 3600),
        ("tune", [py, os.path.join(REPO, "scripts", "tune_tpu.py"),
                  "--bytes", str(128 << 20), "--iters", "3",
                  "--tiles", "1024,2048", "--mc", "perm,roll",
                  "--sbox", "tower,bp",
                  "--engines", "pallas,pallas-gt,pallas-dense",
                  "--timeout", "700"],
         {}, 4 * 3600),
        ("profile", [py, os.path.join(REPO, "scripts", "profile_ctr.py")],
         {}, 1800),
        # Measured VPU ceiling (VERDICT r3 missing #6): pins docs/PERF.md's
        # roofline denominator with hardware u32-ops/s instead of the
        # 2-4 T-ops/s estimate.
        ("vpu_ceiling", [py, os.path.join(REPO, "scripts", "vpu_ceiling.py")],
         {}, 1800),
        # The 16 GiB workload SHAPE (BASELINE config 5) at reduced scale:
        # a 2 GiB message chunk-streamed through the chip in 256 MiB
        # pieces, 128-bit counter carried across seams — the production
        # streaming path (backends.ctr_stream) on real hardware. Rows are
        # e2e-timed by construction (staging is inherent to streaming).
        ("stream_2gib", harness + ["--backend", "tpu", "--modes", "ctr",
                                   "--sizes-mb", "2048",
                                   "--stream-chunk-mb", "256",
                                   "--workers", "1", "--iters", "3"],
         {}, 3600),
        ("corpus", harness + ["--backend", "tpu", "--default-out"],
         {}, 2 * 3600),
    ]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe-interval", type=float, default=780.0,
                    help="seconds between probes while wedged (~13 min)")
    ap.add_argument("--probe-timeout", type=float, default=240.0)
    ap.add_argument("--budget-h", type=float, default=10.0,
                    help="give up after this many hours")
    ap.add_argument("--plan-dir", default="/tmp/ot_plan")
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="plan-step journal (resilience.journal JSONL; "
                         "default <plan-dir>/plan.jsonl): each completed "
                         "step appends as it finishes, and a restarted "
                         "watcher with the SAME plan resumes at the first "
                         "unfinished step — the hand-rolled --start-step "
                         "bookkeeping, journaled. A changed plan "
                         "invalidates the journal")
    ap.add_argument("--start-step", type=int, default=0,
                    help="manual override: resume the plan from this step "
                         "index, regardless of the journal (escape hatch; "
                         "the journal resume needs no index)")
    args = ap.parse_args()

    os.makedirs(args.plan_dir, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    deadline = time.monotonic() + args.budget_h * 3600
    steps = plan()
    # The plan's identity, for the journal's config hash: step names,
    # argv (minus the interpreter path — it is host detail, not plan
    # shape), env overlays, and outer timeouts. Any edit to the plan
    # invalidates recorded progress — replaying "step 3 done" into a
    # different step 3 is exactly the wrong-slot replay the journal's
    # hash exists to prevent.
    journal = rejournal.SweepJournal(
        args.journal or os.path.join(args.plan_dir, "plan.jsonl"),
        {"plan": [[name, argv[1:], env, outer]
                  for name, argv, env, outer in steps]})
    idx = args.start_step
    ledger("watcher_start", interval_s=f"{args.probe_interval:.0f}",
           probe_timeout_s=f"{args.probe_timeout:.0f}",
           budget_h=args.budget_h, start_step=idx,
           journaled_steps=journal.pending, pid=os.getpid())

    devlock = load_devlock()
    #: Children are re-pointed at a plan-local marker so they serialize
    #: among themselves (trivially — the plan is sequential) instead of
    #: waiting out their budget on the watcher's own marker.
    child_busy = devlock.path() + ".plan"

    def attempt_step(step):
        """ONE attempt at one plan step, under the devlock.

        Single-tenant tunnel: the marker is held across probe AND step,
        closing the check-then-act window where a concurrent job (driver
        bench, manual sweep) could start device work between our devlock
        check and the probe's own device op — two overlapping jax
        processes are the documented wedge trigger. acquire() fails while
        another live job holds the marker; then _Busy's short poll takes
        over. Stale markers (dead holders, recycled PIDs) are reclaimed
        inside acquire(). Raises _Busy/_Wedged/_ReWedged for the retry
        policy — whose sleeps happen AFTER this function returns, i.e.
        after the marker is released, so a waiting job can take the
        device during them. Returns the step's own exit code otherwise.
        """
        name, argv, env, outer = step
        with devlock.hold() as owned:  # refresher keeps mtime < STALE_S
            if not owned:
                ledger("busy", next_step=name)
                print("# device busy (devlock held); sleeping 60s",
                      flush=True)
                raise _Busy(name)
            alive, lat = probe(args.probe_timeout)
            ledger("probe", outcome="live" if alive else "wedged",
                   latency_s=f"{lat:.1f}", next_step=name)
            if not alive:
                print(f"# wedged (probe {lat:.0f}s); next step={name}; "
                      f"sleeping {args.probe_interval:.0f}s", flush=True)
                raise _Wedged(name, args.probe_interval)
            log = os.path.join(args.plan_dir, f"{name}.log")
            print(f"# tunnel live -> running {name} (log: {log})",
                  flush=True)
            t0 = time.monotonic()
            # Append: a step retried after a re-wedge must not truncate
            # the previous attempt's partial output — that log is the
            # evidence of what was running when the wedge hit.
            with open(log, "a") as fh:
                fh.write(f"## attempt at {time.strftime('%F %T')}\n")
                fh.flush()
                # The streaming runner owns the session/group-kill
                # semantics: several steps (smoke, tune, corpus) are
                # parents of their own jax subprocesses, and killing
                # only the parent would orphan a grandchild that keeps
                # driving the device while we probe — the documented
                # two-process wedge trigger. The log file is the sink,
                # so a re-wedged step's partial tail is preserved.
                r = reisolate.run_streamed(
                    argv,
                    min(outer, max(deadline - time.monotonic(), 60)),
                    env=dict(os.environ,
                             OT_BENCH_BUSY_FILE=child_busy, **env),
                    cwd=REPO, sink=fh, name=name)
                rc = "timeout" if r.kind == "timeout" else r.rc
            print(f"# {name}: rc={rc} in {time.monotonic() - t0:.0f}s",
                  flush=True)
            ledger("step", name=name, rc=rc,
                   wall_s=f"{time.monotonic() - t0:.0f}")
            # Mirror the step log into the repo: the plan-dir lives in
            # /tmp and dies with the container, while the repo is the
            # only thing that survives a round boundary — an
            # end-of-round sweep of uncommitted files then preserves
            # the measurement evidence even if nobody is around to
            # commit it by hand.
            try:
                dst = os.path.join(REPO, "docs", "hwlogs")
                os.makedirs(dst, exist_ok=True)
                shutil.copyfile(log, os.path.join(dst, f"{name}.log"))
            except OSError as e:
                print(f"# log mirror failed: {e}", flush=True)
            if rc == "timeout":
                # Evidence of a re-wedge: back to probing, same step.
                raise _ReWedged(name)
            return rc  # non-zero rc is the step's own failure, not a
            #            wedge: its log has the story; the plan moves on

    abandon = object()
    while idx < len(steps) and time.monotonic() < deadline:
        step = steps[idx]
        # Journal resume: a step completed by a previous watcher run (the
        # container died, the watcher was restarted) is skipped here —
        # what --start-step used to do by hand, now read from the
        # journal. The manual index still wins when given: steps it
        # jumps over are simply not recorded, and the journal's own
        # order check distrusts any tail that stops matching.
        if journal.is_completed(step[0]):
            # skip() can still return None: a manual --start-step that
            # jumped over recorded steps breaks replay order, and the
            # journal distrusts (and truncates) the tail rather than
            # replaying into the wrong slots. Fall through and run the
            # step — re-running is the safe direction.
            entry = journal.skip(step[0])
            if entry is not None:
                ledger("step_resumed", name=step[0],
                       recorded=";".join(entry.get("lines", [])))
                print(f"# {step[0]}: completed in a previous run "
                      f"(journal); skipping", flush=True)
                idx += 1
                continue
        # The probe-until-live loop is the shared retry primitive
        # (resilience.policy): unbounded attempts, per-outcome delays
        # (the exceptions carry their own retry_delay_s), total budget =
        # whatever is left of --budget-h. Exhausting the budget while
        # still busy/wedged abandons the plan at this step, exactly the
        # old loop's semantics.
        rc = repolicy.RetryPolicy(
            attempts=None,
            budget_s=max(deadline - time.monotonic(), 0.0),
            retry_on=(_Busy, _Wedged, _ReWedged),
            on_exhausted=lambda last: abandon,
            name=f"recover-watch:{step[0]}",
        ).run(lambda a: attempt_step(step))
        if rc is abandon:
            break
        # A non-timeout return — success OR the step's own failure — is
        # this plan's definition of "done with the step" (the old loop
        # moved on either way; the log has the story). Record it so a
        # restarted watcher does not re-run a 4 h sweep that already
        # finished.
        journal.record(step[0], [f"rc={rc}"])
        idx += 1
    done = idx >= len(steps)
    journal.close()
    ledger("watcher_exit", done=done, next_step_idx=idx)
    print(f"PLAN {'COMPLETE' if done else f'ABANDONED at step {idx}'}",
          flush=True)
    return 0 if done else 1


if __name__ == "__main__":
    sys.exit(main())
