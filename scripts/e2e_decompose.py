#!/usr/bin/env python
"""Decompose the e2e corpus time into its stages (VERDICT r4 #5).

The committed e2e rows (results/results.vm.tpu) read 0.028–0.033 GB/s at
1 GiB — 16x BELOW the reference's -O0 CPU baseline — while the device
kernel runs at ~35 GB/s. This script times each stage of one e2e pass
separately so the corpus footnote can say exactly where those seconds go:

  pack      host bytes -> u32 LE words (pure numpy view/copy)
  h2d       jax.device_put + block_until_ready (tunnel upload)
  kernel    chained-difference CTR pass (the compute)
  d2h       np.asarray(out) full readback (tunnel download)
  unpack    u32 words -> host bytes

Each size runs in its own subprocess (axon worker crashes must not kill
the ladder), one JSON line per size. The tunnel-transport stages dominate
on this host by construction: the TPU is reached through an RPC tunnel at
~15–30 MB/s effective staging bandwidth (axon-tpu-pitfalls rule 4). On a
co-located host (PCIe/DMA, tens of GB/s), h2d/d2h shrink by ~3 orders of
magnitude and e2e approaches the kernel rate — the expectation the corpus
footnote states.

    python scripts/e2e_decompose.py                # 256 MiB + 1 GiB
    python scripts/e2e_decompose.py --sizes 64     # MiB subset
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def child(mib: float) -> int:
    import numpy as np
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from our_tree_tpu.harness.backends import TpuBackend
    from our_tree_tpu.resilience import watchdog
    from our_tree_tpu.utils import packing

    assert jax.devices()[0].platform != "cpu", "need the real chip"
    backend = TpuBackend("auto")  # applies stored knobs, resolves engine

    nbytes = int(mib * (1 << 20))
    ctx = backend.make_key(bytes(range(16)))
    host = np.random.default_rng(1337).integers(0, 256, nbytes, dtype=np.uint8)
    nonce = np.frombuffer(
        bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff"), np.uint8)
    ctr_be = backend.ctr_be_words(nonce)

    def t(fn):
        t0 = time.perf_counter()
        out = fn()
        return time.perf_counter() - t0, out

    r = {"mib": mib, "engine": backend.engine}

    # pack: best of 2 (first call may fault pages)
    _, words_np = t(lambda: packing.np_bytes_to_words(host))
    pack_s, words_np = t(lambda: packing.np_bytes_to_words(host))
    r["pack_s"] = round(pack_s, 4)

    # h2d (the tunnel upload; barrier = the backend's completion
    # readback) — watchdog-guarded raw staging, armed via
    # OT_DISPATCH_DEADLINE like every dispatch seam.
    with watchdog.deadline(watchdog.default_deadline_s(), what="e2e h2d"):
        h2d_s, words = t(lambda: backend.block_until_ready(
            jax.device_put(jnp.asarray(words_np))))
    r["h2d_s"] = round(h2d_s, 3)

    # kernel: the harness's own chained-difference helper (no third copy
    # of the methodology — backends.py:chained_device_times_us)
    crypt = lambda w, acc: backend.ctr(ctx, w, ctr_be ^ acc, 1)
    us = sorted(backend.chained_device_times_us(crypt, words, 3, 4))
    kernel_s = us[1] / 1e6  # median of 3
    r["kernel_s"] = round(kernel_s, 4)

    # One per-call sync'd pass isolates the fixed transport dispatch+sync
    # cost as (call time - kernel time); also yields the ciphertext for
    # the d2h stage.
    out_dev = backend.block_until_ready(backend.ctr(ctx, words, ctr_be, 1))
    call_s, out_dev = t(lambda: backend.block_until_ready(
        backend.ctr(ctx, words, ctr_be, 1)))
    r["dispatch_sync_s"] = round(max(call_s - kernel_s, 0.0), 3)

    # d2h: full ciphertext readback (what an e2e pass pays)
    d2h_s, out_np = t(lambda: np.asarray(out_dev))
    r["d2h_s"] = round(d2h_s, 3)

    unpack_s, _ = t(lambda: packing.np_words_to_bytes(
        out_np.reshape(-1, 4)))
    r["unpack_s"] = round(unpack_s, 4)

    # A real e2e pass pays the fixed dispatch+sync round trip too — leaving
    # it out would make the stage sum systematically undershoot the corpus
    # e2e rows this decomposition exists to reconcile with.
    total = pack_s + h2d_s + kernel_s + r["dispatch_sync_s"] + d2h_s + unpack_s
    r["e2e_sum_s"] = round(total, 3)
    r["e2e_gbps"] = round(nbytes / total / 1e9, 4)
    r["kernel_gbps"] = round(nbytes / kernel_s / 1e9, 2)
    r["h2d_mbps"] = round(nbytes / h2d_s / 1e6, 1)
    r["d2h_mbps"] = round(nbytes / d2h_s / 1e6, 1)
    print(json.dumps(r), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="256,1024")
    ap.add_argument("--timeout", type=float, default=900)
    ap.add_argument("--child-mib", type=float, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.child_mib is not None:
        return child(args.child_mib)

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _devlock_loader import load_devlock, load_resilience

    sizes = [float(s) for s in args.sizes.split(",")]
    devlock = load_devlock()
    # Shared deadline-guarded child runner (resilience/isolate.py) — see
    # run_child: timeout, process-group SIGKILL, outcome classification.
    reisolate = load_resilience("isolate")
    rc_all = 0
    with devlock.hold(wait_budget_s=600.0):
        for mib in sizes:
            print(f"## e2e decompose {mib:g} MiB", flush=True)
            r = reisolate.run_child(
                [sys.executable, os.path.abspath(__file__),
                 "--child-mib", str(mib)],
                args.timeout, name=f"e2e-decompose:{mib:g}MiB")
            sys.stdout.write(r.out)
            if r.kind == "timeout":
                rc_all = 1
                print(json.dumps({"mib": mib, "ok": False,
                                  "rc": "timeout"}), flush=True)
            elif r.kind == "crash":
                rc_all = 1
                tail = r.err.strip().splitlines()[-10:]
                print(json.dumps({"mib": mib, "ok": False, "rc": r.rc,
                                  "stderr_tail": tail}), flush=True)
    return rc_all


if __name__ == "__main__":
    sys.exit(main())
