#!/usr/bin/env python
"""The honest sequential baseline: ARC4 keystream generation ON DEVICE.

The framework routes ARC4's sequential keygen phase to the native C core
by design (the phase split exists so serial work runs on the best serial
processor — harness/backends.py:arc4_setup_prep); the on-device lax.scan
path exists for parity and for hosts without a C toolchain. VERDICT r4 #6
asks what that scan actually costs on the chip — the reference published
its own sequential baseline (RC4 keygen 0.037 GB/s, results.myth.1:38),
so this framework publishes its device scan rate too, however bad.

Measures, on the real chip: the single-stream device scan at --sizes-kb,
warmed (compile excluded), per-call sync timing (passes are seconds, the
~0.1 s transport round trip is noise); the native C keygen on the same
host for contrast. Prints one JSON line per measurement plus a derived
s/GiB extrapolation for the device scan.

    python scripts/arc4_device_keygen.py          # 64 KiB + 1 MiB
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _devlock_loader import load_devlock  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-kb", default="64,1024")
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

    import numpy as np
    import jax

    from our_tree_tpu.models.arc4 import ARC4, keystream_scan
    from our_tree_tpu.resilience import watchdog

    assert jax.devices()[0].platform != "cpu", "need the real chip"
    key = bytes(range(1, 17))
    devlock = load_devlock()
    with devlock.hold(wait_budget_s=900.0):
        for kb in [int(s) for s in args.sizes_kb.split(",") if s]:
            n = kb << 10
            import jax.numpy as jnp

            rc = ARC4(key)  # host KSA; the scan times pure PRGA
            state = (jnp.uint32(rc.x), jnp.uint32(rc.y),
                     jnp.asarray(rc.m, jnp.uint32))
            run = lambda st: keystream_scan(st, n)[1]

            def barrier(x):
                # Scalar readback = the real completion barrier on the
                # tunnelled transport (backends.py:block_until_ready:
                # jax.block_until_ready alone can return early there).
                # Watchdog-guarded (armed via OT_DISPATCH_DEADLINE).
                with watchdog.deadline(watchdog.default_deadline_s(),
                                       what="arc4 keystream barrier"):
                    jax.block_until_ready(x)
                    np.asarray(x.ravel()[-1:])
                return x

            ref = np.asarray(barrier(run(state)))  # compile
            # Parity against the host path before trusting the timing.
            assert np.array_equal(ref, ARC4(key).prep(n)), "device != host"
            times = []
            for _ in range(args.iters):
                t0 = time.perf_counter()
                barrier(run(state))
                times.append(time.perf_counter() - t0)
            best = min(times)
            print(json.dumps({
                "what": "arc4-keygen-device-scan", "bytes": n,
                "best_s": round(best, 3),
                "mb_per_s": round(n / best / 1e6, 4),
                "s_per_gib_extrapolated": round(best * (1 << 30) / n, 1),
            }), flush=True)

        # Native C keygen on the same host, same sizes, for the contrast
        # line (this is what production arc4_setup_prep actually runs).
        try:
            from our_tree_tpu.runtime import native

            native.load()
            for kb in [int(s) for s in args.sizes_kb.split(",") if s]:
                n = kb << 10
                nat = native.NativeARC4(key)
                t0 = time.perf_counter()
                ks = nat.prep(n)
                dt = time.perf_counter() - t0
                assert np.array_equal(np.asarray(ks), ARC4(key).prep(n))
                print(json.dumps({
                    "what": "arc4-keygen-native-c", "bytes": n,
                    "best_s": round(dt, 5),
                    "mb_per_s": round(n / dt / 1e6, 1),
                }), flush=True)
        except Exception as e:  # no C toolchain: the device row stands alone
            print(json.dumps({"what": "arc4-keygen-native-c",
                              "unavailable": type(e).__name__}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
