#!/usr/bin/env python
"""Stream-axis scaling of the batch sequence-parallel modes (PARITY's
"what cannot parallelise within a stream scales across streams").

Fixes the total byte count and sweeps the stream count: each doubling
halves the per-stream serial scan length while filling more VPU lanes, so
total GB/s should rise until the lane axis saturates. Measured for both
batch surfaces — cbc-batch (AES recurrence per stream) and rc4-batch
(per-byte PRGA per stream) — on the live chip, per-call sync timing
(passes are long; the ~0.1 s transport round trip is noise).

    python scripts/batch_streams_scaling.py            # 16 MiB total
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _devlock_loader import load_devlock  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--total-mb", type=float, default=16)
    ap.add_argument("--streams", default="32,128,512,2048,8192")
    ap.add_argument("--iters", type=int, default=2)
    args = ap.parse_args()
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

    import numpy as np
    import jax

    from our_tree_tpu.harness.backends import TpuBackend

    assert jax.devices()[0].platform != "cpu", "need the real chip"
    backend = TpuBackend("auto")
    total = int(args.total_mb * (1 << 20))
    rng = np.random.default_rng(1337)

    def timed_best(fn):
        # backend.block_until_ready, NOT jax.block_until_ready: on the
        # tunnelled transport the latter can return before the work is
        # done (backends.py:block_until_ready docstring) — timing around
        # it would under-report exactly like the jitter class PERF.md
        # ledger #13 documents.
        backend.block_until_ready(fn())  # compile + warm
        best = None
        for _ in range(args.iters):
            t0 = time.perf_counter()
            backend.block_until_ready(fn())
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    devlock = load_devlock()
    with devlock.hold(wait_budget_s=900.0):
        for streams in [int(s) for s in args.streams.split(",") if s]:
            per = (total // streams) // 16 * 16
            if per < 16:
                continue
            used = per * streams
            # cbc-batch: S independent CBC-encrypt scans.
            msg = rng.integers(0, 256, (streams, per), dtype=np.uint8)
            ctx = backend.make_key(bytes(range(16)))
            words = backend.stage_batch_words(msg)
            ivw = backend.stage_batch_words(
                rng.integers(0, 256, (streams, 16), dtype=np.uint8))
            best = timed_best(lambda: backend.cbc_batch(ctx, words, ivw, 1))
            print(json.dumps({
                "what": "cbc-batch", "streams": streams, "bytes": used,
                "best_s": round(best, 3),
                "mb_per_s": round(used / best / 1e6, 2)}), flush=True)
            # rc4-batch: S independent PRGA scans (keystream stays on
            # device, no staging by construction).
            keys = [rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
                    for _ in range(streams)]
            states = backend.arc4_batch_states(keys)
            ks_len = total // streams
            best = timed_best(
                lambda: backend.arc4_prep_batch(states, ks_len, 1))
            print(json.dumps({
                "what": "rc4-batch", "streams": streams,
                "bytes": ks_len * streams, "best_s": round(best, 3),
                "mb_per_s": round(ks_len * streams / best / 1e6, 2)}),
                flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
