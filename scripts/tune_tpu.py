#!/usr/bin/env python
"""On-hardware tuning sweep for the Pallas AES engines.

Sweeps OT_PALLAS_TILE x OT_PALLAS_MC x S-box form on the live chip and
prints a GB/s table for the north-star CTR path, using bench.py's chained
timing (fori_loop chain + digest readback — the only honest method on
async/tunnelled platforms). Each configuration runs in a SUBPROCESS because
tile/MC/S-box are import-time constants; run this alone (one jax process at
a time on tunnelled hosts).

Usage: python scripts/tune_tpu.py [--bytes BYTES] [--iters K]
Writes the winning env to stdout; docs/TUNING.md documents the knobs.
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _devlock_loader import load_devlock, load_ranking, load_resilience  # noqa: E402

reisolate = load_resilience("isolate")

CHILD = r"""
import json, os, sys, time
import numpy as np
import jax, jax.numpy as jnp
sys.path.insert(0, %(repo)r)
from our_tree_tpu.models import aes as aes_mod
from our_tree_tpu.models.aes import AES
from our_tree_tpu.utils import packing

nbytes, iters, engine = %(nbytes)d, %(iters)d, %(engine)r
a = AES(bytes(range(16)))
host = np.random.default_rng(1337).integers(0, 256, nbytes, dtype=np.uint8)
# Flat u32 boundary staging, matching bench.py's default (a (N, 4)
# boundary array pads its minor dim to the 128-lane tile on TPU).
words = jax.device_put(jnp.asarray(packing.np_bytes_to_words(host)))
nonce = np.frombuffer(bytes(range(16)), np.uint8)
ctr_be = jax.device_put(jnp.asarray(packing.np_bytes_to_words(nonce).byteswap()))
ctr_fn = aes_mod.ctr_crypt_fn(a.nr, engine=engine)

@jax.jit
def chained(words, ctr_be, rk, k):
    def body(_, acc):
        out = ctr_fn(words, ctr_be ^ acc, rk)
        return jnp.sum(out, dtype=jnp.uint32)
    return jax.lax.fori_loop(jnp.uint32(0), k, body, jnp.uint32(0))

def run(k):
    t0 = time.perf_counter()
    d = int(chained(words, ctr_be, a.rk_enc, jnp.uint32(k)))
    return time.perf_counter() - t0, d

run(1)
t1 = min(run(1)[0] for _ in range(2))
(tk, dig) = min((run(1 + iters) for _ in range(2)), key=lambda r: r[0])
gbps = iters * nbytes / max(tk - t1, 1e-9) / 1e9
from our_tree_tpu.utils import ranking as _rk
_d = jax.devices()[0]
print(json.dumps({"gbps": round(gbps, 3), "digest": dig,
                  "platform": _rk.device_key(
                      _d.platform, getattr(_d, "device_kind", None))}))
"""


#: Default env knobs of the registered engines (OT_PALLAS_TILE /
#: OT_PALLAS_MC / OT_BITSLICE_UNROLL defaults in ops/pallas_aes.py and
#: ops/bitslice.py — mirrored here because this parent stays jax-free).
_DEFAULT_TILE, _DEFAULT_MC, _DEFAULT_UNROLL = 1024, "perm", "1"
#: sbox=bp under a non-bp engine IS the registered -bp engine.
_BP_ALIAS = {"pallas-gt": "pallas-gt-bp", "pallas-dense": "pallas-dense-bp"}


def _rankable_engine_name(engine, tile, mc, sbox, unroll,
                          ref_tile, ref_mc):
    """The registered engine name a sweep config's GB/s may be attributed
    to in the persisted ranking — or None.

    The ranking must only hold numbers the production path can REPRODUCE
    (else it steers engine selection by unreproducible measurements), and
    all rows of one ranking must share a knob setting (mixing settings
    would compare apples to oranges on merge). Since knob persistence
    landed (round 4) the reproducible setting is (ref_tile, ref_mc) — the
    knobs this sweep persists, which bench.py / TpuBackend /
    resolve_engine("auto") all re-apply via apply_stored_knobs; when no
    knobs are persisted the caller passes the defaults, restoring the old
    behavior. Engines that IGNORE the Pallas knobs (bitslice/jnp) are
    attributable from any (tile, mc) row — those rows measure identical
    code. unroll must stay default for everyone: only bitslice reads it,
    and nothing re-applies it. sbox is the one knob that maps onto a
    distinct registered engine (the -bp variants), so those rows are
    attributed there instead of dropped.
    """
    if unroll != _DEFAULT_UNROLL:
        return None
    if engine.startswith("pallas") and (tile, mc) != (ref_tile, ref_mc):
        return None
    if sbox == "tower":
        return engine
    if sbox == "bp":
        return _BP_ALIAS.get(engine)
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bytes", type=int, default=128 << 20)
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--tiles", default="512,1024,2048")
    ap.add_argument("--mc", default="perm,roll")
    ap.add_argument("--sbox", default="tower")
    ap.add_argument("--engines", default="pallas,pallas-gt")
    ap.add_argument("--unroll", default="1",
                    help="OT_BITSLICE_UNROLL values (XLA scan path; only "
                         "meaningful with --engines bitslice)")
    args = ap.parse_args()

    # Tile/MC/S-box are baked into each child's HLO, so configs don't share
    # executables within one sweep — the persistent cache pays off on
    # REPEATED sweep invocations with overlapping configs (retries after a
    # tunnel hiccup being the expected case). Harmless if the platform's
    # cache path is unsupported — jax degrades to a warning.
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

    grid = [
        cfg for cfg in itertools.product(
            [int(t) for t in args.tiles.split(",")],
            args.mc.split(","),
            args.sbox.split(","),
            args.engines.split(","),
            [str(int(u)) for u in args.unroll.split(",")],
        )
        # Only the bitslice engine reads OT_BITSLICE_UNROLL (the Pallas
        # engines keep all rounds in VMEM); crossing other engines with
        # unroll values would just re-measure identical configs under
        # mislabeled tags.
        if cfg[3] == "bitslice" or cfg[4] == "1"
    ]
    # Single-tenant device coordination: wait for any prior measurement
    # job, then hold the marker for the sweep (bench.py waits on the same
    # lock — a concurrent jax process wedges a tunnelled device). The
    # watcher orchestrator holds its own marker around whole plans; this
    # acquire simply fails then (advisory), which is fine — the plan is
    # already serialized. devlock is file-loaded so this jax-free parent
    # stays jax-free (the package import would pull jax in).
    devlock = load_devlock()

    results = []
    digests = set()
    platforms = set()
    with devlock.hold(wait_budget_s=900.0,
                      on_wait=lambda p: print(f"# waiting for {p}",
                                              file=sys.stderr)):
        for tile, mc, sbox, engine, unroll in grid:
            env = dict(os.environ, OT_PALLAS_TILE=str(tile), OT_PALLAS_MC=mc,
                       OT_SBOX=sbox, OT_BITSLICE_UNROLL=unroll)
            code = CHILD % {"repo": REPO, "nbytes": args.bytes,
                            "iters": args.iters, "engine": engine}
            tag = (f"tile={tile:<5} mc={mc:<4} sbox={sbox:<5} "
                   f"engine={engine}"
                   + (f" unroll={unroll}" if unroll != "1" else ""))
            # The shared deadline-guarded child runner (resilience/
            # isolate.py): one place owns the timeout, the process-GROUP
            # SIGKILL (a hung config must not leave a grandchild driving
            # the device), and the outcome classification the three
            # sweep scripts used to hand-roll separately.
            r = reisolate.run_child([sys.executable, "-u", "-c", code],
                                    args.timeout, env=env,
                                    name=f"tune:{engine}")
            if r.kind == "timeout":
                print(f"{tag}  ->  TIMEOUT", flush=True)
            elif r.kind == "crash":
                msg = r.err.strip().splitlines()
                print(f"{tag}  ->  FAILED ({msg[-1] if msg else 'no stderr'})",
                      flush=True)
            else:
                rr = json.loads(r.out.strip().splitlines()[-1])
                results.append((rr["gbps"], tag, tile, mc, engine, sbox,
                                unroll))
                digests.add(rr["digest"])
                platforms.add(rr.get("platform", "unknown"))
                print(f"{tag}  ->  {rr['gbps']:7.3f} GB/s  "
                      f"digest={rr['digest']:#010x}", flush=True)
    if len(digests) > 1:
        print("WARNING: digests disagree across configs — a config computed "
              "different ciphertext; do not trust this sweep", file=sys.stderr)
        return 1
    if results:
        best = max(results)
        print(f"\nBEST: {best[1]}  {best[0]:.3f} GB/s")
        # Persist the measurements — but only when every config agreed on
        # the platform: a sweep that straddled a mid-run CPU demotion would
        # otherwise record cross-platform numbers as one ranking.
        if len(platforms) == 1:
            platform = platforms.pop()
            ranking = load_ranking()
            # The winning tile/MC come from Pallas-engine rows only
            # (bitslice/jnp ignore OT_PALLAS_*, so a bitslice row winning
            # overall must not persist a tile it never exercised), and only
            # when at least two distinct (tile, MC) settings were actually
            # compared there — a single-setting sweep proves nothing about
            # the grid. These knobs are what later runs re-apply
            # (pallas_aes.apply_stored_knobs), so the engine ranking below
            # is attributed from rows at the SAME setting: ranking and
            # knobs persist as one consistent, reproducible pair.
            pallas_rows = [r for r in results if r[4].startswith("pallas")]
            persist_knobs = (
                pallas_rows
                and len({(t, m) for _, _, t, m, _, _, _ in pallas_rows}) >= 2)
            if persist_knobs:
                _, _, ref_tile, ref_mc, _, _, _ = max(pallas_rows)
            else:
                # No knob comparison in this sweep: attribute at the
                # setting production will actually APPLY — the stored
                # knobs when they exist (a focused re-tune at the tuned
                # setting then updates the ranking consistently), else
                # the defaults.
                stored_kn = ranking.knobs(platform)
                ref_tile = stored_kn.get("tile", _DEFAULT_TILE)
                ref_mc = stored_kn.get("mc", _DEFAULT_MC)
            best_by_engine = {}
            for gbps, _, tile, mc, engine, sbox, unroll in results:
                name = _rankable_engine_name(engine, tile, mc, sbox, unroll,
                                             ref_tile, ref_mc)
                if name is not None:
                    best_by_engine[name] = max(
                        best_by_engine.get(name, 0.0), gbps)
            # When the sweep's winning knobs DIFFER from what was stored,
            # previously-ranked Pallas rows not re-measured in this sweep
            # were measured under the old setting — store()'s merge would
            # otherwise carry them into a ranking whose knobs record says
            # something else (apples vs oranges). Drop them; engines that
            # ignore the knobs keep their rows.
            new_knobs = {"tile": ref_tile, "mc": ref_mc}
            # Carry a persisted per-size tile map through: store_knobs
            # REPLACES the knob record, and this flat sweep measured
            # nothing about the per-size buckets (tune_tile_sizes.py owns
            # that record; it carries tile/mc through symmetrically).
            prev_by_mib = ranking.knobs(platform).get("tile_by_mib")
            if prev_by_mib:
                new_knobs["tile_by_mib"] = prev_by_mib
            # "Changed" is measured against the setting prior rows were
            # ACTUALLY measured under — stored knobs when present, else
            # the defaults. A never-stored file whose rows were measured
            # at the defaults must not count as changed when the winner IS
            # the defaults (that would drop every valid row a fresh host's
            # bench probe just ranked).
            prev_kn = ranking.knobs(platform)
            prev_setting = {"tile": prev_kn.get("tile", _DEFAULT_TILE),
                            "mc": prev_kn.get("mc", _DEFAULT_MC)}
            # Compare the flat setting only: the carried-through per-size
            # map is not part of what this sweep measured or changed.
            knobs_changed = persist_knobs and prev_setting != {
                "tile": new_knobs["tile"], "mc": new_knobs["mc"]}
            drop = [e for e in (ranking.order(platform) or [])
                    if e.startswith("pallas") and e not in best_by_engine
                    ] if knobs_changed else []
            stored = ranking.store(platform, best_by_engine, "tune-sweep",
                                   args.bytes, drop=drop)
            if stored:
                print(f"# ranking persisted to {ranking.path()} "
                      f"(rows at tile={ref_tile} mc={ref_mc}"
                      + (f"; dropped stale {drop}" if drop else "") + ")")
            # Knobs persist only beside a successful ranking write: the two
            # records are applied as a pair (apply_stored_knobs + "auto"
            # selection), so a knob update without its matching ranking —
            # e.g. a single-engine sweep, where store() refuses a one-row
            # "ranking" — would re-apply new knobs while selection still
            # runs on old-knob numbers.
            if persist_knobs and stored and ranking.store_knobs(
                    platform, new_knobs, "tune-sweep", args.bytes):
                print(f"# tuned knobs persisted: tile={ref_tile} "
                      f"mc={ref_mc}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
