#!/usr/bin/env python
"""Randomized bit-parity fuzz against the compiled reference oracle.

The fixed suite pins known-answer vectors and hand-picked seams; this
fuzzer drives the SAME parity contract through randomized configurations —
key sizes, modes, directions, odd lengths, random chunk splits exercising
every resume-state seam (CBC's chained IV, CFB128's iv_off register,
CTR's nc_off/counter/stream_block), and random nonces including
near-wraparound — and bit-compares outputs AND final resume states against
the reference C oracle (scripts/gen_golden.py). The reference repo
benchmarked without ever checking outputs (SURVEY.md §4 "output
correctness is never checked"); this is the opposite discipline.

    python scripts/fuzz_parity.py --iters 200 --seed 7

Exit code 0 = every case bit-exact. On failure, prints the reproducing
config (seed/case index) and exits 1. CPU-pinned by default (the oracle
is host C; engines under test default to jnp for speed — use --engines
to fuzz bitslice/pallas too). Pass --device to keep the platform
unpinned and fuzz the pallas engines through REAL Mosaic kernels on a
TPU host; without it they run in interpreter mode.
"""
from __future__ import annotations

import argparse
import os
import pathlib
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--seed", type=int, default=1337)
    ap.add_argument("--max-bytes", type=int, default=1 << 16)
    ap.add_argument("--engines", default="jnp",
                    help="comma list; cipher engines to fuzz per case")
    ap.add_argument("--reference", default="/root/reference",
                    help="reference checkout to compile the oracle from")
    ap.add_argument("--deadline", type=float, default=0,
                    help="stop cleanly after this many seconds (0 = none)")
    def _positive_int(v):
        n = int(v)
        if n < 1:
            raise argparse.ArgumentTypeError(
                "--clear-every must be >= 1 (clearing is the fuzzer's "
                "memory bound; there is no 'never' setting)")
        return n

    ap.add_argument("--clear-every", type=_positive_int, default=25,
                    help="clear XLA compile caches every N cases; lower it "
                         "when fuzzing the pallas engines (their interpret-"
                         "mode compilations are much larger per case — a "
                         "3-engine run at the default interval was observed "
                         "dying on LLVM 'Cannot allocate memory')")
    ap.add_argument("--native", action="store_true",
                    help="also fuzz the native C runtime (runtime/csrc) "
                         "against the oracle each case — bulk calls plus "
                         "every resume surface the C API exposes (CBC "
                         "chained IV, CFB128 iv_off; CTR is bulk-only "
                         "there, compared one-shot with its counter)")
    ap.add_argument("--device", action="store_true",
                    help="do NOT pin the platform to CPU: fuzz pallas "
                         "engines through real Mosaic kernels on a TPU "
                         "host (single-tenant tunnels: coordinate via the "
                         "devlock; do not run beside another device job)")
    ap.add_argument("--sharded", action="store_true",
                    help="also drive every case through the sharded layer "
                         "(parallel/dist.py) on an 8-virtual-device CPU "
                         "mesh: randomized shard counts, flat-vs-block "
                         "staging, chained-mode halo decrypt, and (1 in 4 "
                         "cases) the batch-stream paths (cbc-batch / "
                         "rc4-batch) — outputs AND carried states vs the "
                         "oracle. The CTR aligned-end bug class lived at "
                         "exactly such a seam (VERDICT r2 #5)")
    args = ap.parse_args()

    if args.sharded and args.device:
        print("--sharded needs the 8-virtual-device CPU platform; it cannot "
              "combine with --device (one real chip has no 8-way mesh)",
              file=sys.stderr)
        return 2
    if args.sharded:
        # Must land before jax import: device count is fixed at backend init.
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    import numpy as np

    import jax

    if not args.device:
        # Pinned through jax.config, not just the env var: site hooks that
        # pre-register an accelerator plugin clobber JAX_PLATFORMS at
        # interpreter start (see tests/conftest.py), and on a tunnelled
        # device host an env-only pin would initialize the very tunnel a
        # CPU fuzz run must never touch (observed: a wedged tunnel hanging
        # a "CPU" run at its first device op).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        jax.config.update("jax_platforms", "cpu")

    from gen_golden import Oracle, build_oracle
    from our_tree_tpu.models.aes import AES, AES_DECRYPT, AES_ENCRYPT
    from our_tree_tpu.resilience import policy as repolicy
    from our_tree_tpu.resilience import watchdog as rewatchdog

    NativeAES = None
    if args.native:
        from our_tree_tpu.runtime.native import NativeAES

    dist = meshes = None
    if args.sharded:
        import jax.numpy as jnp

        from our_tree_tpu.parallel import dist
        from our_tree_tpu.utils import packing
        meshes = {}

        def mesh_for(k):
            if k not in meshes:
                meshes[k] = dist.make_mesh(k)
            return meshes[k]

    oracle = Oracle(build_oracle(pathlib.Path(args.reference)))
    rng = np.random.default_rng(args.seed)
    engines = [e.strip() for e in args.engines.split(",") if e.strip()]
    # The deadline through the shared budget accounting (resilience.
    # policy.Budget) instead of a hand-rolled `time.time() - t0` check:
    # one object owns the arithmetic, and injected faults DEBIT it —
    # an armed dispatch_hang below charges the budget the hang would
    # have burned (without sleeping), so a faulted fuzz run stops at
    # the same budget its wedged real twin would, instead of running
    # the full case count as if nothing happened.
    budget = repolicy.Budget(args.deadline)
    done = 0

    def rand_nonce():
        # 1-in-4 cases sit near a counter-wrap seam — the hard part of the
        # multi-chip bookkeeping (SURVEY.md §7 hard part #6).
        if rng.integers(4) == 0:
            n = np.full(16, 0xFF, np.uint8)
            n[-1] = rng.integers(0xF0, 0x100)
            cut = int(rng.integers(0, 16))
            n[:cut] = rng.integers(0, 256, cut, np.uint8)
            return n
        return rng.integers(0, 256, 16, dtype=np.uint8)

    def chunks(total, block_granular):
        """Random split into 1..5 chunks (resume seams). Block-granular
        modes (CBC) split on 16-byte boundaries only."""
        k = int(rng.integers(1, 6))
        if k == 1 or total < 16 * k:
            return [total]
        if block_granular:
            cuts = 16 * np.sort(rng.integers(1, total // 16, k - 1))
        else:
            cuts = np.sort(rng.integers(1, total, k - 1))
        sizes = np.diff(np.concatenate([[0], cuts, [total]]))
        return [int(s) for s in sizes if s > 0]

    def split(data, parts):
        out, pos = [], 0
        for p in parts:
            out.append(data[pos:pos + p])
            pos += p
        return out

    for case in range(args.iters):
        rewatchdog.injected_hang("dispatch_hang", "fuzz case", budget=budget)
        if budget.exhausted():
            print(f"# deadline reached after {done} cases")
            break
        keybits = int(rng.choice([128, 192, 256]))
        key = rng.integers(0, 256, keybits // 8, np.uint8).tobytes()
        mode = str(rng.choice(["ecb", "cbc", "cfb128", "ctr"]))
        encrypt = bool(rng.integers(2))
        n = int(rng.integers(1, args.max_bytes + 1))
        if mode in ("ecb", "cbc"):
            n = max(16, n - n % 16)
        data = rng.integers(0, 256, n, np.uint8)
        iv = rand_nonce()
        parts = chunks(n, block_granular=(mode == "cbc"))
        chunk_note = f" chunks={parts}" if mode != "ecb" else ""
        tag = (f"case {case}: {mode} {'enc' if encrypt else 'dec'} "
               f"k{keybits} n={n}{chunk_note} seed={args.seed}")
        data_parts = split(data, parts)

        # Oracle reference — engine-independent, computed once per case.
        # `want_state` is the final resume state, compared too: a wrong
        # carried IV/offset/counter is invisible to output-only checks.
        if mode == "ecb":
            want = oracle.ecb(key, data.tobytes(), encrypt)
            want_state = None
        elif mode == "cbc":
            wout, wiv = [], iv.tobytes()
            for dp in data_parts:
                w, wiv = oracle.cbc(key, wiv, dp.tobytes(), encrypt)
                wout.append(w)
            want, want_state = b"".join(wout), wiv
        elif mode == "cfb128":
            wchunks, woff, wiv = oracle.cfb128(
                key, iv.tobytes(), [dp.tobytes() for dp in data_parts],
                encrypt)
            want, want_state = b"".join(wchunks), (woff, wiv)
        else:  # ctr
            wchunks, woff, wnc, wsb = oracle.ctr(
                key, iv.tobytes(), [dp.tobytes() for dp in data_parts])
            want, want_state = b"".join(wchunks), (woff, wnc, wsb)

        for engine in engines:
            a = AES(key, engine=engine)
            got_state = None
            if mode == "ecb":
                got = a.crypt_ecb(AES_ENCRYPT if encrypt else AES_DECRYPT,
                                  data).tobytes()
            elif mode == "cbc":
                out, reg = [], iv.copy()
                for dp in data_parts:
                    o, reg = a.crypt_cbc(
                        AES_ENCRYPT if encrypt else AES_DECRYPT, reg, dp)
                    out.append(o)
                got = b"".join(o.tobytes() for o in out)
                got_state = bytes(reg)
            elif mode == "cfb128":
                out, off, reg = [], 0, iv.copy()
                for dp in data_parts:
                    o, off, reg = a.crypt_cfb128(
                        AES_ENCRYPT if encrypt else AES_DECRYPT, off, reg,
                        dp)
                    out.append(o)
                got = b"".join(o.tobytes() for o in out)
                got_state = (off, bytes(reg))
            else:  # ctr (symmetric)
                out, off, nc, sb = [], 0, iv.copy(), np.zeros(16, np.uint8)
                for dp in data_parts:
                    o, off, nc, sb = a.crypt_ctr(off, nc, sb, dp)
                    out.append(o)
                got = b"".join(o.tobytes() for o in out)
                got_state = (off, bytes(nc), bytes(sb))

            if got != want:
                print(f"PARITY FAIL (output) [{engine}] {tag}",
                      file=sys.stderr)
                return 1
            if want_state is not None and got_state != _norm(want_state):
                print(f"PARITY FAIL (resume state) [{engine}] {tag}\n"
                      f"  got  {got_state!r}\n  want {_norm(want_state)!r}",
                      file=sys.stderr)
                return 1

        if NativeAES is not None:
            na = NativeAES(key)
            got_state = state_want = None
            if mode == "ecb":
                got = na.ecb(data, encrypt).tobytes()
            elif mode == "cbc":
                out, reg = [], iv.copy()
                for dp in data_parts:
                    o, reg = na.cbc(reg, dp, encrypt)
                    out.append(o)
                got = b"".join(o.tobytes() for o in out)
                got_state, state_want = bytes(reg), _norm(want_state)
            elif mode == "cfb128":
                out, off, reg = [], 0, iv.copy()
                for dp in data_parts:
                    o, off, reg = na.cfb128(off, reg, dp, encrypt)
                    out.append(o)
                got = b"".join(o.tobytes() for o in out)
                got_state, state_want = (off, bytes(reg)), _norm(want_state)
            else:  # ctr: the C API is bulk-only (no nc_off/stream_block
                # surface) — one-shot output plus the advanced counter.
                o, nc = na.ctr(iv, data)
                got = o.tobytes()
                got_state = bytes(nc)
                state_want = _norm(want_state)[1]  # oracle (off, nc, sb)
            if got != want:
                print(f"PARITY FAIL (output) [native] {tag}",
                      file=sys.stderr)
                return 1
            if state_want is not None and got_state != state_want:
                print(f"PARITY FAIL (resume state) [native] {tag}\n"
                      f"  got  {got_state!r}\n  want {state_want!r}",
                      file=sys.stderr)
                return 1
        if args.sharded:
            # The same case through the sharded layer: a random shard
            # count, random flat-vs-block staging, a random engine. The
            # comparison target is the SAME oracle bytes the single-device
            # paths just matched, so a seam bug (per-shard counter offset,
            # halo block, padding slice) shows up as a direct oracle
            # mismatch, not a drift between two of our own paths.
            eng = str(rng.choice(engines))
            flat = bool(rng.integers(2))
            nfull = n // 16 * 16
            nblocks = nfull // 16

            def stage(buf):
                w = packing.np_bytes_to_words(
                    np.frombuffer(buf, np.uint8, count=nfull))
                return jnp.asarray(w if flat else w.reshape(-1, 4))

            def words_bytes(o):
                return packing.np_words_to_bytes(
                    np.asarray(o, np.uint32).reshape(-1, 4)).tobytes()

            stag = (f"{tag} sharded flat={int(flat)} eng={eng}")
            if nblocks:
                if mode == "ecb":
                    k = int(rng.integers(1, 9))
                    got = words_bytes(dist.ecb_crypt_sharded(
                        stage(data.tobytes()), a.rk_enc if encrypt else a.rk_dec,
                        a.nr, mesh_for(k), encrypt=encrypt, engine=eng))
                    if got != want:
                        print(f"PARITY FAIL (sharded ecb x{k}) {stag}",
                              file=sys.stderr)
                        return 1
                elif mode == "ctr":
                    k = int(rng.integers(1, 9))
                    ctr_be = jnp.asarray(
                        packing.np_bytes_to_words(iv).byteswap())
                    got = words_bytes(dist.ctr_crypt_sharded(
                        stage(data.tobytes()), ctr_be, a.rk_enc, a.nr,
                        mesh_for(k), engine=eng))
                    if got != want[:nfull]:
                        print(f"PARITY FAIL (sharded ctr x{k}) {stag}",
                              file=sys.stderr)
                        return 1
                else:
                    # Chained modes: the sharded layer only has the halo
                    # DECRYPT (encrypt is a true recurrence). Run it on the
                    # case's ciphertext stream whichever direction the case
                    # was: ct -> pt must reproduce the oracle's inverse.
                    ct = (want if encrypt else data.tobytes())[:nfull]
                    expect = (data.tobytes() if encrypt else want)[:nfull]
                    divisors = [k for k in range(1, 9) if nblocks % k == 0]
                    k = int(rng.choice(divisors))
                    ivw = jnp.asarray(packing.np_bytes_to_words(iv))
                    if mode == "cbc":
                        got = words_bytes(dist.cbc_decrypt_sharded(
                            stage(ct), ivw, a.rk_dec, a.nr, mesh_for(k),
                            engine=eng))
                    else:
                        got = words_bytes(dist.cfb128_decrypt_sharded(
                            stage(ct), ivw, a.rk_enc, a.nr, mesh_for(k),
                            engine=eng))
                    if got != expect:
                        print(f"PARITY FAIL (sharded {mode}-dec halo x{k}) "
                              f"{stag}", file=sys.stderr)
                        return 1

            if rng.integers(4) == 0:
                # Batch-stream paths: S independent streams sharded over a
                # random mesh — outputs AND carried states per stream vs
                # the oracle (CBC final IVs; ARC4 keystream from chunked
                # oracle calls, which exercise its carried {x,y,m}).
                from our_tree_tpu.models.arc4 import ARC4

                S = int(rng.integers(1, 9))
                k = int(rng.integers(1, 9))
                per = 16 * int(rng.integers(1, 65))
                bdata = rng.integers(0, 256, (S, per), np.uint8)
                ivs = rng.integers(0, 256, (S, 16), np.uint8)
                w = packing.np_bytes_to_words(bdata.reshape(-1)).reshape(S, -1)
                if not bool(rng.integers(2)):  # block staging A/B
                    w = w.reshape(S, -1, 4)
                ivw = jnp.asarray(
                    packing.np_bytes_to_words(ivs.reshape(-1)).reshape(S, 4))
                out, iv_out = dist.cbc_encrypt_batch_sharded(
                    jnp.asarray(w), ivw, a.rk_enc, a.nr, mesh_for(k))
                out = np.asarray(out, np.uint32).reshape(S, -1)
                iv_out = np.asarray(iv_out, np.uint32).reshape(S, 4)
                for s in range(S):
                    w_want, w_iv = oracle.cbc(
                        key, ivs[s].tobytes(), bdata[s].tobytes(), True)
                    if (words_from := packing.np_words_to_bytes(
                            out[s].reshape(-1, 4)).tobytes()) != w_want:
                        print(f"PARITY FAIL (cbc-batch S={S} x{k} stream "
                              f"{s}) {tag}", file=sys.stderr)
                        return 1
                    if packing.np_words_to_bytes(
                            iv_out[s].reshape(1, 4)).tobytes() != w_iv:
                        print(f"PARITY FAIL (cbc-batch final IV S={S} x{k} "
                              f"stream {s}) {tag}", file=sys.stderr)
                        return 1
                klen = int(rng.integers(1, 33))
                keys = [rng.integers(0, 256, klen, np.uint8).tobytes()
                        for _ in range(S)]
                cuts = [int(c) for c in
                        np.sort(rng.integers(1, per, 2))] + [per]
                chunks_len = np.diff([0] + sorted(set(cuts))).tolist()
                _, ks = dist.arc4_prep_batch_sharded(
                    ARC4.batch_states(keys), per, mesh_for(k))
                ks = np.asarray(ks)
                for s in range(S):
                    w_ks, _ = oracle.arc4_keystream(keys[s], chunks_len)
                    if ks[s].tobytes() != b"".join(w_ks):
                        print(f"PARITY FAIL (rc4-batch S={S} x{k} stream "
                              f"{s}) {tag}", file=sys.stderr)
                        return 1

        done += 1
        if done % args.clear_every == 0:
            # Every random length is a fresh XLA-CPU compilation; the
            # compile caches leak enough that long sessions exhaust memory
            # (same reason tests/conftest.py clears per module). Dropping
            # them bounds the fuzzer's footprint at a small recompile cost.
            jax.clear_caches()
            print(f"# {done} cases ok ({budget.spent():.0f}s)", flush=True)
    print(f"FUZZ PASS: {done} randomized configs bit-exact vs the oracle, "
          f"outputs and resume states (engines={engines})")
    return 0


def _norm(state):
    """Oracle states to the fuzzer's comparison shape (bytes/ints)."""
    if isinstance(state, bytes):
        return state
    return tuple(bytes(s) if isinstance(s, (bytes, bytearray)) else int(s)
                 for s in state)


if __name__ == "__main__":
    sys.exit(main())
