#!/usr/bin/env python
"""Hardware compile smoke for every Pallas kernel variant (run on TPU).

Asserts that each kernel actually COMPILES under Mosaic (non-interpret) and
matches the jnp engine bit-exactly on device — the guard against shipping
kernels that only ever ran in interpreter mode (cf. the reference's GPU
kernels, which never executed at benchmark sizes because launches failed
unchecked — SURVEY.md §2 defect #4). Protects the tuning sweep
(scripts/tune_tpu.py) from dying at compile time mid-run.

Matrix: {ecb-enc, ecb-dec, ctr-fused, ctr-gen, ecb-gt-enc, ecb-gt-dec,
       ctr-gt, ctr-sharded(mesh 1)}
      x MC lowering {perm, roll}  x  tile {1024, 2048}  x  S-box.

OT_PALLAS_TILE / OT_PALLAS_MC are read at module import, so each config
runs in its own subprocess (also: exactly one jax process at a time —
sequential children, never parallel, per the host's tunnel constraints).

    python scripts/smoke_tpu.py                 # full matrix
    python scripts/smoke_tpu.py --tiles 1024 --mc perm   # subset
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: 2 MiB -> 131072 blocks -> 4096 lanes: >= 2 grid steps even at tile 2048,
#: so every config exercises a real multi-step grid, not a shrunken tile.
NBYTES = int(os.environ.get("OT_SMOKE_BYTES", 2 << 20))


def child() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from our_tree_tpu.models.aes import AES
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.parallel import dist
    from our_tree_tpu.resilience import watchdog
    from our_tree_tpu.utils import packing

    platform = jax.devices()[0].platform
    if platform == "cpu":
        print(json.dumps({"config": "n/a", "ok": False,
                          "error": "no accelerator (interpret mode)"}))
        return 1
    assert not pallas_aes._interpret(), "interpret mode on an accelerator?"

    from our_tree_tpu.ops import bitslice

    cfg = (f"tile={pallas_aes.TILE},mc={pallas_aes.MC_LOWERING},"
           f"sbox={bitslice.SBOX_IMPL}")
    a = AES(bytes(range(16)))
    rng = np.random.default_rng(1337)
    host = rng.integers(0, 256, NBYTES, dtype=np.uint8)
    # Watchdog-guarded device contact (armed only when
    # OT_DISPATCH_DEADLINE is set — the parent already SIGKILLs a hung
    # child at its 1800 s deadline; the guard is the honest seam shape).
    with watchdog.deadline(watchdog.default_deadline_s(),
                           what="smoke input staging"):
        words = jax.device_put(jnp.asarray(packing.np_bytes_to_words(host)))
        nonce = np.frombuffer(bytes(range(16)), np.uint8)
        ctr_be = jax.device_put(jnp.asarray(
            packing.np_bytes_to_words(nonce).byteswap()))

    from our_tree_tpu.models import aes as aes_mod

    # Each distinct jnp reference is computed once per child (the CTR one
    # serves three checks). ravel() both sides: the pallas entry points
    # return (N, 4) where the flat-stream references return (4N,) — the
    # byte streams are what must agree, not the container shape.
    with watchdog.deadline(watchdog.default_deadline_s(),
                           what="smoke jnp references"):
        want_ecb = np.asarray(jax.block_until_ready(
            jax.jit(lambda w: aes_mod.ecb_encrypt_words(
                w, a.rk_enc, a.nr, "jnp"))(words))).ravel()
        want_dec = np.asarray(jax.block_until_ready(
            jax.jit(lambda w: aes_mod.ecb_decrypt_words(
                w, a.rk_dec, a.nr, "jnp"))(words))).ravel()
        want_ctr = np.asarray(jax.block_until_ready(
            jax.jit(lambda w: aes_mod.ctr_crypt_words(
                w, ctr_be, a.rk_enc, a.nr, "jnp"))(words))).ravel()

    def check(name, fn, want):
        t0 = time.perf_counter()
        with watchdog.deadline(watchdog.default_deadline_s(),
                               what=f"smoke kernel {name}"):
            got = np.asarray(jax.block_until_ready(jax.jit(fn)(words)))
        dt = time.perf_counter() - t0
        ok = bool(np.array_equal(got.ravel(), want))
        print(json.dumps({"config": cfg, "kernel": name, "ok": ok,
                          "compile_plus_run_s": round(dt, 1)}), flush=True)
        if not ok:
            raise SystemExit(f"{cfg} {name}: MISMATCH vs jnp engine")

    check("ecb-enc",
          lambda w: pallas_aes.encrypt_words(
              w.reshape(-1, 4), a.rk_enc, a.nr), want_ecb)
    check("ecb-dec",
          lambda w: pallas_aes.decrypt_words(
              w.reshape(-1, 4), a.rk_dec, a.nr), want_dec)
    check("ctr-fused",
          lambda w: pallas_aes.ctr_crypt_words(
              w.reshape(-1, 4),
              aes_mod.ctr_le_blocks(
                  ctr_be, jnp.arange(w.size // 4, dtype=jnp.uint32)),
              a.rk_enc, a.nr), want_ctr)
    check("ctr-gen",
          lambda w: pallas_aes.ctr_crypt_words_gen(
              w.reshape(-1, 4), ctr_be, a.rk_enc, a.nr), want_ctr)

    # Grouped-transpose kernels (in-kernel SWAR ladder — the riskiest
    # Mosaic surface in the repo; this smoke is their first hardware
    # compile).
    check("ecb-gt-enc",
          lambda w: pallas_aes.encrypt_words_gt(
              w.reshape(-1, 4), a.rk_enc, a.nr), want_ecb)
    check("ecb-gt-dec",
          lambda w: pallas_aes.decrypt_words_gt(
              w.reshape(-1, 4), a.rk_dec, a.nr), want_dec)
    check("ctr-gt",
          lambda w: pallas_aes.ctr_crypt_words_gt(
              w.reshape(-1, 4), ctr_be, a.rk_enc, a.nr), want_ctr)

    # Dense-boundary kernels ((128, W) layout, transpose32_dense ladder —
    # round-3 addition, VERDICT r2 #3; like the gt kernels before round 2's
    # window, this smoke is their first hardware compile).
    check("ecb-dense-enc",
          lambda w: pallas_aes.encrypt_words_dense(
              w.reshape(-1, 4), a.rk_enc, a.nr), want_ecb)
    check("ecb-dense-dec",
          lambda w: pallas_aes.decrypt_words_dense(
              w.reshape(-1, 4), a.rk_dec, a.nr), want_dec)
    check("ctr-dense",
          lambda w: pallas_aes.ctr_crypt_words_dense(
              w.reshape(-1, 4), ctr_be, a.rk_enc, a.nr), want_ctr)

    # shard_map + pallas on hardware (the check_vma-workaround combination
    # that CI only ever runs on CPU): a 1-device mesh on the real chip,
    # all three kernel-boundary layouts.
    mesh = dist.make_mesh(1)
    check("ctr-sharded-pallas",
          lambda w: dist.ctr_crypt_sharded(
              w, ctr_be, a.rk_enc, a.nr, mesh, engine="pallas"), want_ctr)
    check("ctr-sharded-gt",
          lambda w: dist.ctr_crypt_sharded(
              w, ctr_be, a.rk_enc, a.nr, mesh, engine="pallas-gt"), want_ctr)
    check("ctr-sharded-dense",
          lambda w: dist.ctr_crypt_sharded(
              w, ctr_be, a.rk_enc, a.nr, mesh, engine="pallas-dense"),
          want_ctr)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiles", default="1024,2048")
    ap.add_argument("--mc", default="perm,roll")
    ap.add_argument("--sbox", default="tower,bp",
                    help="S-box formulations to compile-test (the tuning "
                         "sweep runs both; so must the smoke)")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.child:
        return child()

    # Shared persistent compile cache across the per-config children (the
    # jnp reference recompiles identically in every child otherwise).
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

    # Single-tenant device coordination (see utils/devlock.py): wait for a
    # prior measurement job, then hold the marker for the matrix.
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _devlock_loader import load_devlock, load_resilience

    devlock = load_devlock()

    failures = 0
    with devlock.hold(wait_budget_s=900.0,
                      on_wait=lambda p: print(f"# waiting for {p}",
                                              file=sys.stderr)):
        for tile in args.tiles.split(","):
            for mc in args.mc.split(","):
                for sbox in args.sbox.split(","):
                    env = dict(os.environ, OT_PALLAS_TILE=tile.strip(),
                               OT_PALLAS_MC=mc.strip(), OT_SBOX=sbox.strip())
                    tag = f"tile={tile} mc={mc} sbox={sbox}"
                    print(f"## {tag}", flush=True)
                    # capture=False: the child's per-kernel JSON lines
                    # stream live (this is an operator survey, watched as
                    # it runs). A hung Mosaic compile is a failing config
                    # ("timeout" kind; the child's GROUP is SIGKILLed),
                    # not a reason to abandon the rest of the matrix.
                    r = load_resilience("isolate").run_child(
                        [sys.executable, os.path.abspath(__file__),
                         "--child"],
                        timeout_s=1800, env=env, capture=False,
                        name=f"smoke:{tag}")
                    rc = -1 if r.kind == "timeout" else r.rc
                    if rc:
                        failures += 1
                        print(f"## {tag} FAILED rc={rc}", flush=True)
    print(f"SMOKE {'FAIL' if failures else 'PASS'} "
          f"({failures} failing configs)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
