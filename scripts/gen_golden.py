"""Generate golden parity data from the reference C implementation.

Compiles the reference's portable AES (`aes-modes/aes.c`) and ARC4
(`arc4.c`) — the only trustworthy correctness oracles in the reference per
SURVEY.md §2 ("known defects") — into a shared library, drives them through
ctypes, and writes `tests/golden/golden.json`. The checked-in JSON makes the
test suite self-contained: CI parity tests never need the reference repo.

Run once (or whenever coverage is extended):
    python scripts/gen_golden.py [--reference /root/reference]
"""

from __future__ import annotations

import argparse
import ctypes
import json
import pathlib
import sys
import tempfile

import numpy as np

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "scripts"))
from _devlock_loader import load_resilience  # noqa: E402


class AesContext(ctypes.Structure):
    # aes_context at reference aes-modes/aes.h:41-47 (unsigned long on LP64).
    _fields_ = [
        ("nr", ctypes.c_int),
        ("rk", ctypes.POINTER(ctypes.c_ulong)),
        ("buf", ctypes.c_ulong * 68),
    ]


class Arc4Context(ctypes.Structure):
    # arc4_context at reference arc4.h:35-41.
    _fields_ = [
        ("x", ctypes.c_int),
        ("y", ctypes.c_int),
        ("m", ctypes.c_ubyte * 256),
    ]


def build_oracle(reference: pathlib.Path) -> ctypes.CDLL:
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="cryptoracle_"))
    so = tmp / "libref.so"
    r = load_resilience("isolate").run_child(
        [
            "gcc", "-shared", "-fPIC", "-O2", "-std=gnu99",
            # The reference compiles CFB out and never enables the AES self
            # test (aes.c:32-33); enable both for full oracle coverage.
            "-DPOLARSSL_SELF_TEST", "-DPOLARSSL_CIPHER_MODE_CFB",
            "-I", str(reference / "aes-modes"), "-I", str(reference),
            str(reference / "aes-modes" / "aes.c"),
            str(reference / "arc4.c"),
            "-o", str(so),
        ],
        timeout_s=300.0, name="build-ref-oracle",
    )
    if not r.ok:
        raise RuntimeError(
            f"reference oracle build failed ({r.kind}, rc={r.rc}): "
            f"{r.err.strip()[-2000:]}")
    return ctypes.CDLL(str(so))


class Oracle:
    """ctypes driver for the reference implementation."""

    def __init__(self, lib: ctypes.CDLL):
        self.lib = lib

    # -- AES ---------------------------------------------------------------
    def _ctx(self, key: bytes, enc: bool) -> AesContext:
        ctx = AesContext()
        fn = self.lib.aes_setkey_enc if enc else self.lib.aes_setkey_dec
        rc = fn(ctypes.byref(ctx), key, len(key) * 8)
        assert rc == 0
        return ctx

    def ecb(self, key: bytes, data: bytes, encrypt: bool) -> bytes:
        ctx = self._ctx(key, encrypt)
        out = bytearray(len(data))
        buf = (ctypes.c_ubyte * 16)()
        ob = (ctypes.c_ubyte * 16)()
        for off in range(0, len(data), 16):
            buf[:] = data[off : off + 16]
            self.lib.aes_crypt_ecb(ctypes.byref(ctx), 1 if encrypt else 0, buf, ob)
            out[off : off + 16] = bytes(ob)
        return bytes(out)

    def cbc(self, key: bytes, iv: bytes, data: bytes, encrypt: bool) -> tuple[bytes, bytes]:
        ctx = self._ctx(key, encrypt)
        ivb = (ctypes.c_ubyte * 16)(*iv)
        out = (ctypes.c_ubyte * len(data))()
        rc = self.lib.aes_crypt_cbc(
            ctypes.byref(ctx), 1 if encrypt else 0, len(data), ivb, bytes(data), out
        )
        assert rc == 0
        return bytes(out), bytes(ivb)

    def cfb128(self, key: bytes, iv: bytes, chunks: list[bytes], encrypt: bool):
        """Returns (outputs per chunk, final iv_off, final iv)."""
        ctx = self._ctx(key, True)  # CFB always uses the encryption schedule
        ivb = (ctypes.c_ubyte * 16)(*iv)
        off = ctypes.c_int(0)
        outs = []
        for chunk in chunks:
            out = (ctypes.c_ubyte * len(chunk))()
            rc = self.lib.aes_crypt_cfb128(
                ctypes.byref(ctx), 1 if encrypt else 0, len(chunk),
                ctypes.byref(off), ivb, bytes(chunk), out,
            )
            assert rc == 0
            outs.append(bytes(out))
        return outs, off.value, bytes(ivb)

    def ctr(self, key: bytes, nonce: bytes, chunks: list[bytes]):
        """Returns (outputs per chunk, final nc_off, final counter, final stream_block)."""
        ctx = self._ctx(key, True)
        nc = (ctypes.c_ubyte * 16)(*nonce)
        sb = (ctypes.c_ubyte * 16)()
        off = ctypes.c_int(0)
        outs = []
        for chunk in chunks:
            out = (ctypes.c_ubyte * len(chunk))()
            rc = self.lib.aes_crypt_ctr(
                ctypes.byref(ctx), len(chunk), ctypes.byref(off), nc, sb,
                bytes(chunk), out,
            )
            assert rc == 0
            outs.append(bytes(out))
        return outs, off.value, bytes(nc), bytes(sb)

    # -- ARC4 --------------------------------------------------------------
    def arc4_keystream(self, key: bytes, chunks: list[int]):
        ctx = Arc4Context()
        self.lib.arc4_setup(ctypes.byref(ctx), key, len(key))
        outs = []
        for n in chunks:
            ks = (ctypes.c_ubyte * n)()
            self.lib.arc4_prep(ctypes.byref(ctx), n, ks)
            outs.append(bytes(ks))
        return outs, (ctx.x, ctx.y, bytes(ctx.m))

    def self_tests(self) -> dict:
        return {
            "aes_self_test": int(self.lib.aes_self_test(0)),
            "arc4_self_test": int(self.lib.arc4_self_test(0)),
        }


def h(b: bytes) -> str:
    return b.hex()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    args = ap.parse_args()

    oracle = Oracle(build_oracle(pathlib.Path(args.reference)))
    rng = np.random.default_rng(1337)  # the reference's fixed seed (test.c:131)

    golden: dict = {"self_tests": oracle.self_tests()}
    assert golden["self_tests"] == {"aes_self_test": 0, "arc4_self_test": 0}, golden

    aes_cases = []
    for keybits in (128, 192, 256):
        key = rng.integers(0, 256, keybits // 8, dtype=np.uint8).tobytes()
        iv = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        pt = rng.integers(0, 256, 256, dtype=np.uint8).tobytes()
        pt_odd = rng.integers(0, 256, 100, dtype=np.uint8).tobytes()
        case = {"keybits": keybits, "key": h(key), "iv": h(iv), "pt": h(pt), "pt_odd": h(pt_odd)}

        case["ecb_ct"] = h(oracle.ecb(key, pt, True))
        case["ecb_dec_of_pt"] = h(oracle.ecb(key, pt, False))

        ct, iv_out = oracle.cbc(key, iv, pt, True)
        case["cbc_ct"], case["cbc_iv_out"] = h(ct), h(iv_out)
        dpt, div_out = oracle.cbc(key, iv, pt, False)
        case["cbc_dec"], case["cbc_dec_iv_out"] = h(dpt), h(div_out)

        outs, off, ivf = oracle.cfb128(key, iv, [pt_odd], True)
        case["cfb_ct"], case["cfb_iv_off"], case["cfb_iv_out"] = h(outs[0]), off, h(ivf)
        chunks = [pt_odd[:7], pt_odd[7:52], pt_odd[52:]]
        outs_c, off_c, ivf_c = oracle.cfb128(key, iv, chunks, True)
        assert b"".join(outs_c) == outs[0] and off_c == off and ivf_c == ivf
        douts, doff, divf = oracle.cfb128(key, iv, [bytes.fromhex(case["cfb_ct"])], False)
        case["cfb_dec_roundtrip"] = h(douts[0])

        # CTR: plain nonce and a carry-propagating nonce near 2^128.
        for tag, nonce in (("ctr", iv), ("ctr_wrap", b"\xff" * 15 + b"\xfe")):
            outs, off, nc, sb = oracle.ctr(key, nonce, [pt_odd])
            case[f"{tag}_nonce"] = h(nonce)
            case[f"{tag}_ct"] = h(outs[0])
            case[f"{tag}_nc_off"] = off
            case[f"{tag}_counter_out"] = h(nc)
            case[f"{tag}_stream_block"] = h(sb)
            outs_c, off_c, nc_c, sb_c = oracle.ctr(key, nonce, [pt_odd[:7], pt_odd[7:52], pt_odd[52:]])
            assert b"".join(outs_c) == outs[0] and (off_c, nc_c, sb_c) == (off, nc, sb)

        aes_cases.append(case)
    golden["aes"] = aes_cases

    arc4_cases = []
    for klen in (5, 8, 16, 32):
        key = rng.integers(0, 256, klen, dtype=np.uint8).tobytes()
        outs, (x, y, m) = oracle.arc4_keystream(key, [300])
        outs_c, (xc, yc, mc) = oracle.arc4_keystream(key, [100, 200])
        assert b"".join(outs_c) == outs[0] and (xc, yc, mc) == (x, y, m)
        arc4_cases.append(
            {"key": h(key), "keystream": h(outs[0]), "x": x, "y": y, "m": h(m)}
        )
    golden["arc4"] = arc4_cases

    out_path = REPO / "tests" / "golden" / "golden.json"
    out_path.write_text(json.dumps(golden, indent=1))
    print(f"wrote {out_path} ({out_path.stat().st_size} bytes)")


if __name__ == "__main__":
    main()
