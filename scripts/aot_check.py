#!/usr/bin/env python
"""Deviceless Mosaic compile check for every Pallas entry point.

Round 3 shipped kernels that had never met the Mosaic compiler (the tunnel
was wedged all round; everything was interpreter-verified only) — a
first-contact compile failure was an acknowledged, unhandled risk
(VERDICT r3 weak #2). This script retires that risk WITHOUT hardware:
``jax.experimental.topologies.get_topology_desc("v5e:2x2", "tpu")`` builds
a deviceless PJRT TPU topology from the bundled libtpu — verified on this
host to answer locally without touching the (wedged) tunnel — and
``jax.jit(...).trace(...).lower().compile()`` then runs the full
Pallas -> Mosaic -> TPU-executable pipeline against that target from a
CPU-pinned process.

Covers, per pallas-backed engine: the ECB encrypt core, the (deduped)
decrypt core, and the fused-CTR entry — plus the SHARDED CTR path over a
4-chip v5e mesh (shard_map + per-shard counter offsets), so the multichip
sharding also gets a real TPU compile, not just the virtual-CPU dryrun.

The reference's only compile gate was its Makefile
(aes-gpu/Source/Makefile.asc:1-13 — and its kernels shipped broken, §2
defects #3/#4); this is the check it never had. Driven in CI by
tests/test_aot_compile.py (slow tier); runnable standalone:

    python scripts/aot_check.py [--topology v5e:2x2] [--engines all]

Exit 0 iff every kernel compiles. One JSON summary line on stdout.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from our_tree_tpu.utils.platform import pin_cpu_if_requested


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:2x2",
                    help="PJRT TPU topology spec (deviceless)")
    ap.add_argument("--engines", default="all",
                    help="comma list of pallas engines, or 'all'")
    ap.add_argument("--skip-sharded", action="store_true")
    args = ap.parse_args()

    # CPU-pinned process: the topology is the only TPU-shaped thing here.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    pin_cpu_if_requested()
    # The kernels must take the COMPILED path (pl.pallas_call interpret=False)
    # even though the attached devices are CPU — that is the whole point.
    os.environ["OT_PALLAS_INTERPRET"] = "0"

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from our_tree_tpu.models import aes as aes_mod
    from our_tree_tpu.models.aes import CORES, CTR_FUSED, PALLAS_BACKED
    from our_tree_tpu.ops.keyschedule import expand_key_dec, expand_key_enc

    try:
        topo = topologies.get_topology_desc(args.topology, "tpu")
    except Exception as e:
        # No TPU PJRT plugin / libtpu on this host: the check cannot run
        # at all, which is distinct from a kernel failing to compile.
        # Exit 3 so the CI wrapper (tests/test_aot_compile.py) skips
        # instead of failing.
        print(json.dumps({"topology": args.topology,
                          "error": f"topology unavailable: "
                                   f"{type(e).__name__}: {str(e)[:300]}"}))
        return 3
    kind = topo.devices[0].device_kind
    print(f"# topology {args.topology}: {len(topo.devices)} x {kind}",
          file=sys.stderr)

    engines = (sorted(PALLAS_BACKED) if args.engines == "all"
               else [e.strip() for e in args.engines.split(",") if e.strip()])

    nr, rk_enc = expand_key_enc(b"\x00" * 16)
    _, rk_dec = expand_key_dec(b"\x00" * 16)
    mesh1 = Mesh(np.array(topo.devices[:1]), ("x",))
    rep = NamedSharding(mesh1, P())

    def arg(shape, dtype=jnp.uint32, sharding=rep):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    w = arg((64, 4))
    ctr = arg((4,))
    rke = arg(rk_enc.shape)
    rkd = arg(rk_dec.shape)

    # (label, fn, trace_args) — decrypt deduped by callable identity (the
    # -bp engines share their twin's tower decrypt; compiling it twice
    # would just re-verify the identical jaxpr under a second name).
    cases, seen_dec = [], {}
    for eng in engines:
        if eng not in PALLAS_BACKED:
            print(f"# skipping {eng}: not a pallas-backed engine",
                  file=sys.stderr)
            continue
        enc_fn, dec_fn = CORES[eng]
        cases.append((f"{eng}:enc",
                      lambda a, b, _f=enc_fn: _f(a, b, nr), (w, rke)))
        if dec_fn not in seen_dec:
            seen_dec[dec_fn] = eng
            cases.append((f"{eng}:dec",
                          lambda a, b, _f=dec_fn: _f(a, b, nr), (w, rkd)))
        fused = CTR_FUSED.get(eng)
        if fused is not None:
            cases.append((f"{eng}:ctr",
                          lambda a, c, b, _f=fused: _f(a, c, b, nr),
                          (w, ctr, rke)))

    # HBM-fit gate (round 4): the 32x padded-intermediate OOM
    # (ops/bitslice.py:dense_words notes — a (W, 32, 4) stage tensor
    # asking 32 GiB for a 1 GiB buffer) surfaced as a COMPILE-time
    # allocation failure, so the chipless compiler regression-gates it:
    # the 1 GiB flat-boundary dense CTR must compile for one v5e's 16 GiB
    # HBM. Catches any relayout composition whose intermediate re-grows a
    # padded minor dim — the class, not just the instance.
    dense_sel = [e for e in ("pallas-dense-bp", "pallas-dense")
                 if e in engines]
    if dense_sel:
        # Through the models layer with the FLAT (4N,) boundary — the
        # production staging form (a (N, 4) boundary input would itself
        # carry the padded layout: feeding it directly here correctly
        # fails this same gate with a 32 GiB copy, which is the staging
        # tax bench.py's flat default exists to avoid, not a regression).
        # Keyed on EITHER dense engine being selected, and compiled with
        # whichever is — the two share the relayout under test (the bp
        # twin differs only by S-box circuit).
        big = arg((1 << 28,))  # 1 GiB of u32, flat dense boundary
        cases.append((
            "dense-ctr-1gib-hbm-fit",
            lambda a, c, b: aes_mod.ctr_crypt_words(
                a, c, b, nr, dense_sel[0]),
            (big, ctr, rke)))
        # The corpus OOM's second instance: CBC decrypt's shifted-prev
        # stream, built flat since round 4 (models/aes.py:
        # _cbc_decrypt_words_impl) — an (N, 4) shift materialised 32 GiB
        # at 1000 MiB.
        cases.append((
            "cbcdec-1gib-hbm-fit",
            lambda a, i, b: aes_mod.cbc_decrypt_words(
                a, i, b, nr, dense_sel[0])[0],
            (big, ctr, rkd)))

    if not args.skip_sharded and len(topo.devices) > 1:
        from our_tree_tpu.parallel import dist

        meshN = Mesh(np.array(topo.devices), (dist.AXIS,))
        shardN = NamedSharding(meshN, P(dist.AXIS))
        repN = NamedSharding(meshN, P())

        def sharded_ctr(words, ctr_be, rk):
            # check_vma=True: hardware semantics (no interpreter, no bug).
            return dist._ctr_sharded_jit(
                words, ctr_be, rk, nr=nr, mesh=meshN, axis=dist.AXIS,
                engine="pallas-dense", check_vma=True)

        cases.append((f"sharded-ctr[{len(topo.devices)}chip]", sharded_ctr,
                      (arg((64 * len(topo.devices), 4), sharding=shardN),
                       arg((4,), sharding=repN),
                       arg(rk_enc.shape, sharding=repN))))

    results, failed = {}, []
    for label, fn, trace_args in cases:
        t0 = time.perf_counter()
        try:
            jax.jit(fn).trace(*trace_args).lower().compile()
            dt = time.perf_counter() - t0
            results[label] = round(dt, 2)
            print(f"PASS {label}  ({dt:.1f}s)", file=sys.stderr)
        except Exception as e:
            failed.append(label)
            results[label] = f"FAIL: {type(e).__name__}: {str(e)[:300]}"
            print(f"FAIL {label}: {type(e).__name__}: {str(e)[:300]}",
                  file=sys.stderr)
    print(json.dumps({"topology": args.topology, "device_kind": kind,
                      "n_cases": len(cases), "failed": failed,
                      "results": results}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
