"""Shared loader for jax-free bare-file imports used by the sweep scripts.

The sweep parents are deliberately jax-free (they only spawn jax children),
so devlock/ranking/resilience modules are loaded as bare files instead of
through the package import, which would pull jax in. Scripts import this
sibling module (the script's own directory is on sys.path when run as
`python scripts/<name>.py`).
"""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_util(name):
    spec = importlib.util.spec_from_file_location(
        f"_ot_{name}",
        os.path.join(REPO, "our_tree_tpu", "utils", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_devlock():
    return _load_util("devlock")


def load_ranking():
    """utils/ranking.py, bare-loaded for the same jax-free reason."""
    return _load_util("ranking")


def _load_canonical(canonical, *relpath):
    mod = sys.modules.get(canonical)
    if mod is not None:
        return mod
    spec = importlib.util.spec_from_file_location(
        canonical, os.path.join(REPO, *relpath))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[canonical] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(canonical, None)
        raise
    return mod


def load_resilience(name):
    """resilience/<name>.py, bare-loaded — registered in sys.modules under
    its CANONICAL dotted name so the fault counters / degradation ledger
    stay one-per-process: a later package import (`from
    our_tree_tpu.resilience import faults` inside jax-side code) finds and
    reuses this very module instead of creating a second registry. The
    utils/devlock.py lazy hook uses the same key for the same reason."""
    return _load_canonical(f"our_tree_tpu.resilience.{name}",
                           "our_tree_tpu", "resilience", f"{name}.py")


def load_obs(name="trace"):
    """obs/<name>.py, bare-loaded under its canonical dotted name for the
    same one-per-process reason (the span stack, counters, and the open
    trace file must be shared between the jax-free driver shell and the
    package-imported jax-side code)."""
    return _load_canonical(f"our_tree_tpu.obs.{name}",
                           "our_tree_tpu", "obs", f"{name}.py")
