"""Shared loader for utils/devlock.py used by the sweep scripts.

The sweep parents are deliberately jax-free (they only spawn jax children),
so devlock is loaded as a bare file instead of through the package import,
which would pull jax in. Scripts import this sibling module (the script's
own directory is on sys.path when run as `python scripts/<name>.py`).
"""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_util(name):
    spec = importlib.util.spec_from_file_location(
        f"_ot_{name}",
        os.path.join(REPO, "our_tree_tpu", "utils", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_devlock():
    return _load_util("devlock")


def load_ranking():
    """utils/ranking.py, bare-loaded for the same jax-free reason."""
    return _load_util("ranking")
