"""Deterministic fault injection seam (``OT_FAULTS``).

The repo's defenses exist because of real failures — wedged PJRT tunnels,
init hangs, SIGKILLed sweeps — but none of them could be exercised in CI
without a genuinely broken device. This module is the seam: named injection
points wired into the real failure sites consult a registry parsed once
from ``OT_FAULTS``, so CI can script exact failure sequences on CPU and the
production paths pay a single dict lookup when the variable is unset.

Grammar::

    OT_FAULTS=init_hang:2,dispatch_fail:1,build_fail,dispatch_hang:1@2,
              lane_hang:1@lane=3

Comma-separated tokens, each ``<point>[:<count>[@<qualifier>]]``. A
counted token arms the point for exactly ``count`` firings (the first
``count`` calls to ``fire(point)`` return True, every later call
False); a bare token arms it forever. The ``@`` qualifier is one of:

* ``@<skip>`` — defer a counted point past its first ``skip`` calls
  (``dispatch_hang:1@2`` skips two dispatches, then hangs the third):
  the deterministic way to land a fault MID-unit (e.g. on the second
  worker row) instead of always on the first call; an in-process
  affordance: the ``--isolate`` supervisor's metering hands children
  plain ``:1`` shots.
* ``@lane=<i>`` — scope the point to serve dispatch lane ``i``
  (``lane_hang:1@lane=3`` hangs lane 3's next dispatch and no other
  lane's): the registry key becomes ``<point>@lane=<i>`` and only a
  seam asking for that exact lane (``scoped(point, i)`` /
  ``check_lane``) can consume the shot — how the chaos matrix kills
  one fault domain and asserts the other seven kept serving
  (serve/lanes.py, docs/SERVING.md).
* ``@backend=<i>`` — the same scoping one fault domain up: the point is
  scoped to ROUTER backend ``i`` (``backend_hang:1@backend=1`` wedges
  the router's next request to backend 1 and no other's); the registry
  key becomes ``<point>@backend=<i>`` and only the router's
  backend-dispatch seam asking for that backend (``scoped_backend`` /
  ``check_backend``, route/proxy.py) can consume the shot.

Whitespace around tokens is tolerated; unknown point names are
accepted but warned about on stderr (a typo that silently never fires
would make a CI fault job vacuously green).

Registered injection points (the fault matrix, docs/RESILIENCE.md):

=================  ========================================================
point              wired into
=================  ========================================================
``init_hang``      the PJRT init probe (repo-root ``bench.py:
                   _ensure_live_backend``): the attempt behaves as a probe
                   subprocess that hung for its full timeout.
``dispatch_fail``  device dispatch: the first real device op of a
                   measurement (``bench.py:measure``), the harness
                   backend's completion barrier
                   (``harness.backends.TpuBackend.block_until_ready``) and
                   its chained-difference timing dispatch
                   (``chained_device_times_us``).
``build_fail``     the lazy native build (``runtime.native._build``): the
                   ``make`` attempt fails as if the compiler had.
``lock_busy``      devlock acquisition (``utils.devlock.acquire``): the
                   marker behaves as held by a live concurrent job.
``dispatch_hang``  device dispatch, the wedged-not-failed variant: the
                   seam (``harness.bench._time_us``, the TpuBackend
                   barriers, the Pallas dispatch in ``ops.pallas_aes``)
                   blocks "forever" in a GIL-releasing sleep
                   (``watchdog.injected_hang``), for the watchdog to
                   interrupt or the ``--isolate`` supervisor to SIGKILL.
``unit_crash``     sweep-unit execution (``harness.bench``): the unit
                   dies as if the process had crashed mid-row.
``serve_dispatch`` the serve batch-dispatch seam (``serve/server.py``):
                   the batch's engine call raises as if the dispatch had
                   failed — the affected requests get per-request error
                   responses while the server keeps serving (the seam
                   also consults ``dispatch_fail``/``dispatch_hang``, so
                   the generic dispatch faults reach the online path
                   too; the serve-level seams skip warmup dispatches —
                   priming is not traffic — though an engine's own
                   internal seam, e.g. the Pallas launch seam, still
                   sees warmup like any first dispatch).
``lane_fail``      the per-lane dispatch seam (``serve/lanes.py``): the
                   lane's engine call raises as if that DEVICE had
                   failed. Usually lane-scoped (``lane_fail:1@lane=2``);
                   the unscoped form hits whichever lane dispatches
                   next. The lane pool retries on-lane, then fails the
                   lane over (health state machine) and re-dispatches
                   the batch bit-exactly on a healthy lane.
``lane_hang``      the wedged-device variant of ``lane_fail``: the
                   lane's dispatch blocks "forever" in a GIL-releasing
                   sleep for the lane watchdog to interrupt — the lane
                   is quarantined and its in-flight batch re-dispatched
                   on a healthy lane before any request is answered.
``backend_fail``   the router's backend-dispatch seam (route/proxy.py):
                   the framed request to the placed backend raises as if
                   the BACKEND PROCESS had failed mid-request. Usually
                   backend-scoped (``backend_fail:1@backend=2``); the
                   router degrades that backend's health and re-dispatches
                   the request bit-exactly on the next ring node before
                   any rider is answered — the lane failover contract
                   lifted to the per-host fault domain.
``backend_hang``   the wedged-backend variant of ``backend_fail``: the
                   router's request to that backend blocks past the
                   attempt deadline (an awaitable sleep — the router is
                   an asyncio loop, so the hang must yield, not block);
                   the per-request ``Budget``/attempt deadline expires,
                   the ``route-dispatch`` span is deliberately ABANDONED
                   (orphan-as-kill-evidence, the watchdog convention),
                   the backend is quarantined and the request re-dispatched.
``dispatch_slow``  the injected LATENCY regression (``injected_slow``,
                   wired into the serve lane seam): each firing sleeps
                   ``OT_SLOW_S`` (default 0.05 s) WITHOUT failing — the
                   dispatch completes, just late. A bare token slows
                   every dispatch: the deterministic way to turn the
                   ``serve.bench --slo`` regression gate red in CI
                   (docs/OBSERVABILITY.md) — no error counters move,
                   only the latency/goodput SLOs.
``tag_mismatch``   the serve GCM tag-verify seam
                   (``serve/server.py:_gcm_finish``): the next
                   ``gcm-open`` request's computed tag is treated as
                   mismatched, so that ONE request is answered the
                   per-request ``auth-failed`` refusal while its batch
                   riders are untouched — the deterministic way CI
                   drives the authentication-failure path (no
                   exception, no failover, no lost request; the server
                   must keep serving). Fires at the host finisher, not
                   inside the fused kernel: a real mismatch is a DATA
                   event, not a dispatch fault.
``pool_stale``     the router's pooled-transport acquire seam
                   (``route/proxy.py:Backend._exchange``): the next
                   exchange behaves as if its pooled connection was
                   half-closed under the router — first use raises a
                   reset. Usually backend-scoped
                   (``pool_stale:1@backend=1``). The request must ride
                   the ring-retry failover (one redispatch, no error)
                   and the NEXT exchange to that backend re-dials
                   through the pool's RetryPolicy reconnect path — the
                   deterministic rehearsal CI's elasticity drive gates
                   the pool on.
``worker_slow_start`` the fleet supervisor's spawn seam
                   (``route/fleet.py:FleetSupervisor._boot``): the
                   newly-booted worker takes ``OT_SLOW_S`` (default
                   0.05 s) longer to go READY — a slow cold start.
                   Scoped by SPAWN ORDINAL
                   (``worker_slow_start:1@backend=2`` = the third
                   worker the supervisor ever boots). The scale event
                   completes late; riders never see it (the fleet
                   serves on the old set while the newcomer warms).
``scale_stall``    the fleet supervisor's scale-event seam (spawn AND
                   retire, ``route/fleet.py``): the decided scale
                   event aborts before touching the fleet — a stalled
                   provisioner. Scoped by spawn ordinal on the grow
                   side and by the victim's backend index on the
                   shrink side. The supervisor counts + traces a
                   ``stall`` event and retries at the next tick past
                   cooldown; membership, placement, and riders are
                   untouched.
``chunk_lost``     the transfer engine's chunk-completion seam
                   (``serve/transfer.py``): a chunk that the ladder
                   already served bit-exactly is DISCARDED before the
                   reassembly buffer sees it — the result frame lost in
                   flight. Usually chunk-scoped
                   (``chunk_lost:1@chunk=3`` loses transfer chunk 3 and
                   no other); the manager re-dispatches exactly that
                   chunk (one ``serve_transfer_chunks{outcome=
                   redispatch}``) and the spliced output stays
                   byte-identical.
``reassembly_stall`` the transfer engine's in-order emit seam: the
                   consumer of the next contiguous chunk stalls for
                   ``OT_SLOW_S`` (an awaitable sleep — the manager is
                   an asyncio loop, the dispatch path must keep
                   draining under it). Completed chunks pile up in the
                   bounded reassembly buffer; once the byte budget is
                   crossed NEW transfers shed (``serve_transfer_shed
                   {reason=reassembly}``) while admitted chunks keep
                   flowing — backpressure, never a wedged loop.
``transfer_abort`` the transfer engine's per-chunk admission seam: the
                   whole transfer aborts with a typed
                   ``transfer-abort`` error mid-flight, acked chunks
                   preserved in the journal ledger. ``@<skip>`` places
                   the abort (``transfer_abort:1@3`` aborts at the
                   fourth chunk) — the deterministic interrupt the
                   resume drill replays a reconnecting client against.
``session_stall``  the RC4 session engine's keystream-refill seam
                   (``serve/session.py``): the batched PRGA prefetch
                   stalls ``OT_SLOW_S`` (an awaitable sleep) before
                   dispatching. The per-session window drains toward
                   the consumed offset; data chunks wait on the refill
                   (backpressure), and once the GLOBAL byte budget or
                   window can't cover a chunk it sheds typed
                   (``serve_session_shed``) — never a wedged loop.
                   Usually session-scoped
                   (``session_stall:1@session=3`` stalls session 3's
                   refill and no other).
``keystream_miss`` the session reserve seam: the session's cached
                   keystream window is DISCARDED (a cold cache / page
                   loss stand-in) — the engine regenerates from the
                   last acked-checkpoint carry in fixed quanta, counts
                   a ``serve_session_replays`` carry replay, and the
                   chunk's bytes stay bit-exact (the PRGA carry is
                   deterministic). Session-scoped like the rest.
``session_evict``  the session store's open-admission seam: the
                   tenant's least-recently-used IDLE session is
                   force-evicted even below capacity — the
                   deterministic eviction rehearsal
                   (``serve_session_evictions``). Sessions with chunks
                   in flight are never evicted: when every row is busy
                   the open sheds typed instead (the
                   eviction-mid-session refusal).
=================  ========================================================

Determinism contract: firings consume counts in call order within ONE
process (the registry is process-local state; subprocesses re-parse the
inherited env and count independently). ``fire`` never sleeps and never
raises — simulating the *cost* of a fault (e.g. the wall clock a hang
burns) is the injection point's job, so each seam stays honest about what
its real failure does.

Stdlib-only and free of intra-package imports: bare loaders (repo-root
bench.py via scripts/_devlock_loader.py, utils/devlock.py's lazy hook)
must register this module in ``sys.modules`` under
``our_tree_tpu.resilience.faults`` so the counters stay one-per-process
across bare and package import contexts.
"""

from __future__ import annotations

import os
import sys
import time

#: The names wired into real seams. Parsing accepts others (forward
#: compat, tests), but warns — see module docstring.
KNOWN_POINTS = ("init_hang", "dispatch_fail", "build_fail", "lock_busy",
                "dispatch_hang", "unit_crash", "serve_dispatch",
                "lane_fail", "lane_hang", "dispatch_slow",
                "backend_fail", "backend_hang", "tag_mismatch",
                "pool_stale", "worker_slow_start", "scale_stall",
                "chunk_lost", "reassembly_stall", "transfer_abort",
                "session_stall", "keystream_miss", "session_evict")

#: Scope names the ``@<scope>=<i>`` qualifier accepts: ``lane`` (serve
#: dispatch lanes), ``backend`` (the router's backend index), ``chunk``
#: (a transfer's chunk index, serve/transfer.py) and ``session`` (an
#: RC4 session id, serve/session.py).
SCOPES = ("lane", "backend", "chunk", "session")

#: Sentinel count for a bare (uncounted) token: armed forever.
ALWAYS = -1

#: point -> remaining firings (ALWAYS = unbounded). ``None`` until the
#: first fire()/reset() parses OT_FAULTS; ``{}`` thereafter when unset —
#: the steady-state no-op is one None-check + one ``not {}``.
_REGISTRY: dict[str, int] | None = None

#: point -> calls still to skip before the counted shots start firing
#: (the ``@<skip>`` grammar; absent = fire immediately).
_SKIPS: dict[str, int] = {}


def _trace():
    """our_tree_tpu.obs.trace, lazily, under its canonical dotted name
    (the fault -> trace bridge: every firing is an instant event, so a
    fault-matrix run's trace names what was injected). None when
    unloadable — tracing must never break the injection seam."""
    canonical = "our_tree_tpu.obs.trace"
    mod = sys.modules.get(canonical)
    if mod is None:
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                canonical, os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(
                        __file__))), "obs", "trace.py"))
            mod = importlib.util.module_from_spec(spec)
            sys.modules[canonical] = mod
            spec.loader.exec_module(mod)
        except Exception:
            sys.modules.pop(canonical, None)
            return None
    return mod


class InjectedFault(RuntimeError):
    """Raised by injection points when their fault fires.

    A subclass of RuntimeError so seams whose real failures are runtime
    errors (a failed ``make``, a failed dispatch) retry/fall back through
    the same handlers; sites that must tell an injected fault from a real
    one (e.g. bench.py's don't-mask-real-CPU-bugs guard) test the type
    explicitly.
    """


def scoped(point: str, lane) -> str:
    """The registry key of a lane-scoped point — what the ``@lane=<i>``
    grammar arms and what a per-lane seam must ask ``fire`` for
    (serve/lanes.py passes ``scoped("lane_hang", self.idx)``)."""
    return f"{point}@lane={int(lane)}"


def scoped_backend(point: str, backend) -> str:
    """The backend twin of ``scoped``: the registry key the
    ``@backend=<i>`` grammar arms and the router's backend-dispatch
    seam asks ``fire`` for (route/proxy.py) — so the chaos matrix can
    kill ONE backend's traffic and assert the others kept serving,
    exactly the lane story one level up."""
    return f"{point}@backend={int(backend)}"


def _scope_key(base: str, qual: str) -> str | None:
    """Canonical registry key for a ``<scope>=<i>`` qualifier, or None
    when the scope/index is malformed."""
    scope, sep, idx = qual.partition("=")
    if not sep or scope.strip() not in SCOPES:
        return None
    try:
        return f"{base.strip()}@{scope.strip()}={int(idx.strip())}"
    except ValueError:
        return None


def _normalize_lane(name: str, tok: str) -> str | None:
    """Canonicalize a ``<point>@<scope>=<i>`` name (bare-token form), or
    None when the scope qualifier is malformed."""
    base, _, qual = name.partition("@")
    return _scope_key(base, qual)


def _parse(spec: str) -> tuple[dict[str, int], dict[str, int]]:
    reg: dict[str, int] = {}
    skips: dict[str, int] = {}
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, count = tok.partition(":")
        name = name.strip()
        if sep:
            count, at, qual = count.partition("@")
            qual = qual.strip()
            try:
                n = int(count.strip())
                if at and "=" in qual:
                    # Scoped shot (@lane=/@backend=): the scope rides in
                    # the registry key, so two lanes' (or two backends')
                    # shots count independently.
                    key = _scope_key(name, qual)
                    if key is None:
                        raise ValueError(qual)
                    name = key
                elif at:  # last token's skip wins (skips don't accumulate)
                    skips[name] = max(int(qual), 0)
            except ValueError:
                print(f"# OT_FAULTS: malformed token {tok!r} ignored",
                      file=sys.stderr)
                continue
            if n <= 0:
                continue  # zero-count = disarmed, silently fine
        else:
            n = ALWAYS
            if "@" in name:
                canon = _normalize_lane(name, tok)
                if canon is None:
                    print(f"# OT_FAULTS: malformed token {tok!r} ignored",
                          file=sys.stderr)
                    continue
                name = canon
        if name.split("@", 1)[0] not in KNOWN_POINTS:
            print(f"# OT_FAULTS: unknown injection point {name!r} "
                  f"(known: {', '.join(KNOWN_POINTS)}) — armed anyway",
                  file=sys.stderr)
        # Repeated tokens accumulate (":2,x:1" == "x:3"); ALWAYS absorbs.
        prev = reg.get(name, 0)
        reg[name] = ALWAYS if ALWAYS in (prev, n) else prev + n
    return reg, {k: v for k, v in skips.items() if k in reg and v > 0}


def reset() -> None:
    """Re-parse OT_FAULTS (tests that set the env after import)."""
    global _REGISTRY
    _REGISTRY, skips = _parse(os.environ.get("OT_FAULTS", ""))
    _SKIPS.clear()
    _SKIPS.update(skips)


def active() -> bool:
    """True when any point is still armed (cheap post-parse)."""
    if _REGISTRY is None:
        reset()
    return bool(_REGISTRY)


def _take_shot(reg: dict, point: str, n: int) -> None:
    """The one counted-shot decrement (shared by fire/consume so the
    supervisor's metering pool can never desynchronize from in-process
    firing)."""
    if n != ALWAYS:
        if n == 1:
            del reg[point]
        else:
            reg[point] = n - 1


def fire(point: str) -> bool:
    """Consume one shot at `point`; True iff the fault fires now.

    The ONE call every injection point makes. Never raises, never sleeps;
    the point itself decides what its failure looks like (raise
    InjectedFault, return a busy marker, debit a deadline budget...).
    """
    global _REGISTRY
    reg = _REGISTRY
    if reg is None:
        reset()
        reg = _REGISTRY
    if not reg:
        return False
    n = reg.get(point, 0)
    if n == 0:
        return False
    skip = _SKIPS.get(point, 0)
    if skip:  # deferred shot (the @<skip> grammar): not yet
        _SKIPS[point] = skip - 1
        return False
    _take_shot(reg, point, n)
    t = _trace()
    if t is not None:
        t.point("fault-injected", point=point,
                left=("unbounded" if n == ALWAYS else n - 1))
    print(f"# OT_FAULTS: injecting {point} "
          f"({'unbounded' if n == ALWAYS else f'{n - 1} left'})",
          file=sys.stderr)
    return True


def check(point: str, detail: str = "") -> None:
    """Raise InjectedFault iff `point` fires — the common seam shape."""
    if fire(point):
        raise InjectedFault(f"injected fault: {point}"
                            + (f" ({detail})" if detail else ""))


def check_lane(point: str, lane, detail: str = "") -> None:
    """Raise InjectedFault iff the lane-scoped OR the plain form of
    `point` fires — the per-lane seam shape (serve/lanes.py): a token
    ``lane_fail:1@lane=2`` hits lane 2 and no other; a plain
    ``lane_fail:1`` hits whichever lane asks first. Short-circuits so
    one dispatch consumes at most one shot."""
    if fire(scoped(point, lane)) or fire(point):
        raise InjectedFault(f"injected fault: {scoped(point, lane)}"
                            + (f" ({detail})" if detail else ""))


def check_backend(point: str, backend, detail: str = "") -> None:
    """``check_lane`` for the router's per-backend seam: raise
    InjectedFault iff the backend-scoped OR the plain form of `point`
    fires. Short-circuits so one routed request consumes at most one
    shot (the ``check_lane`` contract, one fault domain up)."""
    if fire(scoped_backend(point, backend)) or fire(point):
        raise InjectedFault(f"injected fault: {scoped_backend(point, backend)}"
                            + (f" ({detail})" if detail else ""))


def fire_backend(point: str, backend) -> bool:
    """Consume the backend-scoped OR plain shot of `point`, without
    raising — for seams whose fault is not an exception (the router's
    ``backend_hang`` is an awaitable sleep, not a raise). Same
    short-circuit contract as ``check_backend``."""
    return fire(scoped_backend(point, backend)) or fire(point)


def scoped_chunk(point: str, chunk) -> str:
    """The transfer twin of ``scoped``: the registry key the
    ``@chunk=<i>`` grammar arms and the transfer engine's per-chunk
    seams ask ``fire`` for (serve/transfer.py) — so a chaos drive can
    lose ONE chunk of a multi-chunk transfer and assert the rest
    arrived exactly once."""
    return f"{point}@chunk={int(chunk)}"


def fire_chunk(point: str, chunk) -> bool:
    """Consume the chunk-scoped OR plain shot of `point`, without
    raising — the transfer seams' faults are flow decisions (discard a
    result, stall an emit, abort an exchange), not exceptions. Same
    short-circuit contract as ``fire_backend``."""
    return fire(scoped_chunk(point, chunk)) or fire(point)


def scoped_session(point: str, sid) -> str:
    """The session twin of ``scoped``: the registry key the
    ``@session=<i>`` grammar arms and the RC4 session engine's seams ask
    ``fire`` for (serve/session.py) — so a chaos drive can stall ONE
    session's prefetch or drop ONE session's keystream window and assert
    every other session streamed on undisturbed."""
    return f"{point}@session={int(sid)}"


def fire_session(point: str, sid) -> bool:
    """Consume the session-scoped OR plain shot of `point`, without
    raising — the session seams' faults are flow decisions (stall a
    refill, discard a cached window, evict a store row), not exceptions.
    Same short-circuit contract as ``fire_chunk``."""
    return fire(scoped_session(point, sid)) or fire(point)


def injected_slow(point: str, detail: str = "") -> bool:
    """Simulate a LATENCY regression when ``point`` (``dispatch_slow``)
    is armed: sleep ``OT_SLOW_S`` seconds (default 0.05) and return —
    the call still succeeds, it is just slow. The ``fire`` docstring's
    never-sleeps contract is about ``fire`` itself: the sleep is this
    injection point simulating its fault's cost, exactly like
    ``watchdog.injected_hang`` burning a deadline. A bare token slows
    every dispatch — the SLO-gate red rehearsal
    (``serve.bench --slo``); returns whether it fired."""
    if not fire(point):
        return False
    try:
        slow_s = max(float(os.environ.get("OT_SLOW_S", 0.05)), 0.0)
    except ValueError:
        slow_s = 0.05
    time.sleep(slow_s)
    return True


def consume(point: str) -> bool:
    """Take one shot at `point` WITHOUT it counting as an injection: no
    stderr note, no ``fault-injected`` trace event. For supervisors that
    METER shots into children (isolate._meter_faults) — the injection
    happens at the child's seam (and is traced there); the supervisor's
    consumption is bookkeeping, and recording it as a firing would
    double-count every metered fault in the run's injected-vs-observed
    ledger. Skips (the ``@`` grammar) are not consumed: metering hands
    children plain ``:1`` shots."""
    global _REGISTRY
    reg = _REGISTRY
    if reg is None:
        reset()
        reg = _REGISTRY
    n = reg.get(point, 0) if reg else 0
    if n == 0:
        return False
    _take_shot(reg, point, n)
    return True


def remaining(point: str) -> int:
    """Shots left at `point` (ALWAYS for unbounded, 0 when disarmed)."""
    if _REGISTRY is None:
        reset()
    return _REGISTRY.get(point, 0)


def armed() -> tuple[str, ...]:
    """Currently armed point names (a snapshot — safe to fire() while
    iterating). Supervisors that spawn children use this to METER faults
    instead of letting every child re-arm the full spec: each child
    spawn hands the child exactly one shot (``<point>:1``) per armed
    point — counted points debit the supervisor's pool (via
    ``consume``, so the metering is not itself recorded as an
    injection), bare points draw from an inexhaustible one. So
    ``dispatch_hang:1`` under ``--isolate`` means ONE hung child across
    the whole sweep, and a bare point means one firing per child
    attempt rather than fire-forever in every child
    (resilience/isolate.py:_meter_faults)."""
    if _REGISTRY is None:
        reset()
    return tuple(_REGISTRY)
