"""Process isolation: the shared child runner + the isolated-sweep
supervisor (``harness.bench --isolate``).

The watchdog (watchdog.py) can interrupt a hang only while the blocked
call releases the GIL and only in the main thread; a dispatch wedged
inside native code — the pathology that actually wedges PJRT tunnels —
is unkillable from inside its own process. The only defense that always
works is the one the recovery watcher already uses for whole plans:
run the risky work in a CHILD process, give it a deadline, and SIGKILL
the whole process group when the deadline expires. This module makes
that pattern a primitive instead of four hand-rolled copies
(scripts/tune_tpu.py, scripts/bitslice_tpu_repro.py, the since-retired
scripts/e2e_decompose.py, and now the sweep itself):

* ``run_child`` — run an argv with a wall deadline in its own session,
  SIGKILL the process GROUP on expiry (several callers' children are
  themselves parents of jax subprocesses; killing only the child would
  orphan a grandchild that keeps driving the device), classify the
  outcome (``ok`` / ``timeout`` / ``crash``), and optionally retry
  through the shared ``RetryPolicy`` — attempts, backoff, and
  exhaustion live in ONE place.

* ``run_streamed`` — the same deadline/group-kill contract with merged
  stdout+stderr streamed live into a caller-owned sink instead of
  captured: the shape of a multi-hour plan step whose partial log tail
  is the evidence of where a wedge hit (scripts/recover_watch.py, the
  last pre-isolate supervisor, runs on it).

* ``run_isolated_sweep`` — the ``--isolate`` mode's supervisor: each
  sweep unit runs in a child process (the child targets exactly one
  unit and appends it to the shared journal itself), hangs are
  SIGKILLed at the unit deadline, failures are recorded as journal
  failure rows, and a unit that fails ``quarantine_after`` times is
  QUARANTINED: skipped now and on every later resume, with
  ``quarantined:<unit>`` stamped through the degrade() chokepoint —
  a sweep always terminates and never re-burns its budget on a
  known-bad config. The parent re-emits completed units' lines from
  the journal (the child's stdout is quarantined with it), so the
  surviving corpus is byte-identical to a healthy run's rows.

Stdlib-only and free of intra-package imports (bare-loadable by the
jax-free sweep parents via scripts/_devlock_loader.py); siblings load
lazily under their canonical dotted names.
"""

from __future__ import annotations

import contextlib
import os
import signal
import subprocess
import sys
import time

_null_cm = contextlib.nullcontext


def _sibling(name: str):
    """resilience/<name>.py under its canonical dotted name (see
    watchdog._sibling — same pattern, kept local so either module is
    bare-loadable on its own)."""
    canonical = f"our_tree_tpu.resilience.{name}"
    mod = sys.modules.get(canonical)
    if mod is None:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            canonical,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[canonical] = mod
        try:
            spec.loader.exec_module(mod)
        except BaseException:
            sys.modules.pop(canonical, None)
            raise
    return mod


def _trace():
    """our_tree_tpu.obs.trace, lazily, under its canonical dotted name
    (the child-lifecycle -> trace bridge; same bare-load pattern as
    _sibling, different package). None when unloadable — tracing must
    never break isolation."""
    canonical = "our_tree_tpu.obs.trace"
    mod = sys.modules.get(canonical)
    if mod is None:
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                canonical, os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(
                        __file__))), "obs", "trace.py"))
            mod = importlib.util.module_from_spec(spec)
            sys.modules[canonical] = mod
            spec.loader.exec_module(mod)
        except Exception:
            sys.modules.pop(canonical, None)
            return None
    return mod


def _meter_faults(base_env: dict) -> dict:
    """Meter this process's armed faults into ONE child's environment.

    Children re-parse OT_FAULTS independently (the faults contract), so
    an unmetered ``dispatch_hang:1`` would hang EVERY child's first
    dispatch — "one wedged unit among healthy ones", the scenario the
    quarantine ledger exists for, would be unrehearsable. Instead the
    supervisor holds the process-wide counters: each spawn consumes one
    shot per armed counted point and hands the child exactly that shot.

    Bare (fire-forever) points are metered the same way (ROADMAP
    follow-up): each spawn hands the child ONE shot (``<point>:1``) from
    the supervisor's inexhaustible pool, instead of forwarding the bare
    token — which the child would re-parse as fire-forever and fault on
    EVERY call of every seam. Under ``--isolate`` a bare point therefore
    means "one firing per child attempt": each child rehearses its
    single-fault recovery path (a ``build_fail`` retries and builds, a
    ``dispatch_fail`` falls back once) rather than every child drowning
    in unbounded failures. With OT_FAULTS unset or exhausted the child
    env carries no armed points.
    """
    if not base_env.get("OT_FAULTS"):
        return base_env
    faults = _sibling("faults")
    tokens = []
    for point in faults.armed():
        # consume(), not fire(): the supervisor's metering is
        # bookkeeping — the injection itself happens (and is traced) at
        # the child's seam.
        if faults.remaining(point) == faults.ALWAYS or faults.consume(point):
            tokens.append(f"{point}:1")
    env = dict(base_env)
    env["OT_FAULTS"] = ",".join(tokens)
    return env


class ChildResult:
    """One child run's classified outcome.

    ``kind`` is ``"ok"`` (exit 0), ``"crash"`` (any other exit, signal
    deaths included — ``rc`` is then negative, the POSIX convention), or
    ``"timeout"`` (deadline expired; the group was SIGKILLed; ``rc`` is
    whatever the reaped process reported, typically -9). ``out``/``err``
    are captured text ("" when ``capture=False``); ``wall_s`` the
    attempt's wall clock.
    """

    __slots__ = ("kind", "rc", "out", "err", "wall_s")

    def __init__(self, kind: str, rc, out: str, err: str, wall_s: float):
        self.kind, self.rc = kind, rc
        self.out, self.err, self.wall_s = out, err, wall_s

    @property
    def ok(self) -> bool:
        return self.kind == "ok"

    def __repr__(self):
        return (f"ChildResult({self.kind!r}, rc={self.rc}, "
                f"wall_s={self.wall_s:.1f})")


def _kill_group(proc) -> None:
    """SIGKILL the child's whole session (it was started as a session
    leader); fall back to the single process if the group is gone."""
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (OSError, AttributeError):
        try:
            proc.kill()
        except OSError:
            pass


def _attempt(argv, timeout_s, env, cwd, capture) -> ChildResult:
    t0 = time.monotonic()
    pipe = subprocess.PIPE if capture else None
    proc = subprocess.Popen(argv, env=env, cwd=cwd, stdout=pipe, stderr=pipe,
                            text=True, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        # Reap + drain whatever the child managed to write before dying;
        # partial stderr is often the only evidence of WHERE it hung.
        out, err = proc.communicate()
        return ChildResult("timeout", proc.returncode, out or "", err or "",
                           time.monotonic() - t0)
    kind = "ok" if proc.returncode == 0 else "crash"
    return ChildResult(kind, proc.returncode, out or "", err or "",
                       time.monotonic() - t0)


class ServiceChild:
    """A LONG-RUNNING child started by ``spawn_service`` — the third
    shape of child process next to ``run_child`` (run-to-completion)
    and ``run_isolated_sweep`` (supervised units): a service that is
    *meant* to outlive the call, e.g. an ot-serve backend worker behind
    the router (route/bench.py). The handle owns the lifecycle:

    * ``read_line(deadline_s)`` — one stdout line with a wall deadline
      (the worker's READY line carries its bound ports); never blocks
      past the deadline even if the child wedges before printing.
    * ``stop(term_deadline_s)`` — graceful-then-forceful: SIGTERM to the
      child's session (the drain signal), wait up to the deadline for a
      clean exit, SIGKILL the whole group on expiry (the same
      group-kill ``run_child`` uses — a wedged worker may have jax
      subprocesses of its own). Returns the exit rc (negative = signal
      death, POSIX convention).

    The child runs in its own session (``start_new_session``) so the
    group kill can never reach the caller, and stdout/stderr are piped —
    the service's output is evidence, read deliberately, not interleaved
    with the supervisor's.
    """

    __slots__ = ("name", "proc", "_buf")

    def __init__(self, name: str, proc):
        self.name = name
        self.proc = proc
        self._buf = b""

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def read_line(self, deadline_s: float) -> str | None:
        """The next stdout line within ``deadline_s`` wall seconds, or
        None on deadline/EOF — a select() loop over the pipe, because a
        blocking readline() on a child that hangs before printing would
        turn the spawner into the hang it exists to bound."""
        import select

        fd = self.proc.stdout.fileno()
        end = time.monotonic() + max(deadline_s, 0.0)
        while b"\n" not in self._buf:
            left = end - time.monotonic()
            if left <= 0:
                return None
            ready, _, _ = select.select([fd], [], [], min(left, 0.25))
            if not ready:
                continue
            chunk = os.read(fd, 65536)
            if not chunk:  # EOF: the child died before its line
                return None
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return line.decode("utf-8", "replace")

    def stop(self, term_deadline_s: float = 30.0) -> int:
        """SIGTERM the session, await a graceful exit, SIGKILL the group
        past the deadline; reaps and returns the exit rc."""
        if self.proc.poll() is None:
            try:
                os.killpg(self.proc.pid, signal.SIGTERM)
            except (OSError, AttributeError):
                try:
                    self.proc.terminate()
                except OSError:
                    pass
            try:
                self.proc.wait(timeout=max(term_deadline_s, 0.0))
            except subprocess.TimeoutExpired:
                _kill_group(self.proc)
                self.proc.wait()
        tr = _trace()
        if tr is not None:
            tr.point("service-stopped", label=self.name,
                     rc=self.proc.returncode)
        return self.proc.returncode

    def kill(self) -> int:
        """SIGKILL the whole group NOW — no drain signal first (the
        chaos-drive path: a process that vanishes mid-frame, not one
        asked to leave; ``stop(0.0)`` still sends the SIGTERM courtesy
        shot). Reaps and returns the rc (negative, POSIX convention)."""
        if self.proc.poll() is None:
            _kill_group(self.proc)
            self.proc.wait()
        tr = _trace()
        if tr is not None:
            tr.point("service-killed", label=self.name,
                     rc=self.proc.returncode)
        return self.proc.returncode

    def drain_output(self) -> tuple[str, str]:
        """Whatever stdout/stderr remain after exit (including any
        buffered ready-line tail) — call only once the child is dead."""
        out, err = b"", b""
        try:
            o, e = self.proc.communicate(timeout=5)
            out, err = o or b"", e or b""
        except (ValueError, OSError, subprocess.TimeoutExpired):
            pass
        return ((self._buf + out).decode("utf-8", "replace"),
                err.decode("utf-8", "replace"))


def spawn_service(argv, *, env=None, cwd=None, name: str = "") -> ServiceChild:
    """Start ``argv`` as a long-running service child in its own
    session, stdout/stderr piped; returns the ``ServiceChild`` handle.
    The spawn is traced (``service-spawned``) and the trace run id is
    handed down via ``child_env`` so the service's spans join the
    caller's merged run — same stitch as ``run_child``."""
    tr = _trace()
    cenv = dict(env if env is not None else os.environ)
    if tr is not None:
        cenv = tr.child_env(cenv)
    proc = subprocess.Popen(argv, env=cenv, cwd=cwd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=False,
                            start_new_session=True)
    if tr is not None:
        tr.point("service-spawned",
                 label=name or os.path.basename(str(argv[0])), pid=proc.pid)
    return ServiceChild(name or os.path.basename(str(argv[0])), proc)


def run_streamed(argv, timeout_s: float | None = None, *, env=None,
                 cwd=None, sink=None, name: str = "") -> ChildResult:
    """Run ``argv`` with a wall deadline, STREAMING merged stdout+stderr
    into ``sink`` (an open writable file object) as the child produces
    it — the fourth child shape next to ``run_child`` (capture, read
    after exit), ``spawn_service`` (piped, read deliberately), and the
    sweep supervisor: a run-to-completion step whose output is the
    operator's live log, e.g. a multi-hour hardware plan step
    (scripts/recover_watch.py) whose partial tail is the only evidence
    of where a wedge hit. Same session/group-kill semantics as
    ``run_child``: the child leads its own session and the whole group
    is SIGKILLed at the deadline (plan steps parent jax subprocesses of
    their own — killing only the step would orphan a grandchild that
    keeps driving the device). ``out``/``err`` on the returned
    ``ChildResult`` are always "" — the sink holds the output. With
    ``sink=None`` the child inherits the caller's stdio (stream to the
    terminal)."""
    tr = _trace()
    cenv = dict(env if env is not None else os.environ)
    if tr is not None:
        cenv = tr.child_env(cenv)
    t0 = time.monotonic()
    with (tr.span("child", label=name or os.path.basename(str(argv[0])),
                  streamed=1)
          if tr is not None else _null_cm()):
        proc = subprocess.Popen(
            argv, env=cenv, cwd=cwd, stdout=sink,
            stderr=subprocess.STDOUT if sink is not None else None,
            start_new_session=True)
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            _kill_group(proc)
            proc.wait()
            if tr is not None:
                tr.point("child-killed", label=name,
                         wall_s=round(time.monotonic() - t0, 3))
            return ChildResult("timeout", proc.returncode, "", "",
                               time.monotonic() - t0)
    return ChildResult("ok" if rc == 0 else "crash", rc, "", "",
                       time.monotonic() - t0)


def run_child(argv, timeout_s: float | None = None, *, env=None, cwd=None,
              capture: bool = True, attempts: int = 1,
              base_delay_s: float = 0.0, name: str = "",
              log=None) -> ChildResult:
    """Run ``argv`` in its own session with a wall deadline; retry
    non-``ok`` outcomes through the shared RetryPolicy.

    Always returns the LAST attempt's ``ChildResult`` (never raises for
    child failures — classification is the caller's data, same contract
    as the hand-rolled loops this replaces). ``attempts=1`` is a plain
    deadline-guarded run. ``log(attempt, exc)`` is the policy's
    per-failure observer; the exception's message carries the kind.
    """
    policy = _sibling("policy")
    tr = _trace()
    last: dict = {}

    class _ChildFailed(Exception):
        pass

    def op(attempt):
        if tr is None:
            r = _attempt(argv, timeout_s, env, cwd, capture)
        else:
            # The child span is the cross-process stitch: child_env
            # hands its id down via OT_TRACE_PARENT, so the subprocess's
            # own root spans nest under this attempt in the merged run.
            with tr.span("child",
                         label=name or os.path.basename(str(argv[0])),
                         attempt=attempt.index):
                cenv = tr.child_env(dict(env if env is not None
                                         else os.environ))
                r = _attempt(argv, timeout_s, cenv, cwd, capture)
                if r.kind == "timeout":
                    tr.point("child-killed", label=name,
                             wall_s=round(r.wall_s, 3))
        last["r"] = r
        if not r.ok:
            raise _ChildFailed(f"{r.kind} (rc={r.rc})")
        return r

    return policy.RetryPolicy(
        attempts=max(attempts, 1), base_delay_s=base_delay_s,
        retry_on=(_ChildFailed,), log=log,
        on_exhausted=lambda e: last["r"],
        name=name or f"run_child:{os.path.basename(str(argv[0]))}",
    ).run(op)


def run_isolated_sweep(*, units, child_argv, journal_path: str, config: dict,
                       emit, unit_deadline_s: float, quarantine_after: int,
                       env=None, cwd=None, log=None) -> list[str]:
    """Supervise one sweep, one child process per unit attempt.

    ``units`` is the ordered unit-name list (the journal's replay
    contract: a pure function of ``config``); ``child_argv(unit)``
    builds the argv of a child that replays the journal, runs exactly
    that unit, appends it to the journal itself, and exits.
    ``emit(line)`` is the parent's result emitter (stdout + --out);
    completed units' lines are re-emitted from the journal whether they
    completed in this run's child or a previous run's.

    Per unit: spawn, deadline, SIGKILL on expiry, record a failure row
    on any non-completion; after ``quarantine_after`` recorded failures
    (across runs — the journal is the ledger) the unit is quarantined:
    skipped with ``quarantined:<unit>`` stamped through degrade().
    Returns the quarantined unit names, in sweep order.
    """
    journal_mod = _sibling("journal")
    degr = _sibling("degrade")
    tr = _trace()
    note = log or (lambda msg: print(msg, file=sys.stderr, flush=True))
    journal = journal_mod.SweepJournal(journal_path, config)
    if journal.pending:
        note(f"# journal: {journal.pending} completed unit(s) on file "
             f"({journal_path}); resuming")
    quarantined: list[str] = []

    def emit_entry(entry: dict) -> None:
        for line in entry.get("lines", []):
            emit(line)
        for kind in entry.get("degraded", []):
            degr.degrade(kind, "restored from journal")

    def consume(name: str) -> bool:
        """take+emit `name` iff its completed record is replayable.

        ``take()`` consumes by NAME, not replay order: the supervisor
        only re-emits recorded lines (no RNG state is restored here),
        and out-of-order completion is routine for it — a
        quarantine-released or failed-then-retried unit completes
        after its successors' records are already on file. The
        in-process resume path keeps the strict-order ``skip()``
        (there the RNG stream makes order the contract).
        """
        if not journal.is_completed(name):
            return False
        entry = journal.take(name)
        if entry is None:
            return False
        emit_entry(entry)
        if tr is not None:
            tr.point("unit-replayed", unit=name)
        return True

    try:
        for name in units:
            if consume(name):
                continue
            attempt_no = 0
            while (journal.fail_count(name) < quarantine_after
                   and not journal.is_completed(name)):
                n_prev = journal.fail_count(name)
                attempt_no += 1
                # The unit-attempt span is the supervisor's per-unit
                # wall clock (spawn through reap/kill); run_child's own
                # child span nests inside it, and the subprocess's spans
                # nest under THAT via OT_TRACE_PARENT.
                with (tr.span("unit-attempt", unit=name,
                              attempt=attempt_no)
                      if tr is not None else _null_cm()):
                    r = run_child(child_argv(name), unit_deadline_s,
                                  env=_meter_faults(
                                      dict(env if env is not None
                                           else os.environ)),
                                  cwd=cwd, name=f"isolate:{name}")
                journal.reload_tail()
                if journal.is_completed(name):
                    break
                reason = (f"timeout:{unit_deadline_s:.0f}s"
                          if r.kind == "timeout" else f"crash:rc={r.rc}")
                journal.record_failure(name, reason)
                if tr is not None:
                    tr.point("unit-failed", unit=name, reason=reason,
                             attempt=attempt_no)
                tail = r.err.strip().splitlines()[-3:]
                note(f"# isolate: unit {name} failed "
                     f"({reason}; failure {n_prev + 1}/{quarantine_after})"
                     + (": " + " | ".join(tail) if tail else ""))
            if not consume(name):
                if journal.fail_count(name) >= quarantine_after:
                    quarantined.append(name)
                    if tr is not None:
                        tr.point("quarantine", unit=name,
                                 fails=journal.fail_count(name))
                    degr.degrade(
                        f"quarantined:{name}",
                        f"{journal.fail_count(name)} recorded failure(s); "
                        "skipping on this and every resumed run")
                else:
                    # Defensive corner: the unit completed but its record
                    # was distrusted by an order-mismatch truncation. The
                    # work happened; only the re-emission is lost. Say so
                    # rather than mislabeling it quarantined.
                    note(f"# isolate: unit {name} completed but its "
                         "journal record was distrusted; rows not "
                         "re-emitted")
        if journal.resumed:
            note(f"# journal: skipped {journal.resumed} completed unit(s)")
    finally:
        journal.close()
    return quarantined
