"""Dispatch watchdog: a monitor-thread deadline around any device call.

PR 1's resilience core protects the *seams around* device work — init,
build, lock — but a wedged XLA/Pallas dispatch still hangs the whole
process from the inside: `block_until_ready` on a dead tunnel never
returns, the deadline checks between stages never run, and nothing can
even say where the process was stuck. This module is the in-process
answer (the out-of-process one is ``isolate.py``):

``deadline(seconds, what=...)`` arms a daemon monitor thread that waits
on an Event. If the guarded block finishes first, the monitor is
cancelled and the cost was one Event + one thread. If the deadline
expires first, the monitor — which is NOT blocked, that is the point of
a second thread —

1. dumps **all-thread stacks** to a crash-report file
   (``OT_CRASH_DIR``, default ``/tmp/ot_crash``), so a hang leaves
   evidence of *where* every thread was, not just that it happened;
2. interrupts the main thread with ``DispatchTimeout``, recording the
   demotion through the shared ``degrade()`` chokepoint (kind
   ``dispatch-timeout``) as the exception is raised — ledger stamp and
   exception appear together or not at all, so a block that completes
   exactly at the deadline edge is never marked degraded — and the
   bench JSON line / sweep journal of whatever survives carries the
   fact.

The interruption rides the same mechanism as bench.py's stage alarm: a
signal handler raising in the main thread, which works exactly when the
blocking call releases the GIL (PJRT readbacks, ``time.sleep``,
subprocess waits do; a C loop that holds the GIL does not — that class
of hang is what process isolation exists for). Off the main thread, or
on platforms without SIGALRM, the guard degrades to dump-and-record:
the stacks and the degradation ledger still happen, only the raise
cannot.

``DispatchTimeout`` subclasses ``TimeoutError`` on purpose: every
existing stage-alarm handler (bench.py's fallback chains) catches
``TimeoutError``, and the watchdog must slot into those paths without
each one learning a new type.

``injected_hang(point)`` is the fault side of the same seam: when the
named ``OT_FAULTS`` point (``dispatch_hang``) is armed it sleeps
"forever" (OT_HANG_S, default 24 h) — a GIL-releasing stand-in for a
wedged dispatch that the watchdog can interrupt and a supervising
parent can SIGKILL, so the whole layer is exercisable on CPU in CI.

Stdlib-only and free of intra-package imports (bare-loadable — see the
package docstring); the sibling degrade/faults modules are loaded
lazily under their canonical dotted names so the ledger and fault
counters stay one-per-process across bare and package import contexts.
"""

from __future__ import annotations

import contextlib
import os
import signal
import sys
import threading
import time
import traceback


class DispatchTimeout(TimeoutError):
    """A guarded device call exceeded its watchdog deadline.

    ``what`` names the guarded call; ``report`` is the crash-report path
    (None when the dump itself failed — the raise still happens).
    """

    def __init__(self, what: str, seconds: float, report: str | None):
        self.what, self.seconds, self.report = what, seconds, report
        super().__init__(
            f"{what} exceeded its {seconds:.0f}s watchdog deadline"
            + (f" (stacks: {report})" if report else ""))


def _sibling(name: str):
    """resilience/<name>.py under its canonical dotted name, without an
    intra-package import (same pattern as utils/devlock.py:_faults)."""
    canonical = f"our_tree_tpu.resilience.{name}"
    mod = sys.modules.get(canonical)
    if mod is None:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            canonical,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[canonical] = mod
        try:
            spec.loader.exec_module(mod)
        except BaseException:
            sys.modules.pop(canonical, None)
            raise
    return mod


def _trace():
    """our_tree_tpu.obs.trace, lazily, under its canonical dotted name
    (the watchdog -> trace bridge: arm and expiry become instant
    events). None when unloadable — tracing must never break the
    watchdog; same bare-load pattern as _sibling, different package."""
    canonical = "our_tree_tpu.obs.trace"
    mod = sys.modules.get(canonical)
    if mod is None:
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                canonical, os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(
                        __file__))), "obs", "trace.py"))
            mod = importlib.util.module_from_spec(spec)
            sys.modules[canonical] = mod
            spec.loader.exec_module(mod)
        except Exception:
            sys.modules.pop(canonical, None)
            return None
    return mod


def crash_dir() -> str:
    return os.environ.get("OT_CRASH_DIR", "/tmp/ot_crash")


def default_deadline_s() -> float:
    """The opt-in global dispatch deadline (OT_DISPATCH_DEADLINE, seconds).

    0 / unset = disabled: the watchdog costs nothing unless a caller or
    the environment asks for it. Callers that take an explicit deadline
    flag use this as the flag's default so one env var arms every seam.
    """
    try:
        return max(float(os.environ.get("OT_DISPATCH_DEADLINE", 0) or 0), 0.0)
    except ValueError:
        return 0.0


def current_stacks(depth: int | None = None) -> dict:
    """{thread ident: (name, [compact frame strings, leaf first])} — the
    all-thread frame walk behind the expiry dump, shared with the
    profiler's stack-sampling tier (obs/profiler.py): one machinery for
    "where is every thread right now", whether the question is a hang's
    post-mortem or a capture window's sample."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: dict = {}
    for ident, frame in sys._current_frames().items():
        frames = []
        f = frame
        while f is not None and (depth is None or len(frames) < depth):
            co = f.f_code
            frames.append(f"{co.co_name} "
                          f"({os.path.basename(co.co_filename)}:"
                          f"{f.f_lineno})")
            f = f.f_back
        out[ident] = (names.get(ident, "?"), frames)
    return out


def dump_stacks(what: str, seconds: float) -> str | None:
    """Write every thread's current stack to a crash-report file.

    Returns the path, or None when nothing could be written (an
    unwritable crash dir must not turn the watchdog's raise into a
    second, stranger failure). ``sys._current_frames`` over
    ``faulthandler`` because the report should carry thread NAMES —
    "which thread is the PJRT callback" is half the diagnosis.
    """
    try:
        d = crash_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"watchdog-{os.getpid()}-{int(time.time())}.txt")
        names = {t.ident: t.name for t in threading.enumerate()}
        with open(path, "w") as fh:
            fh.write(f"# watchdog: {what!r} exceeded {seconds:.0f}s "
                     f"(pid {os.getpid()}, "
                     f"{time.strftime('%Y-%m-%dT%H:%M:%S%z')})\n")
            for ident, frame in sorted(sys._current_frames().items()):
                fh.write(f"\n## thread {names.get(ident, '?')} "
                         f"(ident {ident})\n")
                fh.write("".join(traceback.format_stack(frame)))
        return path
    except OSError:
        return None


class _Scheduler:
    """ONE persistent daemon thread multiplexing every armed deadline.

    The original design spawned (and joined) a monitor thread per
    guarded block — correct, but thread spawn is ~0.5 ms on the
    sandboxed hosts the serve path now runs hot on, and the serve lane
    arms a deadline around EVERY dispatch: at fast-engine batch rates
    the spawn alone would eat the latency budget (docs/PERF.md, the
    serve-vs-offline gap). Arming is now a dict insert + condvar notify
    on a long-lived worker; disarming is a pop. The worker sleeps until
    the earliest armed expiry, hands the entry's callback (stack dump +
    SIGALRM delivery — unchanged semantics) to a short-lived fire
    thread, and goes back to sleep; with nothing armed it parks on the
    condvar. An entry popped by
    ``disarm`` before the worker reaches it never fires — the same
    stand-down race the per-thread Event gave (completion exactly at
    the edge may still see the signal; the handler is only installed
    while the block runs, exactly as before).
    """

    def __init__(self):
        self._cv = threading.Condition()
        self._entries: dict = {}  # id -> (monotonic expiry, fire())
        self._seq = 0
        self._thread = None

    def arm(self, seconds: float, fire) -> int:
        with self._cv:
            self._seq += 1
            eid = self._seq
            self._entries[eid] = (time.monotonic() + seconds, fire)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="ot-watchdog")
                self._thread.start()
            self._cv.notify()
        return eid

    def disarm(self, eid: int) -> None:
        with self._cv:
            self._entries.pop(eid, None)
            self._cv.notify()

    def _run(self):
        while True:
            with self._cv:
                if not self._entries:
                    self._cv.wait()
                    continue
                now = time.monotonic()
                nxt = min(t for t, _ in self._entries.values())
                if nxt > now:
                    self._cv.wait(nxt - now)
                    continue
                due = [eid for eid, (t, _) in self._entries.items()
                       if t <= now]
                fires = [self._entries.pop(eid)[1] for eid in due]
            # Each expiry fires on its OWN short-lived thread: fire()
            # does I/O (dump_stacks), and a dump wedged on a full pipe
            # or hung filesystem must only disable ITS deadline, not
            # every armed guard in the process. Spawn cost lands on the
            # rare expiry path; arming stays a dict insert.
            for fire in fires:
                threading.Thread(target=self._fire_one, args=(fire,),
                                 daemon=True,
                                 name="ot-watchdog-fire").start()

    @staticmethod
    def _fire_one(fire):
        try:
            fire()
        except Exception:  # noqa: BLE001 - never kill the fire thread's
            pass           # siblings or leak into threading excepthook


_SCHEDULER = _Scheduler()

#: thread ident -> kill hook (``thread_kill_hook``). A deadline armed on
#: a thread with a registered hook delivers its expiry BY CALLING the
#: hook with the built ``DispatchTimeout`` instead of the main-thread
#: SIGALRM raise — the worker-thread watchdog contract (serve's lane
#: executors): the waiter holding the unit's future is unblocked at the
#: deadline while the wedged thread itself is abandoned as evidence.
_THREAD_KILLS: dict[int, object] = {}


@contextlib.contextmanager
def thread_kill_hook(hook):
    """Register ``hook(exc)`` as THIS thread's watchdog kill path.

    While registered, any ``deadline`` armed on this thread that expires
    calls ``hook(DispatchTimeout(...))`` from the expiry thread (after
    the stack dump and the degrade stamp) — the off-main twin of the
    SIGALRM delivery. The hook must be quick and must not raise into the
    guarded call's thread (it runs on the watchdog's fire thread):
    serve's lane executor uses it to fail the dispatch future and
    abandon the wedged worker. Nests: the previous hook is restored on
    exit (the innermost registration owns deadlines armed inside it).
    """
    ident = threading.get_ident()
    prev = _THREAD_KILLS.get(ident)
    _THREAD_KILLS[ident] = hook
    try:
        yield
    finally:
        if prev is None:
            _THREAD_KILLS.pop(ident, None)
        else:
            _THREAD_KILLS[ident] = prev


@contextlib.contextmanager
def deadline(seconds: float | None, what: str = "device dispatch",
             degrade_kind: str = "dispatch-timeout"):
    """Guard a block with a monitor-thread deadline.

    ``seconds`` None or <= 0 disarms the guard entirely (the common
    production case: OT_DISPATCH_DEADLINE unset). On expiry: stacks are
    dumped, ``degrade(degrade_kind, ...)`` is recorded, and
    ``DispatchTimeout`` is raised in the main thread via a temporarily
    installed SIGALRM handler (see module docstring for the off-main /
    no-SIGALRM degradation). Nesting: the guard saves and restores the
    previous SIGALRM disposition, so it composes with bench.py's stage
    alarm as long as the scopes nest properly — but prefer ONE deadline
    per region; the innermost armed one wins the signal. Monitoring
    rides the process-wide ``_Scheduler`` worker — arming costs a dict
    insert, not a thread spawn (the serve fast path arms one per
    dispatch).
    """
    if not seconds or seconds <= 0:
        yield
        return
    t = _trace()
    if t is not None:
        t.point("watchdog-arm", what=what, seconds=seconds)
    on_main = (threading.current_thread() is threading.main_thread()
               and hasattr(signal, "SIGALRM"))
    # Captured at ARM time: the hook registered for the arming thread
    # (serve's lane-executor worker), if any — the expiry delivery path
    # when SIGALRM-to-main cannot reach the guarded call.
    kill_hook = (None if on_main
                 else _THREAD_KILLS.get(threading.get_ident()))
    fired: dict = {}
    done = threading.Event()
    # Serialises the kill decision against handler restore: the signal
    # may only be sent while our handler is still installed. The dump
    # stays OUTSIDE the gate — it is the slow I/O, and the completing
    # main thread must not wait out a wedged filesystem in its finally.
    gate = threading.Lock()

    def fire():
        if done.is_set():  # completed exactly at the edge: stand down
            return
        fired["report"] = dump_stacks(what, seconds)
        with gate:
            if done.is_set():
                return
            if on_main:
                # Deliver to the Python-level handler (which runs in
                # the main thread) — this is what interrupts a
                # GIL-releasing blocking call.
                try:
                    signal.pthread_kill(threading.main_thread().ident,
                                        signal.SIGALRM)
                except (OSError, RuntimeError):
                    pass
            elif kill_hook is not None:
                # Worker-thread delivery: the wedged call cannot be
                # interrupted, but its WAITER can be unblocked — hand
                # the built timeout (degrade stamp + trace point ride
                # it, same as the raise path) to the registered hook.
                fired["delivered"] = True
                try:
                    kill_hook(_record_and_build())
                except Exception:  # noqa: BLE001 - the hook is not ours
                    pass

    def _record_and_build():
        # The degrade stamp rides the RAISE, not the monitor: a block
        # that completes at ~the deadline while the monitor is mid-fire
        # must not end up permanently marked degraded in a run that
        # never saw a timeout (the ledger's masquerade guarantee,
        # inverted). The stack dump may still be written — a harmless
        # diagnostic file — but the ledger and the exception appear
        # together or not at all.
        _sibling("degrade").degrade(
            degrade_kind,
            f"{what} exceeded {seconds:.0f}s watchdog deadline")
        tt = _trace()
        if tt is not None:
            tt.point("watchdog-expired", what=what, seconds=seconds,
                     report=fired.get("report"))
        return DispatchTimeout(what, seconds, fired.get("report"))

    old = None
    if on_main:
        def handler(signum, frame):
            raise _record_and_build()

        old = signal.signal(signal.SIGALRM, handler)
    eid = _SCHEDULER.arm(seconds, fire)
    try:
        yield
        # A hang the guard could NOT interrupt (off-main, GIL-held) that
        # nevertheless returned after expiry: surface the miss rather
        # than silently continuing past an expired deadline. When the
        # kill hook already DELIVERED the built timeout (the lane
        # executor failed the dispatch future at the deadline), the
        # degrade stamp and trace point are already on record — the
        # late-waking worker re-raises without stamping twice.
        if "report" in fired and not on_main:
            if fired.get("delivered"):
                raise DispatchTimeout(what, seconds, fired.get("report"))
            raise _record_and_build()
    finally:
        try:
            done.set()
            _SCHEDULER.disarm(eid)
            # Wait out a fire() already past its done check: once the
            # gate is free, any in-flight kill has been SENT (pending
            # on our still-installed handler — the documented
            # completed-at-the-edge raise) and any later fire stands
            # down inside the gate. Without this, pthread_kill could
            # land AFTER the restore below — on SIG_DFL for the
            # outermost guard, which terminates the process on a
            # dispatch that actually succeeded.
            with gate:
                pass
        finally:
            if old is not None:
                signal.signal(signal.SIGALRM, old)


#: Injected hangs fired so far in this process. Callers that must tell
#: a rehearsed hang from a real one (repo-root bench.py's don't-mask-
#: real-CPU-bugs guard: a DispatchTimeout that interrupted an INJECTED
#: sleep is exempt from the raise-on-cpu rule) read ``hangs_injected``.
_INJECTED_HANGS = 0


def hangs_injected() -> int:
    return _INJECTED_HANGS


def injected_hang(point: str, detail: str = "", budget=None) -> bool:
    """Simulate a wedged dispatch when the ``point`` fault is armed.

    Fires one shot at ``point`` (``dispatch_hang``); when it fires,
    either sleeps OT_HANG_S seconds (default 24 h — "forever" at sweep
    scale; a GIL-releasing sleep, so the watchdog can interrupt it and a
    parent can SIGKILL it) or, when a ``policy.Budget`` is passed,
    debits the hang's cost from it WITHOUT sleeping — the same
    no-wall-clock rehearsal bench.py's ``_burn`` gives init_hang.
    No-op while the point is unarmed: one dict lookup.

    Returns whether the hang fired, so a seam consulting BOTH a
    lane-scoped and a plain form of the same point (serve/lanes.py) can
    short-circuit — one dispatch consumes at most one shot, the same
    contract as ``faults.check_lane``.
    """
    if not _sibling("faults").fire(point):
        return False
    global _INJECTED_HANGS
    _INJECTED_HANGS += 1
    hang_s = float(os.environ.get("OT_HANG_S", 24 * 3600))
    if budget is not None:
        budget.debit(hang_s)
        return True
    print(f"# OT_FAULTS: {point} sleeping {hang_s:.0f}s"
          + (f" ({detail})" if detail else ""), file=sys.stderr, flush=True)
    time.sleep(hang_s)
    return True
