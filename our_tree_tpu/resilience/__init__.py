"""Shared resilience layer: retry policy, fault injection, journal, degrade.

The repo grew three disjoint ad-hoc defenses against real failures (wedged
PJRT tunnels, >1 h init hangs, SIGKILLed sweeps — utils/devlock.py,
bench.py:_ensure_live_backend, scripts/recover_watch.py) with no shared
policy and no way to exercise any of them in CI without a genuinely broken
device. This package is the shared layer:

* ``policy``  — the one retry/backoff/deadline primitive (attempts,
  exponential backoff with deterministic jitter, per-attempt and total
  budgets, on-exhaustion fallback) every hand-rolled retry loop routes
  through.
* ``faults``  — the deterministic injection seam (``OT_FAULTS=
  init_hang:2,dispatch_fail:1,build_fail``): named points wired into the
  real failure seams, a single dict lookup when unset, exact scripted
  failure sequences when set — CI can rehearse a wedged tunnel on CPU.
* ``journal`` — sweep checkpoint/resume: harness rows append to a JSONL
  journal as they complete; a restarted sweep (same config hash) skips
  completed rows instead of losing the run.
* ``degrade`` — the one chokepoint every graceful demotion (tpu->cpu,
  pallas->bitslice->jnp, native->lax.scan) reports through, so a fallback
  run carries a visible ``degraded:[...]`` record and can never masquerade
  as a healthy one.
* ``watchdog`` — phase 2: a monitor-thread deadline around any device
  call; on expiry it dumps all-thread stacks to a crash report, stamps
  the demotion through ``degrade``, and raises ``DispatchTimeout`` in
  the main thread. Also hosts ``injected_hang`` (the ``dispatch_hang``
  fault's sleeping stand-in for a wedged dispatch).
* ``isolate`` — phase 2: the shared deadline-guarded child runner
  (``run_child``, SIGKILLs the whole process group, retries through
  ``policy``) and the ``harness.bench --isolate`` supervisor: one child
  process per sweep unit, failures journaled, repeat offenders
  QUARANTINED (skipped on every resume with ``quarantined:<unit>``
  stamped) so a sweep always terminates.

Every module here is stdlib-only and free of intra-package imports, for the
same reason utils/devlock.py is: the repo-root ``bench.py`` and the sweep
scripts load them as BARE files before deciding the jax platform (the
package import pulls in jax). Bare loaders MUST register the module in
``sys.modules`` under its canonical dotted name
(``our_tree_tpu.resilience.<name>``) — see scripts/_devlock_loader.py —
so the fault counters and the degradation record stay one-per-process no
matter which context (bare or package) touches them first.

The full fault matrix and the journal/resume contract are documented in
docs/RESILIENCE.md.
"""
