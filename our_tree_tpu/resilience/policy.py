"""The one retry/backoff/deadline primitive (``RetryPolicy``).

Three hand-rolled retry loops grew independently in this repo — the PJRT
init probe (repo-root ``bench.py:_ensure_live_backend``), the lazy native
build (``runtime/native.py:_build``), and the tunnel-recovery watcher's
probe loop (``scripts/recover_watch.py``) — each with its own attempt
counting, budget arithmetic, and exhaustion behavior, and none testable
against the others. This module is the shared policy they all route
through:

* bounded or unbounded **attempts**;
* **exponential backoff** with optional deterministic jitter (seeded —
  the same policy config always produces the same delay sequence, so CI
  fault scripts stay exactly reproducible);
* a per-attempt timeout hint and a **total budget** that stops retries
  when spent;
* per-exception **delay overrides** (an exception carrying
  ``retry_delay_s`` names its own wait — the watcher's "device busy, poll
  sooner" case — without the policy growing outcome-specific branches);
* an **on-exhaustion fallback** callback, so "give up" is a visible,
  typed decision (demote to CPU, raise) instead of loop fall-through.

Stdlib-only, no intra-package imports (bare-loadable — see the package
docstring). Stateless between ``run()`` calls: one policy object can be
reused.
"""

from __future__ import annotations

import os
import random
import sys
import time


def _trace():
    """our_tree_tpu.obs.trace, lazily, under its canonical dotted name
    (the retry -> trace bridge: every failed attempt and every
    exhaustion becomes a trace event carrying the policy's name). None
    when unloadable — tracing must never break the retry machinery."""
    canonical = "our_tree_tpu.obs.trace"
    mod = sys.modules.get(canonical)
    if mod is None:
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                canonical, os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(
                        __file__))), "obs", "trace.py"))
            mod = importlib.util.module_from_spec(spec)
            sys.modules[canonical] = mod
            spec.loader.exec_module(mod)
        except Exception:
            sys.modules.pop(canonical, None)
            return None
    return mod


class PolicyExhausted(Exception):
    """Every attempt failed and no on_exhausted fallback was given.

    ``last`` carries the final attempt's exception (also chained as
    ``__cause__``); ``attempts`` how many were made.
    """

    def __init__(self, name: str, attempts: int, last: BaseException | None):
        self.name, self.attempts, self.last = name, attempts, last
        super().__init__(
            f"{name or 'retry policy'}: exhausted after {attempts} "
            f"attempt(s); last failure: "
            f"{type(last).__name__ if last else 'none'}: {last}")


class Budget:
    """Shared wall-clock budget accounting (the deadline primitive that
    kept being re-implemented as ``time.time() - t0 > deadline``).

    One object owns the arithmetic: ``remaining()`` / ``exhausted()``
    read it, and ``debit(seconds)`` charges simulated costs against it —
    the generalization of repo-root bench.py's ``_burn``: an injected
    fault that stands in for a hang must debit the wall clock the real
    hang would have burned, or the rehearsal exercises a cheaper outage
    than the real one. ``total_s=0`` (or negative) means unbudgeted:
    never exhausted, infinite remaining — callers need no None-checks.
    ``clock`` is injectable for tests, like RetryPolicy's.
    """

    def __init__(self, total_s: float = 0.0, clock=time.monotonic):
        self.total_s = max(float(total_s), 0.0)
        self._clock = clock
        self._t0 = clock()
        self._debited = 0.0

    def spent(self) -> float:
        """Wall seconds consumed so far, simulated debits included."""
        return (self._clock() - self._t0) + self._debited

    def remaining(self) -> float:
        """Seconds left (``inf`` when unbudgeted, floored at 0)."""
        if not self.total_s:
            return float("inf")
        return max(self.total_s - self.spent(), 0.0)

    def exhausted(self) -> bool:
        return bool(self.total_s) and self.spent() >= self.total_s

    def debit(self, seconds: float) -> None:
        """Charge `seconds` without sleeping (simulated fault cost)."""
        self._debited += max(float(seconds), 0.0)


class Attempt:
    """What one attempt knows: its 0-based ``index``, the policy's
    ``remaining_s`` budget (None = unbudgeted), and a ``timeout_s`` hint
    (``per_attempt_s`` clamped to the remaining budget; None when neither
    is configured). Ops are free to derive their own tighter timeout from
    ``index``/``remaining_s`` — the bench init probe does."""

    __slots__ = ("index", "timeout_s", "remaining_s")

    def __init__(self, index: int, timeout_s: float | None,
                 remaining_s: float | None):
        self.index, self.timeout_s, self.remaining_s = (
            index, timeout_s, remaining_s)


class RetryPolicy:
    """Configurable retry/backoff/deadline runner.

    Parameters
    ----------
    attempts : int | None
        Maximum attempts (None = unbounded; then ``budget_s`` and/or
        ``stop_when`` must end the loop).
    base_delay_s, factor, jitter_frac :
        Backoff between failures: ``base_delay_s * factor**index``,
        multiplied by ``1 + jitter_frac * u`` with ``u`` drawn from a
        ``random.Random(jitter_seed)`` private to the run — deterministic
        for a given config, never shared global-RNG state.
    per_attempt_s : float | None
        Timeout hint surfaced on each ``Attempt`` (clamped to the
        remaining budget).
    budget_s : float | None
        Total wall budget measured by ``clock`` from ``run()`` entry;
        once spent, no further retries (the in-flight attempt is not
        interrupted — interruption stays the op's job, e.g. bench.py's
        stage alarm).
    stop_when : callable(Attempt) -> bool
        Extra stop predicate checked before every RETRY (never before the
        first attempt): return True to give up early.
    retry_on : tuple[type, ...]
        Exception types that mean "failed, maybe retry". Anything else
        propagates immediately.
    on_exhausted : callable(last_exc) -> value
        Fallback producing ``run()``'s return value when every attempt
        failed; when absent, ``PolicyExhausted`` is raised.
    log : callable(Attempt, BaseException) | None
        Per-failure observer (the callers' existing stderr diagnostics).
    sleep, clock :
        Injectable for tests (and for the watcher's ledger-aware sleep).
    """

    def __init__(self, *, attempts: int | None = 3, base_delay_s: float = 0.0,
                 factor: float = 2.0, jitter_frac: float = 0.0,
                 per_attempt_s: float | None = None,
                 budget_s: float | None = None, stop_when=None,
                 retry_on: tuple = (Exception,), on_exhausted=None,
                 log=None, name: str = "", jitter_seed: int = 0,
                 sleep=time.sleep, clock=time.monotonic):
        if attempts is not None and attempts < 1:
            raise ValueError(f"attempts must be >= 1 or None, got {attempts}")
        self.attempts = attempts
        self.base_delay_s = base_delay_s
        self.factor = factor
        self.jitter_frac = jitter_frac
        self.per_attempt_s = per_attempt_s
        self.budget_s = budget_s
        self.stop_when = stop_when
        self.retry_on = retry_on
        self.on_exhausted = on_exhausted
        self.log = log
        self.name = name
        self.jitter_seed = jitter_seed
        self.sleep = sleep
        self.clock = clock

    def _delay(self, index: int, rng) -> float:
        d = self.base_delay_s * (self.factor ** index)
        if self.jitter_frac:
            d *= 1.0 + self.jitter_frac * rng.random()
        return d

    def run(self, op):
        """Call ``op(attempt)`` until it returns, retries are exhausted,
        the budget is spent, or ``stop_when`` fires. Returns op's value,
        the fallback's value, or raises ``PolicyExhausted`` / the first
        non-``retry_on`` exception."""
        rng = random.Random(self.jitter_seed)
        t0 = self.clock()
        last: BaseException | None = None
        index = 0
        while True:
            remaining = (None if self.budget_s is None
                         else self.budget_s - (self.clock() - t0))
            timeout = self.per_attempt_s
            if remaining is not None and timeout is not None:
                timeout = max(min(timeout, remaining), 0.0)
            attempt = Attempt(index, timeout, remaining)
            try:
                return op(attempt)
            except self.retry_on as e:
                last = e
                t = _trace()
                if t is not None:
                    t.counter("retry_failures",
                              policy=self.name or "retry", attempt=index,
                              error=type(e).__name__)
                if self.log is not None:
                    self.log(attempt, e)
            index += 1
            if self.attempts is not None and index >= self.attempts:
                break
            remaining = (None if self.budget_s is None
                         else self.budget_s - (self.clock() - t0))
            if remaining is not None and remaining <= 0:
                break
            if self.stop_when is not None and self.stop_when(
                    Attempt(index, self.per_attempt_s, remaining)):
                break
            # An exception that knows its own retry cadence overrides the
            # computed backoff (e.g. the watcher's Busy-vs-Wedged polls).
            delay = getattr(last, "retry_delay_s", None)
            if delay is None:
                delay = self._delay(index - 1, rng)
            if delay > 0:
                if remaining is not None:
                    delay = min(delay, max(remaining, 0.0))
                self.sleep(delay)
        t = _trace()
        if t is not None:
            t.point("retry-exhausted", policy=self.name or "retry",
                    attempts=index,
                    error=type(last).__name__ if last else None)
        if self.on_exhausted is not None:
            return self.on_exhausted(last)
        raise PolicyExhausted(self.name, index, last) from last
