"""Sweep checkpoint/resume journal (JSONL).

A SIGKILLed or tunnel-wedged sweep used to lose the whole run: the results
corpus is written row-by-row, but a restart re-ran everything — including
the rows that already completed — and on a flaky device usually died again
before reaching the row that killed it. This journal is the moral
extension of the reference's ``nc_off`` resume state (PAPER.md §5) from
streams to whole sweeps: the harness appends one JSONL entry per completed
sweep unit, and a restarted sweep with the SAME config hash replays the
recorded units (re-emitting their result lines verbatim and restoring the
shared RNG stream) and resumes execution at the first unfinished one.

File format — line 1 is the header; every later line is one completed
unit, one recorded FAILURE of a unit (an isolated child that hung or
crashed — resilience/isolate.py), or one completed worker ROW inside a
still-running unit (the intra-unit checkpoint)::

    {"kind": "ot-sweep-journal", "v": 1, "config_hash": "...", "config": {...}}
    {"unit": "ecb:65536", "lines": [...], "rng_state": {...}, "degraded": []}
    {"unit": "ctr:65536", "failed": true, "reason": "timeout:20s"}
    {"unit": "ctr:65536", "row": "2", "lines": [...], "rng_state": {...}}

Failure rows are counted (``fail_count``), never replayed: a unit whose
count reaches the caller's quarantine threshold is skipped on resume
with a ``quarantined:<unit>`` demotion stamped through degrade() —
the quarantine ledger of docs/RESILIENCE.md. Completed and failure rows
interleave freely (a unit can fail twice and then complete).

Row records are the PER-WORKER-ROW granularity (docs/OBSERVABILITY.md):
a unit SIGKILLed or watchdog-failed midway leaves its completed rows on
file, and the unit's RE-run replays them (re-emitting their lines,
restoring the post-row RNG state) and resumes at the first fresh row —
instead of re-running every worker row of a half-done unit. They are
consulted only while their unit is incomplete; once the unit's own
completed record lands, stale row records are inert (never replayed,
never counted). ``clear_failures`` is the quarantine-release edit: it
rewrites the file without the named units' failure rows (the
``--unquarantine`` flow).

Durability: entries are flushed + fsync'd as they complete, so a SIGKILL
can tear at most the in-flight line; a torn or otherwise unparseable tail
is truncated away on load (the valid prefix is trusted, nothing after it).
A header whose ``config_hash`` does not match the current sweep's config
invalidates the journal — the file is restarted fresh, because replaying
rows from a different sweep shape would corrupt both the corpus and the
RNG stream.

Resume correctness rests on two facts the harness guarantees:

* unit order is a pure function of the config (so the journal's entries
  are a prefix of the rerun's unit sequence), and
* each entry records the RNG state AFTER its unit ran, so skipping the
  unit and restoring the state leaves later units byte-identical to an
  uninterrupted run.

Stdlib-only, no intra-package imports (bare-loadable; see the package
docstring).
"""

from __future__ import annotations

import hashlib
import json
import os

KIND = "ot-sweep-journal"
VERSION = 1


def config_hash(config: dict) -> str:
    """Stable hash of a sweep's identity (JSON-serializable config)."""
    blob = json.dumps(config, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class SweepJournal:
    """One sweep's checkpoint file. See the module docstring for format.

    ``skip(unit)`` returns the recorded entry when `unit` is the next
    replayable one (consume in sweep order), else None — and a unit-order
    mismatch (possible only if the unit sequence stopped being a pure
    function of the hashed config) distrusts and truncates the remaining
    tail rather than replaying rows into the wrong slots.
    """

    def __init__(self, path: str, config: dict):
        self.path = path
        self.config_hash = config_hash(config)
        self._replay: list[dict] = []
        self._fail_counts: dict[str, int] = {}
        self._rows: dict[str, dict[str, dict]] = {}
        self._resumed = 0
        valid_bytes = 0
        header_ok = False
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            raw = b""
        offset = 0
        for line in raw.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break  # torn in-flight write: trust nothing from here on
            try:
                rec = json.loads(line)
            except ValueError:
                break
            if offset == 0:
                if not (isinstance(rec, dict) and rec.get("kind") == KIND
                        and rec.get("v") == VERSION
                        and rec.get("config_hash") == self.config_hash):
                    break  # foreign/changed config: invalidate everything
                header_ok = True
            elif isinstance(rec, dict) and isinstance(rec.get("unit"), str):
                if rec.get("failed"):
                    # A failure row is evidence, not a checkpoint: count
                    # it toward quarantine, never offer it for replay.
                    u = rec["unit"]
                    self._fail_counts[u] = self._fail_counts.get(u, 0) + 1
                elif rec.get("row") is not None:
                    # An intra-unit worker-row checkpoint: replayable
                    # only from INSIDE its unit's re-run, never as a
                    # completed unit.
                    self._rows.setdefault(rec["unit"], {})[
                        str(rec["row"])] = rec
                else:
                    self._replay.append(rec)
            else:
                break
            offset += len(line)
            valid_bytes = offset
        if not header_ok:
            self._replay = []
            valid_bytes = 0
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # Truncate away any distrusted tail, then hold the file open in
        # append mode; a fresh/invalidated journal gets its header now so
        # a kill before the first completed row still leaves a valid file.
        self._fh = open(path, "ab")
        if self._fh.tell() != valid_bytes:
            self._fh.truncate(valid_bytes)
            self._fh.seek(valid_bytes)
        if valid_bytes == 0:
            self._append({"kind": KIND, "v": VERSION,
                          "config_hash": self.config_hash, "config": config})

    # -- internals ---------------------------------------------------------
    # The crash-safety contract: a record must be on disk before the
    # next admission decision, so the fsync is deliberately inline —
    # failure/lifecycle cadence only, never the per-request path.
    # ot-san: absorb=journal-fsync-durability
    def _append(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec, separators=(",", ":")).encode()
                       + b"\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    # -- API ---------------------------------------------------------------
    @property
    def pending(self) -> int:
        """Completed units not yet replayed this run."""
        return len(self._replay)

    @property
    def resumed(self) -> int:
        """Units replayed from the journal so far this run."""
        return self._resumed

    def is_completed(self, unit: str) -> bool:
        """Whether `unit` has an unconsumed replayable record — one
        loaded from a previous run or absorbed via ``reload_tail`` (a
        unit this handle ``record()``-ed itself is done, not replayable:
        its lines were already emitted live).

        Callers MUST gate ``skip()`` on this: with failure rows on file a
        unit can be absent from the replay list without any disorder
        (it failed; the next completed unit is a later one), and calling
        ``skip()`` for it would misread the head mismatch as corruption
        and truncate a perfectly good tail.
        """
        return any(e.get("unit") == unit for e in self._replay)

    def fail_count(self, unit: str) -> int:
        """Recorded failures of `unit` (the quarantine ledger's count)."""
        return self._fail_counts.get(unit, 0)

    def record_failure(self, unit: str, reason: str) -> None:
        """Append one failure row (fsync'd) and count it in-memory.

        Written by the SUPERVISOR (isolate.py's parent — the child that
        hung was SIGKILLed and cannot write anything), or by the in-
        process watchdog path when a unit's dispatch times out.
        """
        self._fail_counts[unit] = self._fail_counts.get(unit, 0) + 1
        self._append({"unit": unit, "failed": True, "reason": reason})

    def reload_tail(self) -> int:
        """Re-read rows appended by another process (an isolated child)
        since this handle last looked; returns how many completed-unit
        rows arrived. New completed rows join the replay list (the
        supervisor consumes them via ``skip`` to re-emit their lines);
        new failure rows join the counts.

        A torn trailing fragment — the child was SIGKILLed mid-append,
        which is exactly what the isolate supervisor does to a hung
        child — is TRUNCATED away before returning: this handle is
        about to append its own rows (the failure record for that very
        kill), and appending onto a partial line would glue two records
        into one unparseable line, silently discarding every later row
        at the next load. Only called once the child is dead, so there
        is no live writer to race.
        """
        self._fh.flush()
        seen = self._fh.tell()
        added = 0
        with open(self.path, "rb") as f:
            f.seek(seen)
            raw = f.read()
        consumed = 0
        for line in raw.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break
            try:
                rec = json.loads(line)
            except ValueError:
                break
            if not (isinstance(rec, dict) and isinstance(rec.get("unit"),
                                                         str)):
                break
            if rec.get("failed"):
                u = rec["unit"]
                self._fail_counts[u] = self._fail_counts.get(u, 0) + 1
            elif rec.get("row") is not None:
                self._rows.setdefault(rec["unit"], {})[str(rec["row"])] = rec
            else:
                self._replay.append(rec)
                added += 1
            consumed += len(line)
        seen += consumed
        if consumed < len(raw):  # torn/unparseable tail: cut it off
            self._fh.truncate(seen)
        # Keep our append handle pointed past what we just absorbed, so a
        # later record()/record_failure() lands after the child's rows
        # (O_APPEND writes at EOF regardless — this only keeps tell()
        # honest for the next reload).
        self._fh.seek(seen)
        return added

    def take(self, unit: str) -> dict | None:
        """The recorded entry for `unit` regardless of replay position.

        For EMIT-ONLY consumers — the isolate supervisor re-emits
        entries by name and restores no RNG state, so replay order is
        not a correctness surface for it the way it is for ``skip()``.
        Out-of-order completion is routine there: a quarantine-released
        (or failed-then-retried) unit completes AFTER its successors,
        and the strict-order ``skip()`` would distrust and truncate a
        perfectly attributable tail. In-process resume (harness.bench
        without --isolate) MUST keep using ``skip()``: it restores the
        shared RNG stream, where order is the whole contract.
        """
        for i, entry in enumerate(self._replay):
            if entry.get("unit") == unit:
                self._resumed += 1
                return self._replay.pop(i)
        return None

    def skip(self, unit: str) -> dict | None:
        """The recorded entry for `unit` iff it is next in replay order."""
        if not self._replay:
            return None
        if self._replay[0].get("unit") != unit:
            # Order mismatch: the stored tail cannot be mapped onto this
            # run's remaining units. Re-run them (correctness over thrift)
            # and drop the stale records so re-recorded entries don't
            # duplicate them.
            self._replay = []
            self._truncate_to_consumed()
            return None
        self._resumed += 1
        return self._replay.pop(0)

    def _truncate_to_consumed(self) -> None:
        """Rewrite the file as header + already-consumed entries. Only
        reached on the defensive order-mismatch path; everything still in
        self._replay is stale. Rebuild from scratch: cheapest correct
        move for a path that should never execute."""
        self._fh.close()
        with open(self.path, "rb") as f:
            lines = f.read().splitlines(keepends=True)
        self._fh = open(self.path, "wb")
        consumed = 0
        for i, line in enumerate(lines):
            if i == 0:  # header
                self._fh.write(line)
                continue
            if consumed >= self._resumed:
                break
            self._fh.write(line)
            try:
                rec = json.loads(line)
            except ValueError:
                break
            # Failure and worker-row records ride along, uncounted: only
            # completed-unit records were consumed via skip().
            if not rec.get("failed") and rec.get("row") is None:
                consumed += 1
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def rows(self, unit: str) -> dict[str, dict]:
        """`unit`'s recorded worker-row checkpoints (row-key -> record),
        for replay inside the unit's re-run. Meaningful only while the
        unit is incomplete — a completed unit's replay supersedes them."""
        return dict(self._rows.get(unit, {}))

    def record_row(self, unit: str, row: str, lines: list[str],
                   rng_state=None) -> None:
        """Append one completed worker row of a still-running unit
        (fsync'd — the whole point is surviving the unit's SIGKILL)."""
        self._rows.setdefault(unit, {})[str(row)] = rec = {
            "unit": unit, "row": str(row), "lines": list(lines),
            "rng_state": rng_state}
        self._append(rec)

    def record(self, unit: str, lines: list[str], rng_state=None,
               degraded=()) -> None:
        """Append one completed unit (flushed + fsync'd before return)."""
        self._append({"unit": unit, "lines": list(lines),
                      "rng_state": rng_state, "degraded": list(degraded)})

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass


def clear_failures(path: str, units: list[str]) -> dict[str, int]:
    """Drop the named units' failure rows from the journal at ``path``
    (the quarantine-release edit behind ``harness.bench --unquarantine``).

    Returns unit -> number of failure rows removed (0 entries included,
    so a typo'd unit name is visible to the caller). Works on any
    parseable journal regardless of config hash — releasing a unit is a
    ledger edit, not a replay, so it must not depend on reproducing the
    exact sweep config that quarantined it. Every non-failure line
    (header, completed units, worker rows, OTHER units' failures) is
    preserved byte-for-byte; the rewrite goes through a temp file +
    rename so a kill mid-edit leaves the original intact.
    """
    cleared = {u: 0 for u in units}
    targets = set(units)
    try:
        with open(path, "rb") as f:
            lines = f.read().splitlines(keepends=True)
    except OSError:
        return cleared
    kept = []
    for i, line in enumerate(lines):
        drop = False
        if i > 0 and line.endswith(b"\n"):
            try:
                rec = json.loads(line)
            except ValueError:
                rec = None
            if (isinstance(rec, dict) and rec.get("failed")
                    and rec.get("unit") in targets):
                cleared[rec["unit"]] += 1
                drop = True
        if not drop:
            kept.append(line)
    if any(cleared.values()):
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(b"".join(kept))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    return cleared
