"""Sweep checkpoint/resume journal (JSONL).

A SIGKILLed or tunnel-wedged sweep used to lose the whole run: the results
corpus is written row-by-row, but a restart re-ran everything — including
the rows that already completed — and on a flaky device usually died again
before reaching the row that killed it. This journal is the moral
extension of the reference's ``nc_off`` resume state (PAPER.md §5) from
streams to whole sweeps: the harness appends one JSONL entry per completed
sweep unit, and a restarted sweep with the SAME config hash replays the
recorded units (re-emitting their result lines verbatim and restoring the
shared RNG stream) and resumes execution at the first unfinished one.

File format — line 1 is the header, every later line one completed unit::

    {"kind": "ot-sweep-journal", "v": 1, "config_hash": "...", "config": {...}}
    {"unit": "ecb:65536", "lines": [...], "rng_state": {...}, "degraded": []}

Durability: entries are flushed + fsync'd as they complete, so a SIGKILL
can tear at most the in-flight line; a torn or otherwise unparseable tail
is truncated away on load (the valid prefix is trusted, nothing after it).
A header whose ``config_hash`` does not match the current sweep's config
invalidates the journal — the file is restarted fresh, because replaying
rows from a different sweep shape would corrupt both the corpus and the
RNG stream.

Resume correctness rests on two facts the harness guarantees:

* unit order is a pure function of the config (so the journal's entries
  are a prefix of the rerun's unit sequence), and
* each entry records the RNG state AFTER its unit ran, so skipping the
  unit and restoring the state leaves later units byte-identical to an
  uninterrupted run.

Stdlib-only, no intra-package imports (bare-loadable; see the package
docstring).
"""

from __future__ import annotations

import hashlib
import json
import os

KIND = "ot-sweep-journal"
VERSION = 1


def config_hash(config: dict) -> str:
    """Stable hash of a sweep's identity (JSON-serializable config)."""
    blob = json.dumps(config, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class SweepJournal:
    """One sweep's checkpoint file. See the module docstring for format.

    ``skip(unit)`` returns the recorded entry when `unit` is the next
    replayable one (consume in sweep order), else None — and a unit-order
    mismatch (possible only if the unit sequence stopped being a pure
    function of the hashed config) distrusts and truncates the remaining
    tail rather than replaying rows into the wrong slots.
    """

    def __init__(self, path: str, config: dict):
        self.path = path
        self.config_hash = config_hash(config)
        self._replay: list[dict] = []
        self._resumed = 0
        valid_bytes = 0
        header_ok = False
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            raw = b""
        offset = 0
        for line in raw.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break  # torn in-flight write: trust nothing from here on
            try:
                rec = json.loads(line)
            except ValueError:
                break
            if offset == 0:
                if not (isinstance(rec, dict) and rec.get("kind") == KIND
                        and rec.get("v") == VERSION
                        and rec.get("config_hash") == self.config_hash):
                    break  # foreign/changed config: invalidate everything
                header_ok = True
            elif isinstance(rec, dict) and isinstance(rec.get("unit"), str):
                self._replay.append(rec)
            else:
                break
            offset += len(line)
            valid_bytes = offset
        if not header_ok:
            self._replay = []
            valid_bytes = 0
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # Truncate away any distrusted tail, then hold the file open in
        # append mode; a fresh/invalidated journal gets its header now so
        # a kill before the first completed row still leaves a valid file.
        self._fh = open(path, "ab")
        if self._fh.tell() != valid_bytes:
            self._fh.truncate(valid_bytes)
            self._fh.seek(valid_bytes)
        if valid_bytes == 0:
            self._append({"kind": KIND, "v": VERSION,
                          "config_hash": self.config_hash, "config": config})

    # -- internals ---------------------------------------------------------
    def _append(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec, separators=(",", ":")).encode()
                       + b"\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    # -- API ---------------------------------------------------------------
    @property
    def pending(self) -> int:
        """Completed units not yet replayed this run."""
        return len(self._replay)

    @property
    def resumed(self) -> int:
        """Units replayed from the journal so far this run."""
        return self._resumed

    def skip(self, unit: str) -> dict | None:
        """The recorded entry for `unit` iff it is next in replay order."""
        if not self._replay:
            return None
        if self._replay[0].get("unit") != unit:
            # Order mismatch: the stored tail cannot be mapped onto this
            # run's remaining units. Re-run them (correctness over thrift)
            # and drop the stale records so re-recorded entries don't
            # duplicate them.
            self._replay = []
            self._truncate_to_consumed()
            return None
        self._resumed += 1
        return self._replay.pop(0)

    def _truncate_to_consumed(self) -> None:
        """Rewrite the file as header + already-consumed entries. Only
        reached on the defensive order-mismatch path; everything still in
        self._replay is stale. Rebuild from scratch: cheapest correct
        move for a path that should never execute."""
        self._fh.close()
        with open(self.path, "rb") as f:
            lines = f.read().splitlines(keepends=True)
        keep = 1 + self._resumed  # header + consumed prefix
        self._fh = open(self.path, "wb")
        for line in lines[:keep]:
            self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def record(self, unit: str, lines: list[str], rng_state=None,
               degraded=()) -> None:
        """Append one completed unit (flushed + fsync'd before return)."""
        self._append({"unit": unit, "lines": list(lines),
                      "rng_state": rng_state, "degraded": list(degraded)})

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass
