"""The one chokepoint every graceful demotion reports through.

The repo degrades on purpose in several places — a failed init probe
demotes tpu->cpu (repo-root bench.py), a Pallas engine that cannot compile
is skipped for its ranked runner-up (models/aes.py:resolve_engine), an
unbuildable native runtime sends ARC4 keygen to the lax.scan path
(harness/backends.py) — and before this module each site only printed to
stderr, which an orchestrator's log rotation eats. A fallback run could
therefore masquerade as a healthy one in the artifacts that matter (the
bench JSON line, the sweep journal).

``degrade(kind, why)`` records the demotion in a process-global ledger;
``events()`` returns the kinds in first-occurrence order for stamping into
the bench JSON line (``"degraded": ["tpu->cpu"]`` — bench.py:_report) and
the sweep journal entries (harness/bench.py). Kinds are small arrows
naming the demotion: ``tpu->cpu``, ``pallas->bitslice``,
``native->lax.scan``, ``device->native``, ``headline->probe``.

Duplicate kinds collapse (resolve_engine runs per crypt-context; one
demotion is one fact); the full (kind, why) pairs stay available via
``detail()`` for diagnostics.

Stdlib-only, no intra-package imports; bare loaders must register this
module under ``our_tree_tpu.resilience.degrade`` in ``sys.modules`` so the
ledger is one-per-process across bare and package import contexts (the
repo-root bench.py records tpu->cpu in bare context but the engine
demotion it must also report happens inside the package).
"""

from __future__ import annotations

import os
import sys

#: (kind, why) in record order, duplicates (by kind) dropped.
_EVENTS: list[tuple[str, str]] = []


def _trace():
    """our_tree_tpu.obs.trace, lazily, under its canonical dotted name
    (the degrade-ledger -> trace bridge; same bare-load pattern as
    watchdog._sibling). None when unloadable — tracing is an observer
    and must never break the ledger."""
    canonical = "our_tree_tpu.obs.trace"
    mod = sys.modules.get(canonical)
    if mod is None:
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                canonical, os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(
                        __file__))), "obs", "trace.py"))
            mod = importlib.util.module_from_spec(spec)
            sys.modules[canonical] = mod
            spec.loader.exec_module(mod)
        except Exception:
            sys.modules.pop(canonical, None)
            return None
    return mod


def degrade(kind: str, why: str = "") -> None:
    """Record a graceful demotion and announce it on stderr.

    `kind` is the arrow (``"tpu->cpu"``); `why` one human line. A kind
    already recorded is not re-announced — callers may hit the same
    chokepoint per-context (resolve_engine) without spamming the ledger.
    """
    if any(k == kind for k, _ in _EVENTS):
        return
    _EVENTS.append((kind, why))
    # The degrade-ledger -> trace bridge: every demotion is also one
    # instant trace event WITH its cause, so a run's trace stream tells
    # the demotion story without the bench JSON line or the journal.
    t = _trace()
    if t is not None:
        t.point("degrade", kind=kind, why=why)
    print(f"# degraded: {kind}" + (f" ({why})" if why else ""),
          file=sys.stderr, flush=True)


def events() -> list[str]:
    """Recorded demotion kinds, first-occurrence order. Empty = healthy."""
    return [k for k, _ in _EVENTS]


def detail() -> list[tuple[str, str]]:
    """(kind, why) pairs, for diagnostics/tests."""
    return list(_EVENTS)


def clear() -> None:
    """Reset the ledger (tests only — a real process's demotions are
    facts about this process and must survive to the report)."""
    del _EVENTS[:]
