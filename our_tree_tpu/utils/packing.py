"""Byte <-> word packing helpers.

All of the framework's cipher cores operate on little-endian packed uint32
words (the `GET_ULONG_LE`/`PUT_ULONG_LE` convention of the parity oracle,
reference aes-modes/aes.c:43-60). The VPU is a >=32-bit machine, so bytes are
packed 4-per-lane at the boundary and everything stays uint32 internally
(SURVEY.md §7 hard part #2).

numpy variants are host-side (zero-copy views where possible); jnp variants
trace into XLA programs.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def np_bytes_to_words(b: np.ndarray) -> np.ndarray:
    """uint8 array with length % 4 == 0 -> little-endian uint32 words."""
    b = np.ascontiguousarray(b, dtype=np.uint8)
    if b.size % 4:
        raise ValueError("byte length must be a multiple of 4")
    return b.view("<u4").reshape(b.shape[:-1] + (b.shape[-1] // 4,))


def np_words_to_bytes(w: np.ndarray) -> np.ndarray:
    """uint32 words -> little-endian uint8 bytes.

    A zero-copy VIEW whenever the input is already contiguous
    little-endian u32 (the serve output path splits batch results with
    this per request — the old unconditional ``astype`` copy was a full
    extra pass over every payload byte). The view inherits the input's
    writability: jax-backed arrays come through READ-ONLY — callers
    that mutate (or must not alias the input) copy at their boundary
    (``models.aes._bytes_np``, ``serve.batcher.Batch.split_output``)."""
    w = np.ascontiguousarray(w, dtype="<u4")
    return w.view(np.uint8).reshape(w.shape[:-1] + (w.shape[-1] * 4,))


def jnp_bytes_to_words(b: jnp.ndarray) -> jnp.ndarray:
    """(..., 4k) uint8 -> (..., k) uint32, little-endian, on device."""
    b = b.astype(jnp.uint32)
    b = b.reshape(b.shape[:-1] + (b.shape[-1] // 4, 4))
    return b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)


def jnp_words_to_bytes(w: jnp.ndarray) -> jnp.ndarray:
    """(..., k) uint32 -> (..., 4k) uint8, little-endian, on device."""
    parts = jnp.stack(
        [w & 0xFF, (w >> 8) & 0xFF, (w >> 16) & 0xFF, (w >> 24) & 0xFF], axis=-1
    )
    return parts.reshape(w.shape[:-1] + (w.shape[-1] * 4,)).astype(jnp.uint8)


def byteswap32(w: jnp.ndarray) -> jnp.ndarray:
    """Reverse byte order within each uint32 lane (BE<->LE word view)."""
    return (
        ((w & 0xFF) << 24)
        | ((w & 0xFF00) << 8)
        | ((w >> 8) & 0xFF00)
        | ((w >> 24) & 0xFF)
    )


def np_ctr_le_blocks(nonce_counter: np.ndarray | bytes,
                     idx: np.ndarray,
                     out: np.ndarray | None = None) -> np.ndarray:
    """Counter blocks ``nonce + idx[k]`` as the (N, 4) u32 LE words the
    cipher consumes — the host-side twin of ``models.aes.ctr_le_blocks``
    (tests pin the two against each other across multi-word carries).

    The serve batcher materialises each request's counter stream with
    this before concatenating requests into one scattered-CTR dispatch
    (``models.aes.ctr_crypt_words_scattered``); building counters on host
    keeps the device call a pure fixed-shape engine dispatch. It runs
    once per request on the serve fast path, so the common case — the
    low counter word never wraps inside one request — takes a
    carry-free lane: the three upper words are broadcast scalars and
    only the low word is per-block work. ``out`` lets the batcher write
    straight into its batch array (no (N, 4) temporary).

    ``nonce_counter``: the 16 big-endian counter bytes (the resume-state
    convention of ``AES.crypt_ctr``); ``idx``: (N,) block offsets < 2^32.
    """
    b = np.frombuffer(bytes(nonce_counter), dtype=np.uint8)
    if b.size != 16:
        raise ValueError("nonce_counter must be 16 bytes")
    ctr_be = np_bytes_to_words(b).byteswap()  # (4,) big-endian words
    ctr_le = ctr_be.byteswap()                # the same words, LE view
    idx = np.asarray(idx, dtype=np.uint32)
    if out is None:
        out = np.empty((idx.size, 4), dtype=np.uint32)
    with np.errstate(over="ignore"):  # 128-bit ripple: word wrap intended
        s3 = (ctr_be[3] + idx).astype(np.uint32)
        wrapped = s3 < idx
        if wrapped.any():
            out[:, 3] = s3.byteswap()
            c3 = wrapped.astype(np.uint32)
            s2 = (ctr_be[2] + c3).astype(np.uint32)
            c2 = c3 & (s2 == 0)
            s1 = (ctr_be[1] + c2).astype(np.uint32)
            c1 = c2 & (s1 == 0)
            s0 = (ctr_be[0] + c1).astype(np.uint32)
            out[:, 2] = s2.byteswap()
            out[:, 1] = s1.byteswap()
            out[:, 0] = s0.byteswap()
        else:  # no low-word wrap anywhere: upper words are constants
            # One contiguous broadcast pass, then overwrite the low
            # column — three separate strided constant-column writes
            # each re-touch every cache line of the array (write
            # allocate), which at large rungs cost more than the ECB
            # keystream itself.
            out[:] = ctr_le
            out[:, 3] = s3.byteswap()
    return out


def hex_to_bytes(s: str) -> np.ndarray:
    return np.frombuffer(bytes.fromhex(s), dtype=np.uint8)


def bytes_to_hex(b: np.ndarray) -> str:
    return np.asarray(b, dtype=np.uint8).tobytes().hex()
