"""Byte <-> word packing helpers.

All of the framework's cipher cores operate on little-endian packed uint32
words (the `GET_ULONG_LE`/`PUT_ULONG_LE` convention of the parity oracle,
reference aes-modes/aes.c:43-60). The VPU is a >=32-bit machine, so bytes are
packed 4-per-lane at the boundary and everything stays uint32 internally
(SURVEY.md §7 hard part #2).

numpy variants are host-side (zero-copy views where possible); jnp variants
trace into XLA programs.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def np_bytes_to_words(b: np.ndarray) -> np.ndarray:
    """uint8 array with length % 4 == 0 -> little-endian uint32 words."""
    b = np.ascontiguousarray(b, dtype=np.uint8)
    if b.size % 4:
        raise ValueError("byte length must be a multiple of 4")
    return b.view("<u4").reshape(b.shape[:-1] + (b.shape[-1] // 4,))


def np_words_to_bytes(w: np.ndarray) -> np.ndarray:
    """uint32 words -> little-endian uint8 bytes."""
    w = np.ascontiguousarray(w)
    return w.astype("<u4").view(np.uint8).reshape(w.shape[:-1] + (w.shape[-1] * 4,))


def jnp_bytes_to_words(b: jnp.ndarray) -> jnp.ndarray:
    """(..., 4k) uint8 -> (..., k) uint32, little-endian, on device."""
    b = b.astype(jnp.uint32)
    b = b.reshape(b.shape[:-1] + (b.shape[-1] // 4, 4))
    return b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)


def jnp_words_to_bytes(w: jnp.ndarray) -> jnp.ndarray:
    """(..., k) uint32 -> (..., 4k) uint8, little-endian, on device."""
    parts = jnp.stack(
        [w & 0xFF, (w >> 8) & 0xFF, (w >> 16) & 0xFF, (w >> 24) & 0xFF], axis=-1
    )
    return parts.reshape(w.shape[:-1] + (w.shape[-1] * 4,)).astype(jnp.uint8)


def byteswap32(w: jnp.ndarray) -> jnp.ndarray:
    """Reverse byte order within each uint32 lane (BE<->LE word view)."""
    return (
        ((w & 0xFF) << 24)
        | ((w & 0xFF00) << 8)
        | ((w >> 8) & 0xFF00)
        | ((w >> 24) & 0xFF)
    )


def np_ctr_le_blocks(nonce_counter: np.ndarray | bytes,
                     idx: np.ndarray) -> np.ndarray:
    """Counter blocks ``nonce + idx[k]`` as the (N, 4) u32 LE words the
    cipher consumes — the host-side twin of ``models.aes.ctr_le_blocks``
    (tests pin the two against each other across multi-word carries).

    The serve batcher materialises each request's counter stream with
    this before concatenating requests into one scattered-CTR dispatch
    (``models.aes.ctr_crypt_words_scattered``); building counters on host
    keeps the device call a pure fixed-shape engine dispatch.

    ``nonce_counter``: the 16 big-endian counter bytes (the resume-state
    convention of ``AES.crypt_ctr``); ``idx``: (N,) block offsets < 2^32.
    """
    b = np.frombuffer(bytes(nonce_counter), dtype=np.uint8)
    if b.size != 16:
        raise ValueError("nonce_counter must be 16 bytes")
    ctr_be = np_bytes_to_words(b).byteswap()  # (4,) big-endian words
    idx = np.asarray(idx, dtype=np.uint32)
    with np.errstate(over="ignore"):  # 128-bit ripple: word wrap intended
        s3 = (ctr_be[3] + idx).astype(np.uint32)
        c3 = (s3 < idx).astype(np.uint32)
        s2 = (ctr_be[2] + c3).astype(np.uint32)
        c2 = c3 & (s2 == 0)
        s1 = (ctr_be[1] + c2).astype(np.uint32)
        c1 = c2 & (s1 == 0)
        s0 = (ctr_be[0] + c1).astype(np.uint32)
    be = np.stack([s0, s1, s2, s3], axis=-1)
    return be.byteswap()  # LE words of the counter byte stream


def hex_to_bytes(s: str) -> np.ndarray:
    return np.frombuffer(bytes.fromhex(s), dtype=np.uint8)


def bytes_to_hex(b: np.ndarray) -> str:
    return np.asarray(b, dtype=np.uint8).tobytes().hex()
