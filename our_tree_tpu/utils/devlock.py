"""Advisory single-tenant lock for a tunnelled accelerator.

On hosts where the device is reached through a single-tenant tunnel, two
concurrent jax processes wedge the tunnel for every later process (observed:
>1 h of failed PJRT inits). The benchmark entry points coordinate through a
marker file: measurement jobs hold it, bench.py waits on it before probing
the backend, sweep parents wait for a prior holder before starting.

Design points (stdlib-only so the repo-root bench.py can load this file
directly without importing the package, whose import pulls in jax):

* **Atomic ownership** — acquisition is ``O_CREAT | O_EXCL`` with
  ``pid:starttime`` written into the file (starttime from
  ``/proc/<pid>/stat`` field 22 where available); an exists-then-create
  check would let two processes both believe they own the marker.
* **Staleness self-healing** — a marker is ignored (and reclaimed) when its
  writer PID is dead or, for PID-less markers (``touch`` by an
  orchestrator), when its mtime is older than STALE_S. A SIGKILLed job can
  therefore never permanently tax every future bench run's deadline. The
  recorded starttime closes the PID-reuse hole: a marker whose PID was
  recycled by an unrelated long-lived process used to look live until
  STALE_S (4 h); with both recorded, a starttime mismatch proves the
  writer is gone and the marker is reclaimed immediately. Bare-PID markers
  (older writers, other tooling) keep the previous PID+mtime semantics.
* **Deterministic failure rehearsal** — the ``lock_busy`` fault-injection
  point (resilience/faults.py): while armed, ``is_held`` reports a live
  holder (a PEEK — no shot consumed) and each ``acquire`` consumes one
  shot and fails. ``OT_FAULTS=lock_busy:N`` = N failed acquisitions;
  bare ``OT_FAULTS=lock_busy`` = a holder that never goes away, which
  drives the callers' full busy fallback (wait-out-budget ->
  acquire-fails -> is_held-confirms) without a second process.
* **Advisory, never blocking forever** — waiting callers proceed without
  ownership once their budget is spent: on a bench host, progress beats
  deadlock.

Orchestrator contract: a plan that holds ONE marker around several child
jobs must point the children at a different path (export
``OT_BENCH_BUSY_FILE=/tmp/tpu_busy_<plan>``) — otherwise each child would
dead-wait its budget on its own parent's marker. The recovery watcher does
exactly this.

Load sites (this file is loaded as a BARE file, not via the package, so
jax-free parents stay jax-free — keep them in sync if this file moves):
repo-root ``bench.py`` (_load_devlock) and ``scripts/_devlock_loader.py``
(shared by the sweep scripts).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

DEFAULT_PATH = "/tmp/tpu_busy"

#: A PID-less marker older than this is considered abandoned. Must exceed
#: the longest legitimate orchestrated plan that holds one marker across
#: several jobs (the recovery watcher's full measurement plan is < 4 h).
STALE_S = 4 * 3600.0


def path() -> str:
    return os.environ.get("OT_BENCH_BUSY_FILE", DEFAULT_PATH)


def _faults():
    """resilience/faults.py, loaded lazily WITHOUT importing the package
    (this file is bare-loaded by jax-free parents — see module docstring).
    Registered under the canonical dotted name so the counters are shared
    with every other load context; see scripts/_devlock_loader.py."""
    import sys
    canonical = "our_tree_tpu.resilience.faults"
    mod = sys.modules.get(canonical)
    if mod is None:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            canonical,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         os.pardir, "resilience", "faults.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[canonical] = mod
        try:
            spec.loader.exec_module(mod)
        except BaseException:
            sys.modules.pop(canonical, None)
            raise
    return mod


def _proc_starttime(pid: int) -> str | None:
    """Kernel starttime ticks for `pid` (/proc/<pid>/stat field 22), or
    None off-Linux / on any read failure. The (pid, starttime) pair is
    unique for the machine's uptime — the identity a bare PID lacks."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read()
        # comm (field 2) may contain spaces/parens; fields resume after
        # the LAST ')'. starttime is overall field 22 -> index 19 after.
        return stat.rsplit(b")", 1)[1].split()[19].decode()
    except (OSError, IndexError, ValueError):
        return None


def _writer_alive(pid: int, starttime: str | None = None) -> bool:
    if starttime:
        now = _proc_starttime(pid)
        if now is not None:
            # Definitive either way: same starttime = same process still
            # running; different = the writer died and the PID was
            # recycled — the marker is stale NOW, not after STALE_S.
            return now == starttime
        # /proc says no such process — but distinguish "dead" from
        # "unreadable" (non-Linux) via the signal probe below.
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except Exception:
        return True  # EPERM etc.: someone's process — assume alive


def _read_marker(p: str) -> tuple[int, str | None]:
    """(pid, starttime) from a marker file: ``pid:starttime`` for writers
    of this module, bare ``pid`` for older writers, (0, None) for a
    PID-less orchestrator touch or an unreadable file."""
    try:
        with open(p) as f:
            body = f.read().strip()
    except OSError:
        return 0, None
    pid_s, _, start = body.partition(":")
    try:
        return int(pid_s or "0"), (start or None)
    except ValueError:
        return 0, None


def is_held(p: str | None = None) -> bool:
    """True if the marker exists and its holder still looks alive."""
    p = p or path()
    if _faults().remaining("lock_busy"):
        # Peek, never consume: while lock_busy is armed the simulated
        # holder "exists"; only acquire() attempts burn shots. This is
        # what lets a counted config fail exactly N acquisitions while a
        # bare config simulates a holder that outlasts any wait budget.
        return True
    try:
        st = os.stat(p)
    except OSError:
        return False
    pid, start = _read_marker(p)
    fresh = time.time() - st.st_mtime <= STALE_S
    if pid:
        # The mtime bound still applies: for bare-PID markers it is the
        # only cap on PID reuse, and even a starttime-carrying marker
        # must not outlive the longest legitimate plan.
        return _writer_alive(pid, start) and fresh
    # PID-less (touched by an orchestrator): only mtime can age it out.
    return fresh


def wait(budget_s: float, p: str | None = None, poll_s: float = 15.0,
         on_wait=None) -> float:
    """Block while the marker is held, up to budget_s; returns time waited.

    The budget is a DURATION, so it runs on the monotonic clock (otlint
    wallclock rule): an NTP step mid-wait must not stretch or collapse
    the budget. Marker *staleness* (is_held) stays on the wall clock —
    that compares against file mtimes, which are epoch time.
    """
    p = p or path()
    t0 = time.monotonic()
    announced = False
    while is_held(p) and time.monotonic() - t0 < budget_s:
        if not announced and on_wait is not None:
            on_wait(p)
            announced = True
        time.sleep(poll_s)
    return time.monotonic() - t0


def acquire(p: str | None = None) -> bool:
    """Atomically claim the marker; True iff this process now owns it.

    A stale marker (dead writer / aged-out / recycled PID) is reclaimed.
    Returning False means another live holder exists (or the path is
    unwritable) — the caller may still proceed, it just must not remove
    the marker.
    """
    p = p or path()
    if _faults().fire("lock_busy"):
        return False  # injected: behave as if a live holder owns the marker
    for _ in range(2):  # second try after reclaiming a stale marker
        try:
            fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
            with os.fdopen(fd, "w") as f:
                pid = os.getpid()
                start = _proc_starttime(pid)
                f.write(f"{pid}:{start}" if start else str(pid))
            return True
        except FileExistsError:
            if is_held(p):
                return False
            # Stale: reclaim by atomic rename-aside. Of two concurrent
            # reclaimers only one rename succeeds (the loser gets ENOENT),
            # so a freshly re-created marker can never be deleted by the
            # slower reclaimer — a bare remove() here would allow exactly
            # that double-ownership race.
            aside = f"{p}.stale.{os.getpid()}"
            try:
                os.rename(p, aside)
                os.remove(aside)
            except OSError:
                return False
        except OSError:
            return False
    return False


def release(owned: bool, p: str | None = None) -> None:
    if not owned:
        return
    try:
        os.remove(p or path())
    except OSError:
        pass


@contextlib.contextmanager
def hold(p: str | None = None, wait_budget_s: float = 0.0, on_wait=None,
         refresh_s: float = 600.0):
    """Wait for any prior holder (bounded), then claim the marker for the
    block's duration. Yields whether ownership was actually obtained —
    callers proceed either way (advisory lock), but cleanup is only the
    owner's.

    While owned, a daemon thread refreshes the marker's mtime every
    ``refresh_s`` so a legitimately long-running holder (a wide sweep
    matrix) never ages past STALE_S and gets its live lock reclaimed from
    under it. Bare acquire()/release() users don't get the refresh — they
    must finish within STALE_S (bench.py's deadline is minutes).
    """
    p = p or path()
    if wait_budget_s > 0:
        wait(wait_budget_s, p, on_wait=on_wait)
    owned = acquire(p)
    stop = threading.Event()
    refresher = None
    if owned and refresh_s > 0:
        def _refresh():
            while not stop.wait(refresh_s):
                try:
                    os.utime(p)
                except OSError:
                    break

        refresher = threading.Thread(target=_refresh, daemon=True)
        refresher.start()
    try:
        yield owned
    finally:
        stop.set()
        if refresher is not None:
            refresher.join(timeout=2.0)
        release(owned, p)
