"""Persisted per-platform engine ranking — measurement over guesswork.

The benchmark entry points pick a compute engine by probing the registered
engines on the live device (bench.py) or by a full tuning sweep
(scripts/tune_tpu.py). Both are measurements of THIS host's hardware, and
both used to evaporate when the process exited: the probe order and the
"auto" engine preference were hardcoded from one recorded session. This
module makes the measurement durable: every successful probe/sweep stores
its GB/s ranking in a small JSON file (``results/engine_ranking.json``,
override via ``OT_ENGINE_RANKING``), and every later run — bench probe
order, ``models.aes.resolve_engine("auto")`` — reads it back, falling back
to the static defaults only when no measurement exists for the platform.

Schema (one entry per device key — ``device_key()``: platform + device
kind, so a ranking never crosses TPU generations). ``dropped`` lists
engines persisted as compile-broken on this device (``drop_engines()``;
excluded from ``probe_order()`` until a later store measures them again)::

    {"tpu:TPU v5e": {"ranking": [{"engine": "pallas-gt", "gbps": 5.93}, ...],
                     "source": "bench-probe", "bytes": 67108864,
                     "dropped": ["pallas-dense-bp"],
                     "recorded_at": "2026-07-31T12:00:00"}}

Stdlib-only, like utils/devlock.py, and for the same reason: the repo-root
``bench.py`` loads this as a BARE file before deciding the jax platform, so
it must not import the package (whose import pulls in jax). Writes are
advisory — an unwritable path degrades to the static defaults, never to a
failed benchmark run.
"""

from __future__ import annotations

import json
import os
import time

#: Static fallback order. Seeded from the round-4 hardware measurements
#: (docs/PERF.md: dense-bp 22.5 / dense 23.2-at-probe / gt-bp 5.8-7.0 /
#: pallas ~3-5 / bitslice ~1.4 GB/s at 256 MiB after the dense-relayout
#: fix): the dense pair leads — hardware-proven fastest, Mosaic-compiled
#: on-device (104/104 smoke) and gated deviceless every CI run
#: (scripts/aot_check.py), with "auto" additionally carrying a runtime
#: compile-failure fallback (models/aes.py:_engine_compile_ok). Only a
#: never-measured host ever sees this order; the first probe writes the
#: real one.
DEFAULT_ORDER = ("pallas-dense-bp", "pallas-dense", "pallas-gt-bp",
                 "pallas-gt", "pallas", "bitslice")

def device_key(platform: str, device_kind: str | None = None) -> str:
    """Ranking key for a device: ``"tpu:TPU v5e"``.

    Keyed by device KIND, not bare platform: a ranking measured on one TPU
    generation must not feed ``resolve_engine("auto")`` on a different one
    (ADVICE r3): a foreign file could otherwise route production calls
    through a kernel this chip has never compiled. Falls back to the bare
    platform only when the kind is unknown or redundant (CPU reports
    device_kind == "cpu").

    Deliberately NO read-through of old bare-platform entries: a bare
    "tpu" entry could have been measured on any generation — trusting it
    is exactly the hazard this key exists to remove — and no
    pre-device-key ranking file was ever produced on hardware anyway
    (VERDICT r3 missing #4: the file had only ever been written by
    tests)."""
    kind = (device_kind or "").strip()
    if not kind or kind == platform:
        return platform
    return f"{platform}:{kind}"


_DEFAULT_PATH = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir, os.pardir,
    "results", "engine_ranking.json"))


def path() -> str:
    return os.environ.get("OT_ENGINE_RANKING", _DEFAULT_PATH)


#: path -> ((mtime_ns, size), parsed dict). resolve_engine("auto") calls
#: into this per crypt call on auto-engine contexts; a chunked streaming
#: loop must not pay open+parse per chunk for a file that never changes
#: mid-run. Invalidated by mtime/size, refreshed by store().
_CACHE: dict = {}


def _load_all() -> dict:
    p = path()
    try:
        st = os.stat(p)
    except OSError:
        return {}
    key = (st.st_mtime_ns, st.st_size)
    cached = _CACHE.get(p)
    if cached is not None and cached[0] == key:
        return cached[1]
    try:
        with open(p) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {}
    if not isinstance(data, dict):
        data = {}
    _CACHE[p] = (key, data)
    return data


def load(platform: str) -> dict | None:
    """The stored entry for a platform, or None if absent/malformed.

    gbps values are type-checked here (bool excluded: it IS an int) so a
    hand-edited or foreign file degrades to the static defaults instead of
    crashing order()'s sort — the probe_order() contract is that a
    left-over file can reorder probes but never crash them.
    """
    entry = _load_all().get(platform)
    if not isinstance(entry, dict):
        return None
    rk = entry.get("ranking")
    if not isinstance(rk, list) or not rk or not all(
            isinstance(r, dict) and isinstance(r.get("engine"), str)
            and isinstance(r.get("gbps", 0.0), (int, float))
            and not isinstance(r.get("gbps", 0.0), bool)
            for r in rk):
        return None
    return entry


def order(platform: str) -> list[str] | None:
    """Engine names best-first from the stored ranking, or None."""
    entry = load(platform)
    if entry is None:
        return None
    return [r["engine"] for r in sorted(
        entry["ranking"],
        key=lambda r: -float(r.get("gbps", 0.0)))]


def dropped(platform: str) -> set:
    """Engines persisted as compile-broken for this device key
    (drop_engines). Read from the raw entry — load()'s ranking validation
    must not hide a drop record that sits beside an empty ranking (the
    never-measured-host case)."""
    entry = _load_all().get(platform)
    if not isinstance(entry, dict) or not isinstance(entry.get("dropped"), list):
        return set()
    return {e for e in entry["dropped"] if isinstance(e, str)}


def probe_order(platform: str, available) -> list[str]:
    """Full probe order for bench.py: persisted measurement first, static
    defaults appended, then any other registered engine alphabetically.

    "jnp" is never probed — it is the fallback when every probe fails (and
    the slowest engine by ~40x; ranking it would burn a probe budget on an
    engine only ever chosen by default). Unknown names in a stale ranking
    (an engine since renamed/removed) are dropped, so a left-over file can
    reorder probes but never crash them. Engines persisted as
    compile-broken (drop_engines) are EXCLUDED everywhere — including the
    static-default backfill — so neither "auto" nor the bench probe stage
    re-pays a known-failing compile; recovery paths are a tune sweep that
    measures the engine successfully (store() then clears its drop) or
    deleting the ranking file.
    """
    bad = dropped(platform)
    out = [e for e in (order(platform) or [])
           if e in available and e != "jnp" and e not in bad]
    out += [e for e in DEFAULT_ORDER
            if e in available and e not in out and e not in bad]
    out += sorted(e for e in available
                  if e != "jnp" and e not in out and e not in bad)
    return out


#: Validators for persisted kernel knobs (store_knobs/knobs) — THE single
#: source of truth for what a valid tile/MC value is (ops/pallas_aes.py's
#: apply_knobs imports these instead of re-inlining the predicates; only
#: this module's import-freedom matters, and it imports nothing back).
#: Mirrors the OT_PALLAS_TILE / OT_PALLAS_MC import-time constraints.
#: Invalid values are dropped on READ, not trusted because a writer once
#: validated them — the file may be foreign or hand-edited.
def _valid_tile(v) -> bool:
    return (isinstance(v, int) and not isinstance(v, bool)
            and v > 0 and v % 128 == 0)


def _valid_tile_by_mib(v) -> bool:
    """{"<=MiB ceiling as str-int>": tile} — JSON object keys are strings,
    so the ceiling is serialized as a decimal string; values obey the same
    constraint as "tile". The map may be empty-invalid but not empty-valid:
    an empty dict stores nothing worth remembering."""
    return (isinstance(v, dict) and bool(v)
            and all(isinstance(k, str) and k.isdigit() and int(k) > 0
                    and _valid_tile(t) for k, t in v.items()))


_KNOB_VALID = {
    "tile": _valid_tile,
    "tile_by_mib": _valid_tile_by_mib,
    "mc": lambda v: v in ("perm", "roll"),
}


def knobs(platform: str) -> dict:
    """Validated tuned kernel knobs for a device key: ``{"tile": 2048,
    "mc": "roll"}`` (either key may be absent), ``{}`` when none stored.

    Unknown keys and invalid values are silently dropped — the apply site
    (ops/pallas_aes.py:apply_knobs) must only ever see values the module's
    own import-time validation would have accepted.
    """
    entry = _load_all().get(platform)
    if not isinstance(entry, dict) or not isinstance(entry.get("knobs"), dict):
        return {}
    return {k: v for k, v in entry["knobs"].items()
            if k in _KNOB_VALID and _KNOB_VALID[k](v)}


def store_knobs(platform: str, kn: dict, source: str, nbytes: int) -> bool:
    """Persist the winning kernel knobs for a device key.

    Written by scripts/tune_tpu.py when a sweep's overall-best config used
    tile/MC values worth remembering; read back by bench.py and the tpu
    harness backend via ``knobs()`` so the next headline run reproduces the
    tuned configuration instead of the static defaults (VERDICT r3 #7: a
    tune sweep whose winner nothing applies is a measurement, not an
    optimization). Invalid values are rejected here too (defense on both
    sides of the file). Returns True iff the file was written.
    """
    clean = {k: v for k, v in kn.items()
             if k in _KNOB_VALID and _KNOB_VALID[k](v)}
    if not clean:
        return False
    data = dict(_load_all())
    entry = data.get(platform)
    entry = dict(entry) if isinstance(entry, dict) else {"ranking": []}
    entry["knobs"] = {**clean, "source": source, "bytes": int(nbytes),
                      "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S")}
    data[platform] = entry
    return _write_all(data)


def store(platform: str, gbps_by_engine: dict, source: str,
          nbytes: int, drop=()) -> bool:
    """Persist a measured {engine: GB/s} ranking for a platform.

    Rankings of fewer than two engines are not stored: a single data point
    is not an order, and overwriting a real multi-engine ranking with it
    would LOSE information. MERGE semantics for the same reason: engines
    already ranked for the platform but absent from this measurement (a
    deadline-truncated probe stage measures only the favourites) keep
    their previous numbers instead of being deleted — re-measured engines
    update. Returns True iff the file was written. Atomic (write-aside +
    rename) so a crashed writer can't leave a torn file for the next
    reader — a torn JSON would silently demote every later run to the
    static defaults.

    ``drop`` lists engines to REMOVE from the stored entry even where a
    previous run ranked them (bench.py passes its digest-dissenting
    engines: an engine just proven to compute wrong bytes must not be
    resurrected into "auto" selection by the merge).
    """
    real = {e: float(g) for e, g in gbps_by_engine.items() if g > 0.0}
    if len(real) < 2:
        return False
    # Shallow copy: _load_all() returns the CACHED dict, and mutating it in
    # place would make a FAILED write leave a phantom never-persisted entry
    # visible to every later in-process load()/order() call (and a later
    # successful store for another platform would persist it). Top-level
    # copy suffices — the previous entry is only read, never mutated.
    data = dict(_load_all())
    prev = data.get(platform)
    merged = dict(real)
    if isinstance(prev, dict) and isinstance(prev.get("ranking"), list):
        for r in prev["ranking"]:
            if (isinstance(r, dict) and isinstance(r.get("engine"), str)
                    and r["engine"] not in merged):
                try:
                    merged[r["engine"]] = float(r.get("gbps", 0.0))
                except (TypeError, ValueError):
                    pass
    for e in drop:
        merged.pop(e, None)
    entry = {
        "ranking": [{"engine": e, "gbps": round(g, 4)}
                    for e, g in sorted(merged.items(), key=lambda kv: -kv[1])],
        "source": source,
        "bytes": int(nbytes),
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    # Preserve the compile-failure drop record (drop_engines) across probe
    # stores — MINUS any engine this measurement actually ran: a successful
    # measurement is proof the compile works now (e.g. after a jax/libtpu
    # upgrade, via a tune sweep that names the engine explicitly), and is
    # the drop record's designed recovery path.
    prev_dropped = set()
    if isinstance(prev, dict) and isinstance(prev.get("dropped"), list):
        prev_dropped = {e for e in prev["dropped"] if isinstance(e, str)}
    still_dropped = prev_dropped - set(real)
    if still_dropped:
        entry["dropped"] = sorted(still_dropped)
        if isinstance(prev.get("drop_reasons"), dict):
            reasons = {e: r for e, r in prev["drop_reasons"].items()
                       if e in still_dropped}
            if reasons:
                entry["drop_reasons"] = reasons
    # Tuned knobs survive ranking re-stores unchanged: a bench probe
    # measures ENGINES (under whatever knobs are applied), it never
    # re-measures the knob grid — only store_knobs() writes that record.
    if isinstance(prev, dict) and isinstance(prev.get("knobs"), dict):
        entry["knobs"] = prev["knobs"]
    data[platform] = entry
    return _write_all(data)


def drop_engines(platform: str, engines, reason: str | None = None) -> bool:
    """Persist `engines` as compile-broken for `platform`.

    The persistence half of the compile-failure fallback
    (models/aes.py:_engine_compile_ok): an engine that failed to compile on
    this device must not be offered to any later process — probe_order()
    excludes the recorded set everywhere, including its static-default
    backfill. Works with or without a prior entry (a fresh host has no
    ranking yet, but the drop must still stick); also removes the engines
    from the stored ranking list. Unlike store(), a resulting ranking of
    < 2 engines (or zero) is kept: this records known-bad data, not a new
    ordering. Returns True iff the file changed.

    ``reason`` is recorded per engine in ``drop_reasons`` (VERDICT r4 #4:
    a drop record a future maintainer cannot re-derive is a landmine, so
    the file must say WHY — e.g. "chained bench form RESOURCE_EXHAUSTED at
    256 MiB"). The recovery path clears the reason with the drop: store()
    removes both when a measurement runs the engine successfully. Note
    store()'s two-engine floor applies to the recovery too — a sweep must
    measure the dropped engine AND at least one other, or nothing is
    written and the drop stands.
    """
    data = dict(_load_all())
    entry = data.get(platform)
    if not isinstance(entry, dict):
        entry = {"ranking": []}
    ranking_list = entry.get("ranking")
    if not isinstance(ranking_list, list):
        ranking_list = []
    bad = {e for e in engines if isinstance(e, str)}
    kept = [r for r in ranking_list
            if not (isinstance(r, dict) and r.get("engine") in bad)]
    prev_dropped = {e for e in entry.get("dropped", [])
                    if isinstance(e, str)} if isinstance(
                        entry.get("dropped"), list) else set()
    new_dropped = prev_dropped | bad
    prev_reasons = (dict(entry["drop_reasons"])
                    if isinstance(entry.get("drop_reasons"), dict) else {})
    reasons = dict(prev_reasons)
    if reason:
        reasons.update({e: str(reason) for e in bad})
    reasons = {e: r for e, r in reasons.items() if e in new_dropped}
    if (len(kept) == len(ranking_list) and new_dropped == prev_dropped
            and reasons == prev_reasons):
        return False
    new_entry = {**entry, "ranking": kept,
                 "dropped": sorted(new_dropped),
                 "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S")}
    if reasons:
        new_entry["drop_reasons"] = reasons
    else:
        new_entry.pop("drop_reasons", None)
    data[platform] = new_entry
    return _write_all(data)


def _write_all(data: dict) -> bool:
    p = path()
    tmp = f"{p}.tmp.{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, p)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False
    _CACHE.pop(p, None)
    return True
