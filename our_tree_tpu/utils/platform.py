"""Honor a caller's JAX_PLATFORMS=cpu pin *through jax.config*.

The env var alone is not enough on hosts whose site hooks pre-register an
accelerator plugin at interpreter start: the plugin initializes the device
backend regardless, and on a tunnelled single-tenant TPU host that means a
"CPU" run blocks on a wedged tunnel at its first device op (observed: the
decrypt CLI hanging 180 s under JAX_PLATFORMS=cpu — found by round-3
verification). tests/conftest.py, repo-root bench.py, and the fuzzer each
carry this re-assertion; this helper is the one shared home for the CLI
entry points, so the next entry point cannot forget it.

The update only binds while no backend has been initialized yet (it is a
silent no-op afterwards) — call it FIRST in main(), before any jax-touching
work.
"""

from __future__ import annotations

import os


def pin_cpu_if_requested() -> None:
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
