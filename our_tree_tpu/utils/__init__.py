"""Byte/word packing helpers shared by every layer."""

from .packing import (  # noqa: F401
    bytes_to_hex,
    byteswap32,
    hex_to_bytes,
    jnp_bytes_to_words,
    jnp_words_to_bytes,
    np_bytes_to_words,
    np_words_to_bytes,
)
