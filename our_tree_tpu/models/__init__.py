"""Cipher models: AES contexts/modes, phase-split ARC4, fused RC4."""

from .aes import AES, AES_DECRYPT, AES_ENCRYPT  # noqa: F401
from .base import DIR_BOTH, DIR_DECRYPT, DIR_ENCRYPT, AESCipher, BlockCipher  # noqa: F401
from .arc4 import ARC4  # noqa: F401
from .rc4 import RC4  # noqa: F401
