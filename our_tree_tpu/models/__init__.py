"""Cipher models: AES contexts/modes, AES-GCM seal/open, phase-split
ARC4, fused RC4."""

from .aes import AES, AES_DECRYPT, AES_ENCRYPT  # noqa: F401
from .base import DIR_BOTH, DIR_DECRYPT, DIR_ENCRYPT, AESCipher, BlockCipher  # noqa: F401
from .arc4 import ARC4  # noqa: F401
from .rc4 import RC4  # noqa: F401
# The AEAD public API (aead/gcm.py) re-exported at the models layer —
# imported LAST: aead.gcm reaches back into models.aes, which the lines
# above have already bound on the package.
from ..aead.gcm import TagMismatchError, gcm_open, gcm_seal  # noqa: F401,E402
