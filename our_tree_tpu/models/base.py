"""Abstract block-cipher interface — parity with the reference's C++ base
class (BlockCipher.h:48-107).

The reference's GPU backend defines an abstract `BlockCipher` (pure virtuals
blockBits/blockSize/keyBits/keySize/makeKey/encrypt/decrypt, direction flags
DIR_ENCRYPT/DIR_DECRYPT/DIR_BOTH at BlockCipher.h:31-46) with `AES` as its
one subclass. This module is that interface's Python form, implemented by
`AESCipher` over the framework's engine-selectable contexts; a second cipher
family would subclass `BlockCipher` the same way the reference intended.

The reference's byte2int/int2byte conversion virtuals are replaced by the
framework-wide packing convention (utils/packing.py) rather than per-cipher
methods — one byte-order decision for the whole framework (SURVEY.md §7
layer 1) instead of one per backend, which is exactly how the reference's
two backends ended up with conflicting endianness (aes.c LE vs AES.cu BE).
"""

from __future__ import annotations

import abc

import numpy as np

#: Direction flags, values as in the reference (BlockCipher.h:31-46).
DIR_ENCRYPT = 1
DIR_DECRYPT = 2
DIR_BOTH = DIR_ENCRYPT | DIR_DECRYPT


class BlockCipher(abc.ABC):
    """A keyed block cipher over n-block byte buffers."""

    @property
    @abc.abstractmethod
    def block_bits(self) -> int: ...

    @property
    def block_size(self) -> int:
        return self.block_bits // 8

    @property
    @abc.abstractmethod
    def key_bits(self) -> int: ...

    @property
    def key_size(self) -> int:
        return self.key_bits // 8

    @abc.abstractmethod
    def make_key(self, key: bytes, direction: int = DIR_BOTH) -> None:
        """Install a key for the given direction(s) (makeKey,
        BlockCipher.h:74-83)."""

    @abc.abstractmethod
    def encrypt(self, data) -> np.ndarray:
        """Bulk-encrypt a multiple of block_size bytes."""

    @abc.abstractmethod
    def decrypt(self, data) -> np.ndarray:
        """Bulk-decrypt a multiple of block_size bytes."""


class AESCipher(BlockCipher):
    """The framework's AES behind the BlockCipher interface.

    `engine` selects the compute core ("auto"/"jnp"/"bitslice"/"pallas");
    the reference's analogue of this choice was picking a build directory.
    """

    def __init__(self, key: bytes | None = None, engine: str = "auto"):
        self._engine = engine
        self._ctx = None
        self._direction = 0
        if key is not None:
            self.make_key(key)

    @property
    def block_bits(self) -> int:
        return 128

    @property
    def key_bits(self) -> int:
        if self._ctx is None:
            raise ValueError("no key installed")
        return len(self._ctx.key) * 8

    def make_key(self, key: bytes, direction: int = DIR_BOTH) -> None:
        from .aes import AES

        self._ctx = AES(bytes(key), engine=self._engine)
        self._direction = direction

    def _require(self, direction: int):
        if self._ctx is None:
            raise ValueError("no key installed")
        if not (self._direction & direction):
            raise ValueError("key not installed for this direction")

    def encrypt(self, data) -> np.ndarray:
        from .aes import AES_ENCRYPT

        self._require(DIR_ENCRYPT)
        return self._ctx.crypt_ecb(AES_ENCRYPT, data)

    def decrypt(self, data) -> np.ndarray:
        from .aes import AES_DECRYPT

        self._require(DIR_DECRYPT)
        return self._ctx.crypt_ecb(AES_DECRYPT, data)
