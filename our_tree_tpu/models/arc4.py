"""ARC4 stream cipher with the reference's three-phase split.

The reference's one original design idea (SURVEY.md §0) is splitting RC4 into
a sequential keystream-generation phase and a data-parallel XOR phase
(`arc4_prep` / `arc4_crypt`, reference arc4.c:72-112, vs the usual fused
loop). That phase split *is* this framework's sequence-parallelism story, so
the three-phase API is preserved exactly:

  * `setup`   — key schedule, 256 sequential swaps (reference arc4.c:43-67).
    Host-side numpy: tiny, inherently serial.
  * `prep`    — keystream generation. An O(n) recurrence with 258 bytes of
    state `{x, y, m[256]}`; expressed as a `lax.scan` whose carry is exactly
    that state, so a stream can be generated in chunks and resumed — the
    scan carry is the reference's cross-call resumability (arc4.c:93-94).
    A numpy fallback exists for host-only use.
  * `crypt`   — pure XOR of data against keystream (arc4.c:101-112);
    embarrassingly parallel, batched on device, shardable across chips.

State convention matches `arc4_context {x, y, m[256]}` (arc4.h:35-41).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


def key_schedule(key: bytes | np.ndarray) -> np.ndarray:
    """KSA: returns the initial 256-byte permutation (uint8)."""
    key = np.frombuffer(bytes(key), dtype=np.uint8) if isinstance(key, (bytes, bytearray)) else np.asarray(key, np.uint8)
    m = np.arange(256, dtype=np.int64)
    j = 0
    for i in range(256):
        j = (j + int(m[i]) + int(key[i % len(key)])) & 0xFF
        m[i], m[j] = m[j], m[i]
    return m.astype(np.uint8)


def keystream_np(state: tuple[int, int, np.ndarray], length: int):
    """Host PRGA: returns (keystream, new_state). Oracle for the scan path."""
    x, y, m = state
    m = m.astype(np.int64).copy()
    ks = np.empty(length, dtype=np.uint8)
    for i in range(length):
        x = (x + 1) & 0xFF
        a = m[x]
        y = (y + a) & 0xFF
        b = m[y]
        m[x] = b
        m[y] = a
        ks[i] = m[(a + b) & 0xFF]
    return ks, (x, y, m.astype(np.uint8))


@functools.partial(jax.jit, static_argnums=(1, 2))
def keystream_scan(state, length: int, unroll: int = 8):
    """PRGA as an XLA scan. state = (x, y, m) with x,y uint32 scalars and m
    a (256,) uint32 permutation; returns ((x', y', m'), keystream uint8).

    One byte per scan step with two dynamic scatter updates — the honest
    sequential baseline, exactly as the reference's keygen loop is the
    sequential baseline there (arc4.c:82-91 at 0.037 GB/s, results.myth.1:38).
    `unroll` inlines that many steps per scan iteration (SURVEY.md §7 hard
    part #3's mitigation: amortise loop overhead over the recurrence).
    """

    def step(carry, _):
        x, y, m = carry
        x = (x + 1) & 0xFF
        a = m[x]
        y = (y + a) & 0xFF
        b = m[y]
        m = m.at[x].set(b).at[y].set(a)
        out = m[(a + b) & 0xFF]
        return (x, y, m), out.astype(jnp.uint8)

    carry, ks = jax.lax.scan(step, state, None, length=length, unroll=unroll)
    return carry, ks


@functools.partial(jax.jit, static_argnums=(1, 2))
def keystream_scan_batch(states, length: int, unroll: int = 8):
    """Many independent keystreams at once: vmap over the stream axis.

    The scan is inherently sequential *within* a stream; across streams it
    is embarrassingly parallel — the batch axis fills the VPU lanes the way
    CTR's counter axis does. states = (x, y, m) with shapes ((S,), (S,),
    (S, 256)); returns ((x', y', m'), keystream (S, length) uint8).
    """
    return jax.vmap(lambda st: keystream_scan(st, length, unroll))(states)


def crypt(data: jnp.ndarray, keystream: jnp.ndarray) -> jnp.ndarray:
    """Phase 3: XOR (device, parallel)."""
    return jnp.bitwise_xor(data, keystream)


@jax.jit
def xor_words(words: jnp.ndarray, ks_words: jnp.ndarray) -> jnp.ndarray:
    """The served XOR phase on the serve stack's packed uint32 word
    layout (serve/batcher.py): ciphertext words = payload words XOR
    cached keystream words. Key-oblivious and constant-time — no
    secret-indexed access at all — so many sessions' chunks coalesce
    into one dispatch exactly like multikey CTR (the jaxpr audit pins
    this CLEAN; the secret-indexed PRGA lives in prep, not here)."""
    return jnp.bitwise_xor(words, ks_words)


@functools.partial(jax.jit, static_argnums=(2, 3))
def prep_batch_words(m_words: jnp.ndarray, xy_words: jnp.ndarray,
                     length: int, unroll: int = 8) -> jnp.ndarray:
    """The served batched-PRGA entry: many sessions' sequential scans in
    one vmapped dispatch, on the flat uint32 array layout the lane seam
    ships (serve/lanes.py ``mode="rc4-prep"``).

    ``m_words`` is the (S*256,) flattened permutation stack, ``xy_words``
    the (2*S,) x/y stack ([x0..xS-1, y0..yS-1]); ``length`` (bytes per
    session, multiple of 4) is static so the serve prefetcher's fixed
    (S, length) quantum is ONE compiled shape — zero-recompile holds.
    Returns (S, 258 + length//4) uint32: per session ``[x', y', m'[256],
    keystream packed little-endian 4 bytes/word]`` — carry and keystream
    in one fenceable array, a pure function of the inputs, so bit-exact
    failover replay on another lane is byte-identical by construction.
    """
    s = xy_words.shape[0] // 2
    m = m_words.reshape(s, 256)
    (x2, y2, m2), ks = keystream_scan_batch(
        (xy_words[:s], xy_words[s:], m), length, unroll)
    k = ks.reshape(s, length // 4, 4).astype(jnp.uint32)
    ks_words = (k[..., 0] | (k[..., 1] << 8)
                | (k[..., 2] << 16) | (k[..., 3] << 24))
    return jnp.concatenate(
        [x2[:, None], y2[:, None], m2, ks_words], axis=1)


@dataclass
class ARC4:
    """arc4_context equivalent: holds {x, y, m} across calls."""

    key: bytes

    def __post_init__(self):
        self.x = 0
        self.y = 0
        self.m = key_schedule(self.key)

    def prep(self, length: int, backend: str = "jax") -> np.ndarray:
        """Generate `length` keystream bytes, advancing internal state."""
        if backend == "np":
            ks, (self.x, self.y, self.m) = keystream_np((self.x, self.y, self.m), length)
            return ks
        state = (jnp.uint32(self.x), jnp.uint32(self.y), jnp.asarray(self.m, jnp.uint32))
        (x, y, m), ks = keystream_scan(state, length)
        self.x, self.y = int(x), int(y)
        self.m = np.asarray(m, dtype=np.uint8)
        return np.asarray(ks)

    @staticmethod
    def batch_states(keys: list[bytes]):
        """KSA for many keys -> the (x, y, m) state stacks the batch scan
        takes: ((S,), (S,), (S, 256)) uint32. The one construction shared
        by prep_batch and the sharded bench path (backends.py)."""
        ms = np.stack([key_schedule(k) for k in keys]).astype(np.uint32)
        return (
            jnp.zeros(len(keys), jnp.uint32),
            jnp.zeros(len(keys), jnp.uint32),
            jnp.asarray(ms),
        )

    @staticmethod
    def prep_batch(keys: list[bytes], length: int) -> np.ndarray:
        """Keystreams for many independent keys in one device call.

        Multi-stream parallelism: sequence-level work that cannot be
        parallelised within a stream scales across streams instead (the
        batch axis is the parallel axis, like CTR's counter axis). Returns
        (len(keys), length) uint8.
        """
        _, ks = keystream_scan_batch(ARC4.batch_states(keys), length)
        return np.asarray(ks)

    def crypt(self, data, keystream=None) -> np.ndarray:
        """XOR data with keystream (generated here if not supplied)."""
        d = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else np.asarray(data, np.uint8)
        if keystream is None:
            keystream = self.prep(d.size)
        return np.asarray(crypt(jnp.asarray(d), jnp.asarray(keystream, dtype=jnp.uint8)))
