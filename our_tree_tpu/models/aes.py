"""AES cipher contexts and block modes (ECB / CBC / CFB128 / CTR).

API parity target is the reference C interface (aes-modes/aes.h:62-161):
key setup for both directions over an `aes_context`, bulk mode functions that
carry resumable stream state (`iv`, `iv_off`, `nonce_counter`, `stream_block`,
`nc_off`). Those resume offsets are the reference's miniature
checkpoint/restore system (SURVEY.md §5) and are preserved here so chunked /
streaming encryption produces byte-identical output to one-shot calls.

Mode dataflow is chosen for the hardware, not transliterated
(SURVEY.md §2 parallelism table):

  * ECB — embarrassingly parallel: one batched call over all blocks
    (reference: pthread chunks, aes-modes/test.c:33-35).
  * CTR — keystream block k = E(counter0 + k); counters are materialised with
    an iota and encrypted in one batch (reference: sequential per-block
    increment, aes-modes/aes.c:869-901; the *semantics* — post-increment
    big-endian 128-bit counter — are matched bit-for-bit).
  * CBC encrypt / CFB128 encrypt — true recurrences, expressed as `lax.scan`
    over blocks (reference: while-loops, aes.c:757-816, aes.c:822-863).
  * CBC decrypt / CFB128 decrypt — the recurrence reads only *ciphertext*,
    so decryption is fully parallel: batch-decrypt all blocks and XOR against
    the shifted ciphertext stream.

The compute engine is selectable: "jnp" (T-table gather core, ops/block.py)
or "bitslice" (bit-plane engine, ops/bitslice.py — the TPU throughput path).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import block
from ..ops.keyschedule import expand_key_dec, expand_key_enc
from ..utils import packing

AES_ENCRYPT = 1
AES_DECRYPT = 0


# ---------------------------------------------------------------------------
# Engine registry: pluggable (words, rk, nr) -> words block cores behind one
# functional surface. "jnp" is the T-table correctness core; throughput
# engines ("bitslice", "pallas") register themselves at import (bottom of
# this module) so every mode/ shard path picks them up by name.
# ---------------------------------------------------------------------------

CORES: dict[str, tuple] = {"jnp": (block.encrypt_words, block.decrypt_words)}

#: Optional fused-CTR fast paths: (words, ctr_be_words, rk, nr) -> words
#: where the counter for block i is ctr_be + i (128-bit BE semantics),
#: keeping the keystream — and, for counter-synthesising kernels, the
#: counter stream itself — on-chip instead of materialising it in HBM.
#: Engines without an entry fall back to the layered keystream-then-XOR
#: path. Both the single-device dispatcher (ctr_crypt_words) and the
#: sharded one (parallel/dist.py:_ctr_shard_body, which pre-offsets
#: ctr_be to the shard's first block) consult this registry.
CTR_FUSED: dict[str, object] = {}


#: Engines whose cores route into pl.pallas_call — the set parallel/dist.py
#: keys its interpreter-mode check_vma workaround on (a name prefix would
#: silently extend the workaround to any future engine that happens to be
#: named "pallas-…" without being kernel-backed).
PALLAS_BACKED: set[str] = set()

#: Multi-key scattered-CTR cores: (words2, ctr2, rks, key_slots, nr) ->
#: words2, where rks is a (K, 4*(nr+1)) stack of expanded schedules and
#: key_slots a (N,) PUBLIC per-block slot-index vector — one device call
#: carrying K tenants' keys (the serve rung-packer's dispatch shape).
#: Engines without an entry fall back to the generic bitsliced
#: per-block-key-planes circuit inside the jit (still one call, still
#: shape-closed); see ctr_crypt_words_scattered_multikey.
MULTIKEY_CTR: dict[str, object] = {}

#: The host-tier engine name: the native C runtime (AESNI where the CPU
#: has it) dispatched directly from the serve seam — no jit, no XLA, no
#: compile cache. Deliberately NOT in CORES: it has no traced core, so
#: the mode dispatchers and the jaxpr auditor never see it; only the
#: scattered-CTR serve entry points accept it (resolve_serve_engine).
NATIVE_ENGINE = "native"


def register_core(name: str, encrypt_fn, decrypt_fn, ctr_fused_fn=None,
                  pallas_backed: bool = False, multikey_fn=None) -> None:
    CORES[name] = (encrypt_fn, decrypt_fn)
    if ctr_fused_fn is not None:
        CTR_FUSED[name] = ctr_fused_fn
    if pallas_backed:
        PALLAS_BACKED.add(name)
    if multikey_fn is not None:
        MULTIKEY_CTR[name] = multikey_fn


#: engine -> whether its encrypt core compiled+ran on this process's device
#: (None while unprobed). In-process memo for _engine_compile_ok.
_COMPILE_OK: dict[str, bool] = {}


def _engine_compile_ok(eng: str, rank_key: str) -> bool:
    """Can `eng` actually compile and execute on the attached device?

    The compile-failure fallback VERDICT r3 #2 asked for: "auto" must not
    route production calls through a kernel the device cannot compile (the
    dense-layout engines were shipped interpreter-verified only — Mosaic
    has never seen them, and a first-contact compile failure was a live,
    acknowledged risk with no handler). One tiny batch (32 blocks, tile 1)
    through the engine's encrypt core AND its fused-CTR entry (the
    production "auto" CTR path dispatches through CTR_FUSED, a different
    kernel — probing only encrypt would leave the flagship path unprobed).

    Skipped entirely when the stored ranking holds a measurement for this
    engine under this device key — a measured GB/s is proof the kernels
    compiled and ran here, and the probe would just tax every process's
    first resolve. Failure policy by phase: a Mosaic LOWERING failure
    (host-local, deterministic, no tunnel involved) is memoized and —
    when no tuning env overrides are active — PERSISTED as a drop
    (utils/ranking.py:drop_engines) that probe_order() excludes
    everywhere, so no later process re-pays it; under OT_PALLAS_TILE /
    OT_PALLAS_MC / OT_SBOX overrides the failure may be the CONFIG's
    fault, so it stays process-local. PJRT compile or execution failures
    (indistinguishable from tunnel/RPC hiccups) are always process-local.

    Never probes under an ambient trace (running a jax computation inside
    another trace misclassifies — same hazard as parallel/dist.py's
    _vma_drop_bug); there it reports True and lets the real call surface
    the error loudly.
    """
    cached = _COMPILE_OK.get(eng)
    if cached is not None:
        return cached
    try:
        from jax._src import core as _core  # no public trace-state API yet
        if not _core.trace_state_clean():
            return True
    except Exception:
        pass
    import os
    import sys

    from ..utils import ranking

    # Steady-state short-circuit: a stored gbps for this engine under this
    # very device key means a probe/tune MEASURED it here — its kernels
    # compiled and executed. Skipping the probe saves two Mosaic compiles
    # per process on every healthy host; if a later regression (e.g. a
    # libtpu upgrade) breaks the kernel, the real call fails loudly and
    # the next bench probe re-ranks.
    entry = ranking.load(rank_key)
    if entry is not None and any(
            r.get("engine") == eng and r.get("gbps", 0.0) > 0.0
            for r in entry["ranking"]):
        _COMPILE_OK[eng] = True
        return True

    nr, rk = expand_key_enc(b"\x00" * 16)
    w = jnp.zeros((32, 4), jnp.uint32)
    rk = jnp.asarray(rk)
    ctr = jnp.zeros(4, jnp.uint32)
    enc_fn = CORES[eng][0]
    targets = [("enc", lambda: jax.jit(lambda a, b: enc_fn(a, b, nr))
                .trace(w, rk), (w, rk))]
    fused = CTR_FUSED.get(eng)
    if fused is not None:
        targets.append(("ctr",
                        lambda: jax.jit(lambda a, c, b: fused(a, c, b, nr))
                        .trace(w, ctr, rk), (w, ctr, rk)))
    for label, trace_fn, args in targets:
        # Three phases, three failure policies:
        #   lower()   — host-local Pallas->Mosaic lowering, deterministic,
        #               no tunnel involved: a failure is durable and
        #               (under default config) PERSISTED as a ranking drop.
        #   compile() — goes through the PJRT runtime, where a genuine
        #               Mosaic-backend error is indistinguishable from a
        #               tunnel/RPC hiccup: fail safe, process-local only.
        #   execute   — transient by default: process-local only.
        try:
            lowered = trace_fn().lower()
        except Exception as e:
            tuned = [k for k in ("OT_PALLAS_TILE", "OT_PALLAS_MC",
                                 "OT_SBOX", "OT_BITSLICE_UNROLL")
                     if os.environ.get(k)]
            # Non-default EFFECTIVE knobs count as overrides too: stored
            # tuned knobs (pallas_aes.apply_stored_knobs, no env involved)
            # can make a lowering fail that succeeds under defaults — that
            # must not be persisted as a durable engine drop any more than
            # an env override's failure would be.
            from ..ops import pallas_aes as _pa
            if _pa.TILE != _pa.DEFAULT_TILE:
                tuned.append(f"tile={_pa.TILE}")
            if _pa.MC_LOWERING != _pa.DEFAULT_MC:
                tuned.append(f"mc={_pa.MC_LOWERING}")
            if tuned:
                # The failure may be the override's fault, not the
                # engine's — don't poison default-config processes.
                print(f"# engine {eng}:{label}: lowering failed under "
                      f"tuning overrides {tuned}; skipping for this "
                      f"process only ({type(e).__name__}: {str(e)[:200]})",
                      file=sys.stderr)
            else:
                print(f"# engine {eng}:{label}: Mosaic lowering failed "
                      f"({type(e).__name__}); dropping from auto "
                      f"selection: {str(e)[:200]}", file=sys.stderr)
                ranking.drop_engines(
                    rank_key, (eng,),
                    reason=f"Mosaic lowering failed under default knobs "
                           f"({type(e).__name__}: {str(e)[:120]})")
            _COMPILE_OK[eng] = False
            return False
        try:
            # The probe's compile+execute is a real device dispatch and
            # rides the shared watchdog like every other one (otlint
            # dispatch-watchdog): disarmed when OT_DISPATCH_DEADLINE is
            # unset, and a wedged first-contact compile otherwise becomes
            # a DispatchTimeout (caught below — the engine is skipped
            # process-locally, same as any other probe failure).
            from ..resilience import watchdog as _watchdog

            with _watchdog.deadline(
                    _watchdog.default_deadline_s(),
                    what=f"engine compile probe {eng}:{label}"):
                jax.block_until_ready(lowered.compile()(*args))
        except Exception as e:
            print(f"# engine {eng}:{label}: lowered but failed to "
                  f"compile/execute ({type(e).__name__}); skipping for "
                  f"this process only: {str(e)[:200]}", file=sys.stderr)
            _COMPILE_OK[eng] = False
            return False
    _COMPILE_OK[eng] = True
    return True


def _note_engine_demotion(skipped: list, chosen: str) -> None:
    """Engine fallback through the shared degradation chokepoint
    (resilience.degrade): "auto" routing around a compile-broken favourite
    is the right call, but the run it happens in must carry the record —
    the bench JSON line and the sweep journal stamp it as e.g.
    ``degraded:["pallas-dense-bp->bitslice"]`` instead of the fallback
    masquerading as the measured winner."""
    from ..resilience import degrade as _degrade

    _degrade.degrade(
        f"{skipped[0]}->{chosen}",
        f"engine(s) failed the compile probe: {', '.join(skipped)}")


def resolve_engine(name: str | None = "auto") -> str:
    """Map "auto" to the best available engine for the current backend.

    The gather-based T-table core is fine on CPU; on TPU the VPU has no
    cheap 256-way gather (SURVEY.md §7 hard part #1), so batch paths use
    the bitsliced circuit — preferably through the Pallas kernels. The
    preference order is DATA when data exists: the last persisted hardware
    probe/tune ranking for this platform (utils/ranking.py, written by
    bench.py's probe stage and scripts/tune_tpu.py); the static default
    (the round-2 hardware A/B — docs/PERF.md) only seeds hosts that have
    never measured. On real hardware, a candidate Pallas engine must also
    pass a one-time compile probe (_engine_compile_ok) — the ranked
    runner-up takes over when the favourite cannot compile.
    """
    if name in (None, "auto"):
        if jax.default_backend() == "cpu":
            return "jnp"
        from ..ops import pallas_aes
        from ..utils import ranking

        # The Pallas engines only beat the XLA circuit when they actually
        # compile under Mosaic; on a non-TPU accelerator they would run in
        # interpreter mode (Python emulation) — keep the compiled circuit
        # there.
        allow_pallas = not pallas_aes.interpret_mode()
        try:
            d = jax.devices()[0]
            rank_key = ranking.device_key(
                d.platform, getattr(d, "device_kind", None))
            # Every "auto" context reproduces the tune sweep's winning
            # tile/MC (not just bench.py/TpuBackend): the persisted engine
            # ranking is measured under these knobs, so selecting by it
            # without applying them would pick by numbers this process
            # cannot reproduce. Idempotent + mtime-cached — fine per call.
            if allow_pallas:
                pallas_aes.apply_stored_knobs(d)
        except Exception:
            rank_key = jax.default_backend()
        skipped = []
        for eng in ranking.probe_order(rank_key, CORES):
            if eng not in CORES or (eng in PALLAS_BACKED and not allow_pallas):
                continue
            # Compile-probe only where a compile can actually fail: a
            # PALLAS engine on real hardware (Mosaic). The XLA engines and
            # interpreter mode have no first-contact compile risk.
            if (eng in PALLAS_BACKED and allow_pallas
                    and not _engine_compile_ok(eng, rank_key)):
                skipped.append(eng)
                continue
            if skipped:
                _note_engine_demotion(skipped, eng)
            return eng
        fallback = "bitslice" if "bitslice" in CORES else "jnp"
        if skipped:
            _note_engine_demotion(skipped, fallback)
        return fallback
    if name not in CORES:
        raise ValueError(f"unknown engine {name!r}; available: {sorted(CORES)}")
    return name


_NATIVE_OK: bool | None = None


def native_runtime_available() -> bool:
    """Can the native C runtime load (building it on first use)? Memoized:
    a failed build is reported once and the resolver falls back."""
    global _NATIVE_OK
    if _NATIVE_OK is None:
        try:
            from ..runtime import native as _native

            _native.load()
            _NATIVE_OK = True
        except Exception as e:  # noqa: BLE001 - the probe IS the question
            import sys

            print(f"# native runtime unavailable "
                  f"({type(e).__name__}: {str(e)[:160]})", file=sys.stderr)
            _NATIVE_OK = False
    return _NATIVE_OK


def resolve_serve_engine(name: str | None = "auto") -> str:
    """Engine resolution for the SERVE dispatch path (the scattered-CTR
    seam): the ranked-engine ladder plus the host tier.

    On an accelerator, "auto" is exactly ``resolve_engine`` — the
    persisted hardware ranking, pallas-dense-bp on a measured TPU, with
    the compile-probe demotion chain. On CPU, "auto" prefers the native
    C runtime (``NATIVE_ENGINE``): hardware AES-NI through one ctypes
    call per batch beats the XLA T-table oracle by orders of magnitude,
    and serving is the one path where that gap is the headline number
    (SERVE_r01 vs BENCH_r05, docs/PERF.md). A native build failure
    demotes to "jnp" through the shared degrade chokepoint. An explicit
    ``"native"`` raises loudly when the runtime cannot load — an
    operator who pinned the tier should not silently serve on the
    oracle engine.
    """
    if name == NATIVE_ENGINE:
        if not native_runtime_available():
            raise RuntimeError(
                "engine 'native' requested but the native C runtime "
                "failed to load/build (see stderr for the build error)")
        return NATIVE_ENGINE
    if name in (None, "auto") and jax.default_backend() == "cpu":
        if native_runtime_available():
            return NATIVE_ENGINE
        _note_engine_demotion([NATIVE_ENGINE], "jnp")
        return "jnp"
    return resolve_engine(name)


# ---------------------------------------------------------------------------
# Jitted functional cores (word-level). Shapes: words (N, 4) uint32.
# ---------------------------------------------------------------------------


def _as_block_words(words):
    """(N, 4) block view of a words argument that may be a flat (4N,) u32
    stream. Flat is the dense TPU *boundary* layout: a (N, 4) array at a jit
    boundary pads its 4-wide minor dim to the 128-lane tile (~32x HBM
    footprint/bandwidth); internally the compiler fuses this reshape. Every
    words-taking entry point goes through this ONE helper and restores the
    caller's shape on output, so the boundary-layout decision cannot be
    half-applied across modes."""
    return words.reshape(-1, 4) if words.ndim == 1 else words


def _engine_knobs_key(engine: str):
    """The tuned-knob component of an engine entry point's compile key.

    Pallas engines read TILE / MC_LOWERING at trace time, so a jit keyed
    only on (shape, nr, engine) would silently pin whatever knobs were
    live at FIRST trace — a pallas engine traced before apply_stored_knobs
    runs would keep default knobs for those shapes forever (ADVICE r4 #1).
    Returning the live values for pallas-backed engines makes a knob
    change a cache miss (clean recompile); None for other engines, whose
    traces don't read the knobs — keying them would only cause spurious
    recompiles.
    """
    if engine in PALLAS_BACKED:
        from ..ops import pallas_aes

        # The per-size map is part of the key: its selection is a pure
        # function of (map, shape) and shape is already a trace key, so
        # keying the map itself is what makes a map change a cache miss.
        return (pallas_aes.TILE, pallas_aes.MC_LOWERING,
                tuple(sorted(pallas_aes.TILE_BY_MIB.items())))
    return None


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _ecb_encrypt_words_jit(words, rk, nr, engine, knobs):
    del knobs  # compile-cache key only (see _engine_knobs_key)
    return CORES[engine][0](_as_block_words(words), rk, nr).reshape(words.shape)


def ecb_encrypt_words(words, rk, nr, engine="jnp"):
    """Batch ECB encrypt over (N, 4) block words or a flat (4N,) stream."""
    return _ecb_encrypt_words_jit(words, rk, nr, engine,
                                  _engine_knobs_key(engine))


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _ecb_decrypt_words_jit(words, rk_dec, nr, engine, knobs):
    del knobs
    return CORES[engine][1](_as_block_words(words), rk_dec, nr).reshape(words.shape)


def ecb_decrypt_words(words, rk_dec, nr, engine="jnp"):
    """Batch ECB decrypt; flat-stream contract of ecb_encrypt_words."""
    return _ecb_decrypt_words_jit(words, rk_dec, nr, engine,
                                  _engine_knobs_key(engine))


def _add_counter_be(ctr_be: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """128-bit big-endian add: (4,) u32 BE words + (N,) u32 -> (N, 4).

    Matches the reference's byte-ripple increment (aes-modes/aes.c:879-884)
    vectorised: word 3 is least significant; carries ripple upward.
    """
    s3 = ctr_be[3] + idx
    c3 = (s3 < idx).astype(jnp.uint32)
    s2 = ctr_be[2] + c3
    c2 = c3 & (s2 == 0).astype(jnp.uint32)
    s1 = ctr_be[1] + c2
    c1 = c2 & (s1 == 0).astype(jnp.uint32)
    s0 = ctr_be[0] + c1
    return jnp.stack([jnp.broadcast_to(s0, idx.shape), jnp.broadcast_to(s1, idx.shape),
                      jnp.broadcast_to(s2, idx.shape), s3], axis=-1)


def ctr_le_blocks(ctr_be_words, idx):
    """Counter blocks counter0+idx as the (N, 4) u32 LE words the cipher
    consumes. Owns the BE-add + byte-order conversion for every path that
    *materialises* counter words (layered keystream, non-fused shards).
    Counter-synthesising fused kernels don't materialise words at all —
    they share `_add_counter_be` for seam offsets and re-derive the same
    byte-plane mapping bitwise (ops/pallas_aes.py:_ctr_planes_from_base);
    tests/test_pallas.py pins the two formulations against each other
    across multi-word carries.

    The cipher consumes LE-packed words of the counter's byte stream; the
    counter bytes are the BE words' bytes, so each word is byteswapped.
    """
    return packing.byteswap32(_add_counter_be(ctr_be_words, idx))


@functools.partial(jax.jit, static_argnums=(2, 4, 5))
def _ctr_keystream_words_jit(ctr_be_words, rk, nr, nblocks_idx, engine,
                             knobs):
    del knobs
    return CORES[engine][0](ctr_le_blocks(ctr_be_words, nblocks_idx), rk, nr)


def ctr_keystream_words(ctr_be_words, rk, nr, nblocks_idx, engine="jnp"):
    """Keystream for blocks counter0+idx. ctr_be_words: (4,) u32 BE."""
    return _ctr_keystream_words_jit(ctr_be_words, rk, nr, nblocks_idx,
                                    engine, _engine_knobs_key(engine))


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _ctr_crypt_words_jit(words, ctr_be_words, rk, nr, engine, knobs):
    del knobs
    w2 = _as_block_words(words)
    fused = CTR_FUSED.get(engine)
    if fused is not None:
        # Fused kernel: neither the keystream nor (for counter-synthesising
        # kernels) the counter stream round-trips through HBM
        # (ops/pallas_aes.py:ctr_crypt_words_gen).
        out = fused(w2, ctr_be_words, rk, nr)
    else:
        idx = jnp.arange(w2.shape[0], dtype=jnp.uint32)
        out = w2 ^ ctr_keystream_words(ctr_be_words, rk, nr, idx, engine)
    return out.reshape(words.shape)


def ctr_crypt_words(words, ctr_be_words, rk, nr, engine="jnp"):
    """CTR over (N, 4) u32 block words — or a flat (4N,) u32 stream.

    Flat inputs exist for the jit *boundary*: a (N, 4) boundary array gets
    the default TPU layout with its 4-wide minor dim padded to the 128-lane
    tile (~32x HBM footprint and bandwidth on staging and readback); a flat
    stream lays out densely, and the (N, 4) view below is internal, where
    the compiler fuses the reshape instead of materialising the padded
    form. Same byte semantics either way.
    """
    return _ctr_crypt_words_jit(words, ctr_be_words, rk, nr, engine,
                                _engine_knobs_key(engine))


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _ctr_crypt_words_scattered_jit(words, ctr_le_words, rk, nr, engine,
                                   knobs):
    del knobs
    ks = CORES[engine][0](_as_block_words(ctr_le_words), rk, nr)
    return (words.reshape(-1) ^ ks.reshape(-1)).reshape(words.shape)


def ctr_crypt_words_scattered(words, ctr_le_words, rk, nr, engine="jnp"):
    """CTR where every block's counter is given EXPLICITLY, not derived
    from one base: (N, 4) u32 LE counter words (or a flat (4N,) stream)
    alongside the (N, 4)/(4N,) data words.

    This is the serving seam (serve/batcher.py): a batch coalesces many
    independent requests under one key, and each request's counter stream
    starts at its OWN nonce — there is no single ``ctr_be + i`` law across
    the concatenation, so the fused single-base kernels don't apply. CTR
    is ECB over the counter stream XOR the data, so the dispatch is one
    batched engine call over the scattered counters (every engine,
    including Pallas, through its ECB core) — same shape contract as
    ``ecb_encrypt_words``, keystream never materialised separately from
    the XOR under jit. Callers build the per-request counter blocks with
    ``utils.packing.np_ctr_le_blocks`` (host) or ``ctr_le_blocks``
    (traced); padding blocks may carry any counter value (their output is
    discarded by construction).

    ``engine="native"`` dispatches the whole call on the host tier
    instead: one threaded ECB over the counter bytes through the native
    C runtime (AESNI where the CPU has it) plus a vectorised XOR — no
    jit, no compile cache, numpy in and numpy out. That is the serve
    path's CPU fallback rung in the engine ladder
    (``resolve_serve_engine``; docs/SERVING.md has the tier table).
    """
    if engine == NATIVE_ENGINE:
        from ..runtime import native as _native

        w = np.asarray(words)
        ctx = _native.aes_ctx_from_schedule(
            int(nr), np.asarray(rk, dtype=np.uint32))
        out = _native.ctr_scattered_words(
            [ctx], w.reshape(-1),
            np.asarray(ctr_le_words, dtype=np.uint32).reshape(-1))
        return out.reshape(w.shape)
    return _ctr_crypt_words_scattered_jit(words, ctr_le_words, rk, nr,
                                          engine, _engine_knobs_key(engine))


def _multikey_jnp(w2, c2, rks, key_slots, nr):
    """T-table multi-key core: gather each block's schedule by its PUBLIC
    slot index and vmap the oracle core over blocks. The per-round
    T-table gathers stay the documented jnp timing-channel tradeoff
    (baselined, like every jnp entry); the key-index gather itself is
    public-indexed and audits clean."""
    rkb = rks[key_slots]  # (N, 4*(nr+1)) — public gather
    ks = jax.vmap(lambda c, r: block.encrypt_words(c, r, nr))(c2, rkb)
    return w2 ^ ks


def _multikey_bitslice(w2, c2, rks, key_slots, nr):
    """Bitsliced multi-key core: the same public schedule gather feeding
    genuine per-block key planes (ops/bitslice.py:multikey_planes) — the
    round circuit is key-oblivious, so K keys cost one extra to_planes
    pass over the gathered schedules, not a new formulation."""
    from ..ops import bitslice as _bs

    ks = _bs.encrypt_words_multikey(c2, rks[key_slots], nr)
    return w2 ^ ks


MULTIKEY_CTR["jnp"] = _multikey_jnp
MULTIKEY_CTR["bitslice"] = _multikey_bitslice


#: Multi-key CBC-DECRYPT cores: (cipher2, prev2, rks_dec, key_slots,
#: nr) -> plain2, where prev2 is the shifted ciphertext stream (IV at
#: each request's first block) the batcher materialises host-side —
#: P_i = D(C_i) ^ C_{i-1} reads only ciphertext, so decryption is
#: data-parallel even though encryption is a true recurrence (the
#: reference does BOTH serially, aes.c:757-816). Same fixed-K stacked
#: dispatch shape as MULTIKEY_CTR, with the DECRYPT (InvMixColumns-
#: folded) schedule stack; engines without an entry fall back to the
#: bitsliced circuit inside the jit.
MULTIKEY_CBC: dict[str, object] = {}


def _multikey_cbc_jnp(c2, prev2, rks_dec, key_slots, nr):
    """T-table multi-key CBC decrypt: public schedule gather + vmapped
    oracle decrypt core, shifted-XOR against the host-built prev
    stream. Same documented jnp timing-channel tradeoff (baselined)."""
    rkb = rks_dec[key_slots]  # (N, 4*(nr+1)) — public gather
    return jax.vmap(lambda c, r: block.decrypt_words(c, r, nr))(
        c2, rkb) ^ prev2


def _multikey_cbc_bitslice(c2, prev2, rks_dec, key_slots, nr):
    from ..ops import bitslice as _bs

    return _bs.decrypt_words_multikey(c2, rks_dec[key_slots], nr) ^ prev2


MULTIKEY_CBC["jnp"] = _multikey_cbc_jnp
MULTIKEY_CBC["bitslice"] = _multikey_cbc_bitslice


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _cbc_dec_scattered_multikey_jit(words, prev_words, rks_dec, key_slots,
                                    nr, engine, knobs):
    del knobs
    w2 = _as_block_words(words)
    p2 = _as_block_words(prev_words)
    fn = MULTIKEY_CBC.get(engine, _multikey_cbc_bitslice)
    return fn(w2, p2, rks_dec, key_slots.astype(jnp.uint32),
              nr).reshape(words.shape)


def cbc_decrypt_words_scattered_multikey(words, prev_words, rks_dec,
                                         key_slots, nr, engine="jnp"):
    """Parallel CBC decrypt across many requests and K keys in ONE
    dispatch: ``words`` the concatenated ciphertext blocks, and
    ``prev_words`` the per-block XOR stream — each request's IV at its
    first block, then its own shifted ciphertext (serve/batcher.py
    materialises it exactly like the scattered counters, so CBC rides
    the rung-packer with the SAME closed shapes as CTR). ``rks_dec`` is
    the (K, 4*(nr+1)) DECRYPT schedule stack (keycache builds it beside
    the encrypt one), ``key_slots`` the public per-block slot vector.
    CBC *encrypt* stays a per-stream recurrence and is deliberately not
    servable — the reference ships both directions serial
    (aes.c:757-816); only the decrypt direction parallelises."""
    return _cbc_dec_scattered_multikey_jit(words, prev_words, rks_dec,
                                           key_slots, nr, engine,
                                           _engine_knobs_key(engine))


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _ctr_scattered_multikey_jit(words, ctr_le_words, rks, key_slots, nr,
                                engine, knobs):
    del knobs
    w2 = _as_block_words(words)
    c2 = _as_block_words(ctr_le_words)
    fn = MULTIKEY_CTR.get(engine, _multikey_bitslice)
    return fn(w2, c2, rks, key_slots.astype(jnp.uint32),
              nr).reshape(words.shape)


def ctr_crypt_words_scattered_multikey(words, ctr_le_words, rks, key_slots,
                                       nr, engine="jnp", *,
                                       native_ctxs=None, native_threads=0,
                                       native_runs=None):
    """Scattered CTR where one device call carries K independent keys.

    The multi-key serve seam: ``rks`` is a (K, 4*(nr+1)) u32 stack of
    expanded schedules (unused slots hold the all-zero schedule so the
    batch shape is closed over K — the ladder's fixed key dimension) and
    ``key_slots`` a (N,) u32 vector mapping each block to its slot. The
    slot vector is PUBLIC — it derives from batch layout, never from key
    or payload bytes — which is exactly what the
    ``aes-ctr-scattered-multikey[*]`` audit entries pin: the schedule
    gather it feeds must stay untainted (analysis/jaxpr_audit.py).

    Engines with a dedicated multi-key core (MULTIKEY_CTR: the Pallas
    masked-select kernel, the bitsliced per-block-plane circuit, the
    vmapped T-table oracle) dispatch it; anything else falls back to the
    bitsliced circuit inside the same jit. ``engine="native"`` runs the
    host tier: per-slot threaded ECB runs over the contiguous key
    segments plus one XOR (``runtime.native.ctr_scattered_words``);
    ``native_ctxs`` lets a caller (the serve key cache) hand in
    pre-built contexts so steady-state dispatch does no key setup at
    all, and ``native_threads`` overrides the size-based thread default.
    ``native_runs`` — the batch's request layout,
    ``[(slot, start_block, nblocks, nonce16), ...]`` — switches the
    host tier to the per-request C CTR fast path
    (``runtime.native.ctr_requests_words``): counters are generated
    inside C per request instead of being materialised as an (N, 4)
    array, bit-exact with the array path (``ctr_le_words`` may then be
    None). Jax engines ignore it — their seam is the traced array pair.
    """
    if engine == NATIVE_ENGINE:
        from ..runtime import native as _native

        w = np.asarray(words)
        ctxs = native_ctxs
        if ctxs is None:
            ctxs = [_native.aes_ctx_from_schedule(
                        int(nr), np.asarray(r, dtype=np.uint32))
                    for r in np.asarray(rks)]
        if native_runs is not None:
            out = _native.ctr_requests_words(
                ctxs, w.reshape(-1), native_runs, nthreads=native_threads)
            return out.reshape(w.shape)
        out = _native.ctr_scattered_words(
            ctxs, w.reshape(-1),
            np.asarray(ctr_le_words, dtype=np.uint32).reshape(-1),
            np.asarray(key_slots), nthreads=native_threads)
        return out.reshape(w.shape)
    return _ctr_scattered_multikey_jit(words, ctr_le_words, rks, key_slots,
                                       nr, engine,
                                       _engine_knobs_key(engine))


@functools.partial(jax.jit, static_argnums=(3,))
def cbc_encrypt_words(words, iv_words, rk, nr):
    w2 = _as_block_words(words)

    def step(iv, p):
        c = block.encrypt_block_fused(p ^ iv, rk, nr)
        return c, c

    # Fused-gather body (block.encrypt_block_fused: one gather per round
    # instead of 16) — the scan recurrence is latency-bound, 3.4x measured
    # on chip vs the per-word core; unroll amortises per-step scan overhead
    # over the unavoidable block-to-block dependency (SURVEY.md §7 hard
    # part #3; unroll itself measured a null lever, docs/PERF.md).
    iv_out, out = jax.lax.scan(step, iv_words, w2, unroll=4)
    return out.reshape(words.shape), iv_out


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _cbc_encrypt_words_batch_jit(words, iv_words, rk, nr, engine, knobs):
    del knobs  # compile-cache key only (see _engine_knobs_key)
    iv0 = iv_words.reshape(-1, 4)
    s = iv0.shape[0]
    w3 = words.reshape(s, -1, 4)
    # Scan over the BLOCK axis; each step encrypts one block from every
    # stream as a single (S, 4) batch through the selected engine. The
    # earlier formulation (vmap of the single-block scan body) was
    # gather-bound at ~11 MB/s regardless of S; the batched engine step
    # measured 65-126 MB/s at S=32-8192 on chip — the Pallas kernel's
    # launch cost per step is far below S fused gathers (docs/PERF.md
    # ledger #14). xs is kept flat (N, 4S) across the scan boundary so no
    # materialised tensor carries a 4-wide minor dim (the 32x tiling-pad
    # class of ledger #10).
    xs = jnp.swapaxes(w3, 0, 1).reshape(w3.shape[1], -1)
    enc = CORES[engine][0]

    def step(iv, p):
        c = enc(p.reshape(s, 4) ^ iv, rk, nr)
        # Emit FLAT: lax.scan stacks the per-step outputs, and a stacked
        # (N, S, 4) tensor pads its 4-wide minor dim 32x under TPU tiling
        # (33.5 GiB asked for a 1 GiB batch — the ledger #10 class, third
        # instance); (N, 4S) stacks dense.
        return c, c.reshape(-1)

    iv_out, ys = jax.lax.scan(step, iv0, xs)
    out = jnp.swapaxes(ys.reshape(ys.shape[0], s, 4), 0, 1)
    return out.reshape(words.shape), iv_out


def cbc_encrypt_words_batch(words, iv_words, rk, nr, engine="jnp"):
    """Many independent CBC streams at once: one engine call per block step.

    CBC encryption is a true per-stream recurrence (reference
    aes.c:799-813, necessarily serial there). The sequence-parallel answer
    is the same as ARC4's prep_batch (models/arc4.py): work that cannot
    parallelise *within* a stream scales *across* streams — each scan step
    batches one block from every stream through the engine, and
    parallel/dist.py shards the stream axis over chips.
    words: (S, N, 4) block words or (S, 4N) flat streams; iv_words: (S, 4).
    Returns (outputs, final ivs) just like cbc_encrypt_words, per stream.
    """
    return _cbc_encrypt_words_batch_jit(words, iv_words, rk, nr, engine,
                                        _engine_knobs_key(engine))


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _cbc_decrypt_words_jit(words, iv_words, rk_dec, nr, engine, knobs):
    # Parallel: P_i = D(C_i) ^ C_{i-1} (C_{-1} = IV). Reference does this
    # serially (aes.c:782-796); the dependency chain only involves ciphertext,
    # so the TPU version is one batched decrypt + shifted XOR.
    #
    # The shifted-prev stream is built in the CALLER's boundary layout: on
    # a flat (4N,) stream the concat stays flat (minor dim 4N — dense
    # under tiling), where an (N, 4) prev tensor materialises with its
    # 4-wide minor dim padded to the 128-lane tile, 32x the logical bytes
    # — the round-4 corpus OOM at 1000 MiB (docs/hwlogs/corpus.log class,
    # second instance; cf. ops/bitslice.py:dense_words).
    # One always-flat form for both call layouts: the internal reshape
    # fuses (same reasoning as _as_block_words), the shift/concat keeps a
    # 4N-wide minor dim for (N, 4) callers too, and the engine call goes
    # through the models-level entry — the layer that accepts the flat
    # stream for EVERY engine (raw CORES callables are only uniform over
    # (N, 4)).
    del knobs
    flat = words.reshape(-1)
    prev = jnp.concatenate([iv_words, flat[:-4]])
    out = ecb_decrypt_words(flat, rk_dec, nr, engine) ^ prev
    return out.reshape(words.shape), flat[-4:]


def cbc_decrypt_words(words, iv_words, rk_dec, nr, engine="jnp"):
    if words.shape[0] == 0:  # length-0 is a no-op, as in the reference
        return words, iv_words
    return _cbc_decrypt_words_jit(words, iv_words, rk_dec, nr, engine,
                                  _engine_knobs_key(engine))


@functools.partial(jax.jit, static_argnums=(3,))
def cfb128_encrypt_words(words, iv_words, rk, nr):
    w2 = _as_block_words(words)

    def step(iv, p):
        c = p ^ block.encrypt_block_fused(iv, rk, nr)
        return c, c

    iv_out, out = jax.lax.scan(step, iv_words, w2, unroll=4)
    return out.reshape(words.shape), iv_out


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _cfb128_decrypt_words_jit(words, iv_words, rk, nr, engine, knobs):
    # Keystream block i = E(C_{i-1}) — all known up front, so parallel.
    # Always-flat shift + models-level engine entry, same rationale as
    # _cbc_decrypt_words_jit (a flat concat stays dense; an (N, 4) one
    # pads its minor dim 32x).
    del knobs
    flat = words.reshape(-1)
    prev = jnp.concatenate([iv_words, flat[:-4]])
    out = flat ^ ecb_encrypt_words(prev, rk, nr, engine)
    return out.reshape(words.shape), flat[-4:]


def cfb128_decrypt_words(words, iv_words, rk, nr, engine="jnp"):
    return _cfb128_decrypt_words_jit(words, iv_words, rk, nr, engine,
                                     _engine_knobs_key(engine))


def ctr_crypt_fn(nr: int, engine: str = "auto"):
    """A jitted (words, ctr_be_words, rk) -> words CTR function."""
    engine = resolve_engine(engine)
    return lambda words, ctr_be, rk: ctr_crypt_words(words, ctr_be, rk, nr, engine)


# ---------------------------------------------------------------------------
# Host-facing context with byte-granular streaming (the aes.h API shape).
# ---------------------------------------------------------------------------


def _to_u8(data) -> np.ndarray:
    if isinstance(data, (bytes, bytearray, memoryview)):
        return np.frombuffer(bytes(data), dtype=np.uint8)
    return np.asarray(data, dtype=np.uint8)


def _words_np(b: np.ndarray) -> np.ndarray:
    return packing.np_bytes_to_words(b).reshape(-1, 4)


def _bytes_np(w) -> np.ndarray:
    b = packing.np_words_to_bytes(
        np.asarray(w, dtype=np.uint32).reshape(-1, 4)).reshape(-1)
    # jax-backed arrays view as READ-ONLY; the context API has always
    # returned bytes the caller may mutate in place.
    return b if b.flags.writeable else np.array(b)


def _inc_counter_bytes(ctr: np.ndarray, k: int = 1) -> np.ndarray:
    """Add k to a 16-byte big-endian counter (host-side bookkeeping)."""
    val = int.from_bytes(ctr.tobytes(), "big")
    val = (val + k) % (1 << 128)
    return np.frombuffer(val.to_bytes(16, "big"), dtype=np.uint8).copy()


@dataclass
class AES:
    """An AES key context, both directions, engine-selectable.

    Equivalent of `aes_context` + `aes_setkey_enc`/`aes_setkey_dec`
    (reference aes-modes/aes.h:41-84). Round keys are expanded on host and
    staged to device once.
    """

    key: bytes
    engine: str = "auto"

    def __post_init__(self):
        # Validate names eagerly but resolve "auto" lazily at call time:
        # resolving needs jax.default_backend(), and initializing the backend
        # as a construction side effect would defeat later platform switches
        # (e.g. dryrun_multichip's jax.config.update to CPU).
        if self.engine not in (None, "auto") and self.engine not in CORES:
            raise ValueError(
                f"unknown engine {self.engine!r}; available: {sorted(CORES)}"
            )
        self.key = bytes(self.key)
        self.nr, rk_enc = expand_key_enc(self.key)
        _, rk_dec = expand_key_dec(self.key)
        self.rk_enc = jnp.asarray(rk_enc)
        self.rk_dec = jnp.asarray(rk_dec)

    # -- ECB ---------------------------------------------------------------
    def crypt_ecb(self, mode: int, data) -> np.ndarray:
        """Bulk ECB over any multiple of 16 bytes (reference aes.c:650-752
        handles one block; the batch dimension replaces the caller's loop)."""
        b = _to_u8(data)
        if b.size % 16:
            raise ValueError("ECB data must be a multiple of 16 bytes")
        # Flat u32 staging: dense jit-boundary layout (_as_block_words).
        w = packing.np_bytes_to_words(b)
        engine = resolve_engine(self.engine)
        if mode == AES_ENCRYPT:
            out = ecb_encrypt_words(jnp.asarray(w), self.rk_enc, self.nr, engine)
        else:
            out = ecb_decrypt_words(jnp.asarray(w), self.rk_dec, self.nr, engine)
        return _bytes_np(np.asarray(out))

    # -- CBC ---------------------------------------------------------------
    def crypt_cbc(self, mode: int, iv: np.ndarray, data) -> tuple[np.ndarray, np.ndarray]:
        """CBC with explicit IV state; returns (output, new_iv). Semantics of
        reference aes.c:757-816 (IV updated to last ciphertext block)."""
        b = _to_u8(data)
        if b.size % 16:
            raise ValueError("CBC data must be a multiple of 16 bytes")
        ivw = jnp.asarray(_words_np(_to_u8(iv))[0])
        w = jnp.asarray(packing.np_bytes_to_words(b))  # flat boundary staging
        if mode == AES_ENCRYPT:
            out, newiv = cbc_encrypt_words(w, ivw, self.rk_enc, self.nr)
        else:
            out, newiv = cbc_decrypt_words(
                w, ivw, self.rk_dec, self.nr, resolve_engine(self.engine)
            )
        return _bytes_np(np.asarray(out)), _bytes_np(np.asarray(newiv)[None, :])

    # -- CFB128 ------------------------------------------------------------
    def crypt_cfb128(self, mode: int, iv_off: int, iv: np.ndarray, data):
        """Byte-granular CFB128 (reference aes.c:822-863): returns
        (output, new_iv_off, new_iv). `iv` carries the feedback register,
        partially overwritten with ciphertext when iv_off != 0."""
        b = _to_u8(data)
        iv = _to_u8(iv).copy()
        return self._cfb_impl(mode, int(iv_off), iv, b)

    def _ecb1(self, block16: np.ndarray) -> np.ndarray:
        # One block at a time (CFB feedback / CTR tail): always the T-table
        # core — the bitsliced engine's 32-block lane packing is pure
        # overhead at batch size 1.
        w = jnp.asarray(_words_np(_to_u8(block16)))
        out = ecb_encrypt_words(w, self.rk_enc, self.nr, "jnp")
        return _bytes_np(np.asarray(out))

    def _cfb_impl(self, mode, iv_off, iv, b):
        out = np.empty_like(b)
        pos = 0
        n = int(iv_off)
        # PolarSSL keeps the *current* keystream implicitly: when n != 0 the
        # iv buffer holds ciphertext in positions [0, n) and not-yet-consumed
        # keystream bytes E(prev_iv) in positions [n, 16). See aes.c:836-846.
        while pos < b.size:
            if n == 0 and b.size - pos >= 16:
                # Aligned bulk: batched device kernels over all full blocks.
                nfull = (b.size - pos) // 16
                w = jnp.asarray(  # flat boundary staging (_as_block_words)
                    packing.np_bytes_to_words(b[pos : pos + nfull * 16]))
                ivw = jnp.asarray(_words_np(iv)[0])
                if mode == AES_ENCRYPT:
                    o, newiv = cfb128_encrypt_words(w, ivw, self.rk_enc, self.nr)
                else:
                    o, newiv = cfb128_decrypt_words(
                        w, ivw, self.rk_enc, self.nr, resolve_engine(self.engine)
                    )
                out[pos : pos + nfull * 16] = _bytes_np(np.asarray(o))
                iv = _bytes_np(np.asarray(newiv)[None, :]).copy()
                pos += nfull * 16
                continue
            if n == 0:
                iv = self._ecb1(iv).copy()
            take = min(16 - n, b.size - pos)
            chunk = b[pos : pos + take]
            c = chunk ^ iv[n : n + take]
            iv[n : n + take] = c if mode == AES_ENCRYPT else chunk
            out[pos : pos + take] = c
            pos += take
            n = (n + take) & 0x0F
        return out, n, iv

    # -- CTR ---------------------------------------------------------------
    def crypt_ctr(self, nc_off: int, nonce_counter: np.ndarray,
                  stream_block: np.ndarray, data):
        """Byte-granular CTR (reference aes.c:869-901): returns
        (output, new_nc_off, new_nonce_counter, new_stream_block).

        Parity-critical detail: the reference computes
        ``stream_block = E(counter)`` and **then** post-increments the
        counter (aes.c:876-884), so keystream block k is E(counter0 + k) and
        after a call that ends mid-block the stored counter is one ahead of
        the block being consumed.
        """
        b = _to_u8(data)
        nonce_counter = _to_u8(nonce_counter).copy()
        stream_block = _to_u8(stream_block).copy()
        out = np.empty_like(b)
        pos = 0
        n = int(nc_off)

        # Drain a partial stream block left over from a previous call.
        if n != 0:
            take = min(16 - n, b.size)
            out[:take] = b[:take] ^ stream_block[n : n + take]
            pos = take
            n = (n + take) & 0x0F

        nfull = (b.size - pos) // 16
        if nfull:
            # Flat u32 staging: dense boundary layout on TPU (see
            # ctr_crypt_words — a (N, 4) boundary array pads its minor dim
            # to the 128-lane tile).
            w = jnp.asarray(packing.np_bytes_to_words(b[pos : pos + nfull * 16]))
            ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce_counter).byteswap())
            o = ctr_crypt_words(
                w, ctr_be, self.rk_enc, self.nr, resolve_engine(self.engine)
            )
            out[pos : pos + nfull * 16] = _bytes_np(np.asarray(o))
            pos += nfull * 16
            nonce_counter = _inc_counter_bytes(nonce_counter, nfull)

        if pos < b.size:
            # Tail: generate one more keystream block, post-increment counter.
            stream_block = self._ecb1(nonce_counter)
            nonce_counter = _inc_counter_bytes(nonce_counter, 1)
            take = b.size - pos
            out[pos:] = b[pos:] ^ stream_block[:take]
            n = take
        elif nfull:
            # Parity detail (found by scripts/fuzz_parity.py): the
            # reference's byte loop regenerates stream_block for EVERY
            # block (aes.c:876-884), so a call that ends exactly on a block
            # boundary leaves stream_block = E(last counter) — dead state
            # while nc_off == 0, since the next call regenerates before
            # use, but the resume-state surface must be bit-identical. The
            # bulk path never materialises the keystream (fused kernels),
            # but CTR is an XOR stream: the last keystream block is just
            # in ^ out of the final block — free, host-side.
            stream_block = b[pos - 16 : pos] ^ out[pos - 16 : pos]
        return out, n, nonce_counter, stream_block


# ---------------------------------------------------------------------------
# Throughput-engine registration. Imported last: the modules below depend
# only on ops/{tables,gf,...}, never on this module, so there is no cycle.
# The chained modes (CBC/CFB encrypt scans) intentionally stay on the T-table
# core regardless of engine: their scan steps see one block at a time, where
# the bitsliced circuit's 32-block lane packing and transposes are pure
# overhead — sequential modes are latency-bound, the honest "anti-parallel
# baseline" of the reference (SURVEY.md §2 parallelism table).
# ---------------------------------------------------------------------------

from ..ops import bitslice as _bitslice  # noqa: E402
from ..ops import pallas_aes as _pallas_aes  # noqa: E402

register_core("bitslice", _bitslice.encrypt_words, _bitslice.decrypt_words)
# Every Pallas engine NAME gets a multi-key seam, but all of them route to
# the DENSE multi-key kernel (with the engine's S-box formulation): the
# masked-select key reconstruction is layout-independent and the dense
# boundary is the one without the sublane-padding tax, so there is exactly
# one multi-key kernel to tune/audit rather than one per boundary layout.
register_core("pallas", _pallas_aes.encrypt_words, _pallas_aes.decrypt_words,
              ctr_fused_fn=_pallas_aes.ctr_crypt_words_gen,
              pallas_backed=True,
              multikey_fn=_pallas_aes.ctr_scattered_multikey_dense)
register_core("pallas-gt", _pallas_aes.encrypt_words_gt,
              _pallas_aes.decrypt_words_gt,
              ctr_fused_fn=_pallas_aes.ctr_crypt_words_gt,
              pallas_backed=True,
              multikey_fn=_pallas_aes.ctr_scattered_multikey_dense)
# Same kernel structure as pallas-gt with the Boyar–Peralta S-box circuit
# pinned per-call (~25% less round arithmetic; decrypt shares pallas-gt's
# tower path — there is no comparably small inverse circuit). A separate
# engine NAME so bench.py's probe stage A/Bs the two formulations on
# hardware in one run; under OT_SBOX=bp it coincides with pallas-gt.
register_core("pallas-gt-bp", _pallas_aes.encrypt_words_gt_bp,
              _pallas_aes.decrypt_words_gt,
              ctr_fused_fn=_pallas_aes.ctr_crypt_words_gt_bp,
              pallas_backed=True,
              multikey_fn=_pallas_aes.ctr_scattered_multikey_dense_bp)
# The dense (128, W) boundary: pallas-gt's in-kernel ladder without the
# grouped layout's 2x sublane-padding tax on HBM streams / VMEM tiles —
# and without its halved buffer ceiling (the 1 GiB headline path). Its own
# engine name so the first hardware probe A/Bs the two boundary layouts
# and the persisted ranking (utils/ranking.py) retires the loser.
register_core("pallas-dense", _pallas_aes.encrypt_words_dense,
              _pallas_aes.decrypt_words_dense,
              ctr_fused_fn=_pallas_aes.ctr_crypt_words_dense,
              pallas_backed=True,
              multikey_fn=_pallas_aes.ctr_scattered_multikey_dense)
register_core("pallas-dense-bp", _pallas_aes.encrypt_words_dense_bp,
              _pallas_aes.decrypt_words_dense,
              ctr_fused_fn=_pallas_aes.ctr_crypt_words_dense_bp,
              pallas_backed=True,
              multikey_fn=_pallas_aes.ctr_scattered_multikey_dense_bp)
