"""Fused RC4 — the single-pass keystream+XOR variant.

The reference carries a second RC4 implementation (FreeBSD-derived rc4.c/
rc4.h) that is *dead code*: no Makefile builds it and its only call site is
commented out (reference Makefile:25, test.c:158-171 — SURVEY.md §2 #7). It
differs from arc4.c only in fusing keystream generation with the XOR, i.e.
the classic `rc4_crypt(buf)` API.

The framework keeps that API alive (completeness: component #7 of the
inventory), expressed the TPU way: one `lax.scan` whose step emits the
XORed byte directly, state carried exactly like the phase-split path. For
throughput-critical use prefer models/arc4.py — its phase split is what
makes the XOR phase data-parallel/shardable; this fused form is inherently
one long scan.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .arc4 import key_schedule


@functools.partial(jax.jit, static_argnums=())
def _fused_scan(state, data_u32):
    """state = (x, y, m) as uint32; data (N,) u32 in [0,256) -> XORed out."""

    def step(carry, d):
        x, y, m = carry
        x = (x + 1) & 0xFF
        a = m[x]
        y = (y + a) & 0xFF
        b = m[y]
        m = m.at[x].set(b).at[y].set(a)
        ks = m[(a + b) & 0xFF]
        return (x, y, m), (d ^ ks).astype(jnp.uint8)

    return jax.lax.scan(step, state, data_u32, unroll=8)


@dataclass
class RC4:
    """Fused-API RC4 context: `crypt` consumes data and advances state."""

    key: bytes

    def __post_init__(self):
        if len(self.key) == 0:
            raise ValueError("RC4 key must be non-empty")
        self.x = 0
        self.y = 0
        self.m = key_schedule(self.key)

    def crypt(self, data) -> np.ndarray:
        """Encrypt/decrypt `data` in one fused pass (rc4.c's API shape)."""
        d = (
            np.frombuffer(bytes(data), dtype=np.uint8)
            if isinstance(data, (bytes, bytearray))
            else np.asarray(data, dtype=np.uint8)
        )
        state = (
            jnp.uint32(self.x),
            jnp.uint32(self.y),
            jnp.asarray(self.m, jnp.uint32),
        )
        (x, y, m), out = _fused_scan(state, jnp.asarray(d, jnp.uint32))
        self.x, self.y = int(x), int(y)
        self.m = np.asarray(m, dtype=np.uint8)
        return np.asarray(out)
