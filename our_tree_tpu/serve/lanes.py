"""Per-device dispatch lanes: the serve path's fault domains.

The paper's one original idea — split the work into independent
contiguous chunks and run them in parallel (``aes-modes/test.c:33-35``)
— applied at the DEVICE level: every visible device gets one dispatch
lane, and a lane is an isolated fault domain. A wedged or dying chip
degrades its lane (watchdog kill, quarantine, canary probation), never
the service: the lane's in-flight batch is re-dispatched **bit-exactly**
on a healthy lane before any per-request error is answered — CTR with
explicit per-block counters makes replay side-effect-free, so a batch
is a pure function of (words, counters, key) and can run anywhere,
twice, with identical bytes.

This module is the ONLY place in ``serve/`` that touches a device
(otlint's ``serve-lane-seam`` rule enforces it): ``Lane.engine_call``
stages the batch arrays onto the lane's device and runs the
scattered-CTR seam under the lane's own watchdog deadline, with the
per-lane fault points (``lane_fail:<n>@lane=<i>``,
``lane_hang:<n>@lane=<i>``) alongside the generic dispatch seams.

Health state machine (every transition is a ``lane-state`` trace point;
quarantine also stamps ``degraded:["quarantined:lane:<i>"]`` through
the shared ``resilience.degrade()`` chokepoint and appends a failure
row to the serve journal — the SAME record ``resilience.journal`` uses
for sweep units, so ``clear_failures`` / ``--unquarantine`` is one
release model across harness and serve)::

    healthy ──failure──> suspect ──failure──> quarantined
       ^                    │ clean batch        │  canary ok
       │<───"recovered"─────┘                    v
       │                                     probation
       │<──"released" (probation served)────────┘
                         (a probation failure goes straight back
                          to quarantined; a TIMEOUT quarantines
                          from any state — a hang is never transient)

Placement is least-loaded (cumulative blocks dispatched) across
placeable lanes (healthy/suspect/probation, warmed only); a quarantined
lane is periodically probed with a warmup-shaped CANARY batch whose
expected output was pinned at warmup, and released into probation on a
bit-exact response. When NO placeable lane remains, quarantined lanes
are canary-probed as a last resort before the batch is failed — a
single-lane server therefore self-heals after a transient hang instead
of bricking.

Dispatch is OVERLAPPED: each lane owns a worker-thread executor
(``serve/dispatch.py``), ``LanePool.dispatch`` is an awaitable that
submits the guarded engine call to the placed lane's worker and yields
the event loop until the lane completes — so the batcher keeps forming
and placing batches while up to ``--max-inflight`` dispatches are in
flight across lanes (the paper's ``length/num_threads`` decomposition
finally applied ACROSS devices, not just within one). The watchdog
contract moved with it: a deadline armed on a worker thread delivers
its expiry through ``watchdog.thread_kill_hook`` — fail the dispatch
future, abandon the wedged worker — instead of the main-thread SIGALRM
raise, so failover still begins AT the deadline while the hung thread
is left as kill evidence. Every lane-seam property holds under
overlap: placement counts in-flight work (a lane with a batch in
flight is at capacity — one batch per lane, a device serializes its
own work anyway), failover re-dispatches bit-exactly before any rider
errors, a hung dispatch still abandons its ``lane-dispatch`` span, and
graceful drain awaits every in-flight batch. The synchronous
``probe_lane`` (main-thread SIGALRM path) remains for rehearsals and
single-shot tools.
"""

from __future__ import annotations

import asyncio
import collections
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..models import aes, arc4
from ..obs import incident, metrics, trace
from ..resilience import degrade, faults, watchdog
from ..resilience.policy import RetryPolicy
from .dispatch import LaneExecutor

#: Health states. RELEASED appears in transition logs (the moment a
#: lane finishes probation) and immediately rests as HEALTHY.
HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
PROBATION = "probation"
RELEASED = "released"

#: States that may receive traffic.
PLACEABLE = (HEALTHY, SUSPECT, PROBATION)


#: The pinned canary batch (set_canary): inputs, the expected bit-exact
#: output, and the rung it was shaped at — named fields, so the probe
#: helpers read .expected/.bucket instead of magic tuple indices.
_Canary = collections.namedtuple(
    "_Canary", "words ctr_words sched key_slots expected bucket")


def lane_unit(idx: int) -> str:
    """The lane's name in the shared quarantine ledger (journal failure
    rows, ``quarantine``/``quarantine-release`` trace points, degrade
    kinds) — the serve twin of a sweep unit name."""
    return f"lane:{idx}"


class LanesExhausted(RuntimeError):
    """Every placeable lane failed this batch (including last-resort
    canary rescues). ``causes`` is [(lane_idx, exc), ...] in attempt
    order; ``timed_out`` reflects the LAST cause — the error code the
    riders see matches what finally stopped the batch."""

    def __init__(self, label: str, causes: list):
        self.causes = causes
        last = causes[-1][1] if causes else None
        self.timed_out = isinstance(last, watchdog.DispatchTimeout)
        names = ",".join(f"lane{i}:{type(e).__name__}" for i, e in causes)
        super().__init__(
            f"batch {label}: no lane could serve it ({names or 'no lanes'})")


class Lane:
    """One dispatch lane: a device, a health state, and the one guarded
    engine-call seam. The pool owns placement and failover; the lane
    owns its device contact and its state transitions."""

    def __init__(self, idx: int, device, engine: str, deadline_s: float,
                 retries: int, clock=time.monotonic, native_threads: int = 0):
        self.idx = idx
        self.device = device
        self.engine = engine
        self.native_threads = int(native_threads)
        self.deadline_s = deadline_s
        self.state = HEALTHY
        self.warmed = False
        self.policy = RetryPolicy(
            attempts=max(int(retries), 1), base_delay_s=0.0,
            retry_on=(RuntimeError,), name=f"lane{idx}-dispatch")
        self.dispatches = 0
        self.blocks = 0
        self.failures = 0
        self.timeouts = 0
        self.redispatches_in = 0
        self.canaries = 0
        self.probation_left = 0
        self.transitions: list[dict] = []
        #: overlap state: batches currently in flight on this lane
        #: (capacity is ONE — a device serializes its own work, so a
        #: busy lane is simply not placeable) and cumulative busy wall
        #: time (the bench's per-lane busy-fraction numerator).
        self.inflight = 0
        self.busy_us = 0
        #: cumulative DEVICE time (the block-until-ready fence window on
        #: jax engines; the C engine-compute window on the native tier)
        #: — the half of busy_us that is compute, not host overhead
        self.device_us = 0
        self.executor: LaneExecutor | None = None
        self._clock = clock
        self._t0 = clock()

    def run_async(self, unit) -> asyncio.Future:
        """Submit ``unit`` (a zero-arg callable wrapping this lane's
        guarded ``engine_call``) to the lane's worker executor; returns
        an awaitable future. The executor is created on first use and
        replaced automatically after a watchdog kill abandoned its
        worker (serve/dispatch.py)."""
        if self.executor is None:
            self.executor = LaneExecutor(f"ot-lane{self.idx}",
                                         lane=self.idx)
        return asyncio.wrap_future(self.executor.submit(unit))

    # -- state machine -----------------------------------------------------
    def _to(self, new: str, why: str) -> None:
        old = self.state
        if old == new:
            return
        self.state = new
        self.transitions.append({
            "prev": old, "to": new, "why": why,
            "t_s": round(self._clock() - self._t0, 3)})
        metrics.counter("serve_lane_transitions", lane=self.idx, state=new)
        metrics.gauge("serve_lane_placeable",
                      1 if new in PLACEABLE else 0, lane=self.idx)
        trace.point("lane-state", lane=self.idx, prev=old, to=new, why=why)

    def _quarantine(self, why: str, journal) -> None:
        came_from = self.state
        self._to(QUARANTINED, why)
        if came_from == QUARANTINED:
            return  # already there (e.g. a failed canary): one event
        trace.point("quarantine", unit=lane_unit(self.idx), lane=self.idx,
                    reason=why)
        degrade.degrade(f"quarantined:{lane_unit(self.idx)}",
                        f"lane {self.idx} ({self.device}): {why}")
        if journal is not None:
            journal.record_failure(lane_unit(self.idx), why)
        # A quarantine is an incident: dump the flight-recorder bundle
        # (obs/incident.py). Coalesced by the trigger cooldown — the
        # common kill->quarantine pair is ONE incident, one bundle.
        incident.trigger("quarantine", unit=lane_unit(self.idx),
                         lane=self.idx, why=why)

    def adopt_journal_quarantine(self, fails: int) -> None:
        """Start quarantined from recorded journal failure rows (the
        resume path — no NEW failure row is appended; the evidence is
        already on file). The lane still gets warmed so a canary can
        release it once it proves healthy."""
        self._to(QUARANTINED, f"journal:{fails}")
        trace.point("quarantine", unit=lane_unit(self.idx), lane=self.idx,
                    reason=f"journal:{fails}")
        degrade.degrade(f"quarantined:{lane_unit(self.idx)}",
                        f"lane {self.idx}: {fails} failure row(s) on the "
                        f"serve journal (release: canary probe or "
                        f"serve.bench --unquarantine {lane_unit(self.idx)})")

    def note_success(self, blocks: int, redispatch: bool,
                     probation_batches: int) -> None:
        self.dispatches += 1
        self.blocks += int(blocks)
        if redispatch:
            self.redispatches_in += 1
        if self.state == SUSPECT:
            self._to(HEALTHY, "recovered")
        elif self.state == PROBATION:
            self.probation_left -= 1
            if self.probation_left <= 0:
                self._to(RELEASED, f"probation-served:{probation_batches}")
                trace.point("quarantine-release", unit=lane_unit(self.idx),
                            lane=self.idx)
                self._to(HEALTHY, "released")

    def note_failure(self, exc: BaseException, journal) -> None:
        self.failures += 1
        if self.state == HEALTHY:
            self._to(SUSPECT, type(exc).__name__)
        else:  # a suspect or probation lane gets no second failure
            self._quarantine(type(exc).__name__, journal)

    def note_timeout(self, exc: BaseException, journal) -> None:
        # A hang is never transient: a device that wedged once cannot be
        # trusted with another batch's latency budget until a canary
        # proves it — straight to quarantined from any state.
        self.timeouts += 1
        self._quarantine("dispatch-timeout", journal)

    # -- the ONE device-dispatch seam in serve/ ----------------------------
    def engine_call(self, words, ctr_words, sched, key_slots, label: str,
                    warmup: bool = False, runs=None,
                    timing: dict | None = None, mode: str = "ctr",
                    inject_words=None, seg_keep=None,
                    prep_len: int | None = None):
        """One MULTI-KEY dispatch on THIS lane's device, under this
        lane's watchdog deadline. ``sched`` is the keycache's
        StackedSchedules view (K expanded schedules, zero rows in unused
        slots) and ``key_slots`` the per-block slot-index vector — the
        fixed-K dispatch shape that keeps the ladder's compile cache
        closed (serve/batcher.py). Inputs are staged (committed) onto
        the lane's device so jit routes the compiled program there; on
        the NATIVE host tier there is no device and no jit — the call
        runs the C runtime with the stack's pre-built contexts, still
        inside this lane's watchdog/fault seams, so health accounting
        and failover are engine-independent. The fault seams fire only
        for traffic (warmup primes compiles, it is not a servable
        batch). Warmup runs under the global opt-in deadline (a
        first-contact compile legitimately dwarfs a steady-state
        dispatch) — EXCEPT on a quarantined lane, which already proved
        it cannot be trusted with an unbounded wait.

        ``mode`` routes the batch to its kernel (serve/queue.py MODES):
        ``ctr`` the scattered-CTR seam as always; ``gcm``/``gcm-open``
        the fused CTR+GHASH dispatch (``aead.gcm``; ``inject_words`` /
        ``seg_keep`` are its segment arrays, the result is the (2, 4N)
        stack [crypt output, running GHASH states]); ``cbc`` the
        parallel CBC-decrypt core (``ctr_words`` carries the PREV
        stream). Every mode's dispatch is a pure function of its
        arrays, so bit-exact failover replay holds for all of them. The
        AEAD kernels are jax-only: on the native host tier they run the
        jnp engine in-process (no C GHASH exists; documented in
        docs/SERVING.md's tier table)."""
        deadline_s = (self.deadline_s
                      if (not warmup or self.state == QUARANTINED)
                      else watchdog.default_deadline_s())
        with watchdog.deadline(deadline_s,
                               what=f"lane {self.idx} dispatch {label}"):
            if not warmup:
                faults.check("serve_dispatch", label)
                faults.check("dispatch_fail", label)
                faults.check_lane("lane_fail", self.idx, label)
                watchdog.injected_hang("dispatch_hang", label)
                # Scoped shot first, plain pool only if it did not fire:
                # one dispatch consumes at most one lane_hang shot (the
                # check_lane contract).
                if not watchdog.injected_hang(
                        faults.scoped("lane_hang", self.idx), label):
                    watchdog.injected_hang("lane_hang", label)
                # The injected LATENCY regression (no failure, just a
                # slower dispatch): the knob the SLO gate rehearsal
                # (`serve.bench --slo`, docs/RESILIENCE.md) turns red.
                faults.injected_slow("dispatch_slow", label)
            if mode in ("rc4", "rc4-prep"):
                # The session-mode seams (serve/session.py), jax-only
                # like the AEAD kernels and schedule-free — ``sched`` is
                # ignored entirely. ``rc4`` XORs payload words against
                # cached keystream words (key-oblivious — coalesced
                # sessions ride one dispatch); ``rc4-prep`` runs the
                # batched PRGA at the prefetcher's fixed (slots,
                # prep_len) quantum, carries in ``words``/``ctr_words``
                # (m stack / xy stack), carry + keystream out in one
                # array. Both are pure functions of their arrays, so the
                # pool's bit-exact failover replay holds unchanged.
                w, c = words, ctr_words
                if self.device is not None:
                    w = jax.device_put(w, self.device)
                    c = jax.device_put(c, self.device)
                out = (arc4.xor_words(w, c) if mode == "rc4"
                       else arc4.prep_batch_words(w, c, int(prep_len)))
                t_fence = self._clock()
                jax.block_until_ready(out)
                if timing is not None:
                    self.device_us += (d_us := int(
                        (self._clock() - t_fence) * 1e6))
                    timing["device_us"] = d_us
                return np.asarray(out)
            if mode == "ctr" and self.engine == aes.NATIVE_ENGINE:
                # ``runs`` (the batch's request layout) flips the host
                # tier to the per-request C CTR fast path: counters are
                # generated inside C, no (N, 4) array ever exists —
                # warmup/canary calls pass explicit arrays instead
                # (runs=None) and take the scattered counter path.
                t_eng = self._clock()
                out = np.asarray(aes.ctr_crypt_words_scattered_multikey(
                    words, ctr_words, sched.rks, key_slots, sched.nr,
                    self.engine, native_ctxs=sched.native_ctxs(),
                    native_threads=self.native_threads,
                    native_runs=runs))
                if timing is not None:
                    # The host tier has no device, but the C engine-
                    # compute window is the same ledger stage: "time the
                    # cipher itself took", distinct from staging,
                    # watchdog, and retry overhead around it.
                    self.device_us += (d_us := int(
                        (self._clock() - t_eng) * 1e6))
                    timing["device_us"] = d_us
                return out
            # The jax path (all modes; AEAD/CBC on a native-tier server
            # run the jnp engine here — the docstring's tier note).
            engine = (aes.resolve_engine("jnp")
                      if self.engine == aes.NATIVE_ENGINE else self.engine)
            w, c, r, s = words, ctr_words, sched.rks, key_slots
            if mode in ("gcm", "gcm-open"):
                r = (sched.rks, sched.hmats, inject_words, seg_keep)
            elif mode == "cbc":
                r = sched.rks_dec
            if self.device is not None:
                w = jax.device_put(w, self.device)
                c = jax.device_put(c, self.device)
                s = jax.device_put(s, self.device)
                r = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, self.device), r)
            if mode in ("gcm", "gcm-open"):
                from ..aead import gcm as aead_gcm

                rks, hmats, inject, keep = r
                out_w, y_w = aead_gcm.gcm_crypt_ghash_words(
                    w, c, rks, s, hmats, inject, keep, sched.nr, engine,
                    aead_gcm.SEAL if mode == "gcm" else aead_gcm.OPEN)
                out = jnp.stack([out_w.reshape(-1), y_w.reshape(-1)])
            elif mode == "cbc":
                out = aes.cbc_decrypt_words_scattered_multikey(
                    w, c, r, s, sched.nr, engine)
            else:
                out = aes.ctr_crypt_words_scattered_multikey(
                    w, c, r, s, sched.nr, engine)
            # Device-time accounting: jax dispatch is ASYNC — the call
            # above returns once the program is enqueued (host: cache
            # lookup + launch), and the block-until-ready fence below is
            # where device compute is actually waited out. The fence
            # window is the ledger's "device" stage (an upper bound that
            # excludes host work by construction; transfer rides it on
            # committed inputs). The kernel-internal refinement lives
            # behind the profiler seam (obs/profiler.py — a /profilez
            # window captures jax.profiler traces around these fences);
            # the fence split itself is engine-independent.
            t_fence = self._clock()
            jax.block_until_ready(out)
            if timing is not None:
                self.device_us += (d_us := int(
                    (self._clock() - t_fence) * 1e6))
                timing["device_us"] = d_us
        return np.asarray(out)

    def stats(self) -> dict:
        return {
            "lane": self.idx, "device": str(self.device),
            "state": self.state, "warmed": self.warmed,
            "dispatches": self.dispatches, "blocks": self.blocks,
            "bytes": self.blocks * 16, "failures": self.failures,
            "timeouts": self.timeouts,
            "redispatches_in": self.redispatches_in,
            "canaries": self.canaries,
            "busy_s": round(self.busy_us / 1e6, 6),
            "device_s": round(self.device_us / 1e6, 6),
            "abandoned_workers": (self.executor.abandoned
                                  if self.executor is not None else 0),
            "transitions": list(self.transitions),
        }


class LanePool:
    """The lane set plus placement, failover, and canary probing.

    ``lanes=None`` gives one lane per visible device; an explicit count
    may exceed the device count (lanes then share devices round-robin —
    the single-device rehearsal mode tests and CPU CI use)."""

    def __init__(self, engine: str, deadline_s: float = 0.0,
                 retries: int = 2, lanes: int | None = None,
                 probe_every: int = 8, probation_batches: int = 2,
                 journal=None, clock=time.monotonic,
                 native_threads: int = 0):
        # The native host tier has no jax devices to fan over: lanes
        # still exist (health machine, watchdog, failover rehearsals)
        # but share the host; device staging is skipped in engine_call.
        devices = (list(jax.devices())
                   if engine != aes.NATIVE_ENGINE else [None])
        n = len(devices) if lanes is None else max(int(lanes), 1)
        self.engine = engine
        self.lanes = [Lane(i, devices[i % len(devices)], engine,
                           deadline_s, retries, clock,
                           native_threads=native_threads)
                      for i in range(n)]
        self.journal = journal
        self.probe_every = max(int(probe_every), 1)
        self.probation_batches = max(int(probation_batches), 1)
        self.redispatches = 0
        self._since_probe = 0
        self._canary = None  # (words, ctr, sched, key_slots, expected, rung)
        #: pulsed (replaced) on every completion/state change so an
        #: awaiting dispatch re-evaluates placement; see _wait_change.
        self._change = asyncio.Event()
        #: lanes OCCUPIED right now (dispatch or probe windows) and the
        #: run's high-water mark — the measured overlap. Counted around
        #: the actual lane.run_async window, NOT around batch tasks: a
        #: task parked waiting for a busy lane is queued work, not an
        #: in-flight dispatch, and the `--min-inflight` gate must not be
        #: satisfiable by queuing alone (`--lanes 1 --max-inflight 4`
        #: serializes on the single lane and must measure 1).
        self.inflight_now = 0
        self.max_inflight_seen = 0

    def close(self) -> None:
        """Stop every lane's idle worker (abandoned/wedged ones need no
        stop — they exit on wake via their stale generation)."""
        for lane in self.lanes:
            if lane.executor is not None:
                lane.executor.close()

    # -- overlap accounting ------------------------------------------------
    def _inflight(self, d: int) -> None:
        """The in-flight ledger + `serve_inflight` gauge: one event per
        TRAFFIC-dispatch lane window, so `obs.report` can reconstruct
        the overlap a run actually achieved (the "serve overlap" line)
        and `serve.bench --min-inflight` gates the high-water mark.
        Canary probes occupy lanes but are excluded — they bypass the
        server's in-flight semaphore, and the measured number must stay
        comparable to the configured `max_inflight` limit (a serialized
        control run with one probe must still measure 1). Mirrored into
        the metrics registry (exact + on /metrics + snapshotted for the
        Perfetto counter track) — the trace gauge stays because the
        report's per-window overlap reconstruction needs every edge,
        and it is per-BATCH, not per-request, so sampling leaves it."""
        self.inflight_now += d
        if self.inflight_now > self.max_inflight_seen:
            self.max_inflight_seen = self.inflight_now
            metrics.gauge_max("serve_inflight_peak", self.inflight_now)
        metrics.gauge("serve_inflight", self.inflight_now)
        trace.gauge("serve_inflight", self.inflight_now)

    # -- overlap wakeups ---------------------------------------------------
    def _notify_change(self) -> None:
        """Wake every dispatch waiting for a lane: swap in a fresh event
        and set the old one. Waiters capture ``self._change`` BEFORE
        re-checking placement (see ``dispatch``), so a pulse landing
        between their check and their await cannot be missed."""
        ev, self._change = self._change, asyncio.Event()
        ev.set()

    # -- journal resume ----------------------------------------------------
    def adopt_journal_quarantines(self) -> list[int]:
        """Quarantine lanes with failure rows on the serve journal (any
        recorded row: serve only journals quarantine-grade events).
        Returns the adopted lane indices."""
        if self.journal is None:
            return []
        adopted = []
        for lane in self.lanes:
            fails = self.journal.fail_count(lane_unit(lane.idx))
            if fails > 0:
                lane.adopt_journal_quarantine(fails)
                adopted.append(lane.idx)
        return adopted

    # -- placement ---------------------------------------------------------
    def placeable(self, exclude=()) -> list[Lane]:
        return [l for l in self.lanes
                if l.idx not in exclude and l.warmed
                and l.state in PLACEABLE]

    def place(self, exclude=()) -> Lane | None:
        """Least-loaded IDLE placeable lane (cumulative blocks; index
        breaks ties so placement is deterministic for a given history).
        In-flight work counts against placement: a lane with a batch in
        flight is at capacity — one batch per lane, since a device
        serializes its own dispatches and queuing a second batch behind
        a possibly-wedging one would only couple their fates. A caller
        finding no idle lane but a busy placeable one waits for a
        completion pulse instead of failing (``dispatch``)."""
        cands = [l for l in self.placeable(exclude) if not l.inflight]
        if not cands:
            return None
        return min(cands, key=lambda l: (l.blocks, l.idx))

    # -- the canary --------------------------------------------------------
    def set_canary(self, words, ctr_words, sched, key_slots, expected,
                   bucket: int) -> None:
        """Pin the warmup-shaped probe batch and its expected output
        (captured from the first lane to warm; every other lane's warmup
        output was compared against it — cross-lane bit-exactness is a
        startup invariant, not a hope). ``sched``/``key_slots`` are the
        multi-key dispatch pair (StackedSchedules + per-block slot
        vector), so the canary replays the EXACT traffic shape."""
        self._canary = _Canary(words, ctr_words, sched, key_slots,
                               np.asarray(expected), int(bucket))

    def _probe_open(self, lane: Lane):
        """Probe preconditions + the ``lane-probe`` span, or None when
        the lane is not probeable (not quarantined, unwarmed, busy, or
        no canary pinned)."""
        if (self._canary is None or not lane.warmed
                or lane.state != QUARANTINED or lane.inflight):
            return None
        lane.canaries += 1
        cm = trace.detached_span("lane-probe", lane=lane.idx,
                                 bucket=self._canary.bucket,
                                 engine=self.engine)
        cm.__enter__()
        return cm

    def _probe_settle(self, lane: Lane, cm, c: _Canary,
                      out=None, exc=None) -> bool:
        """Close the probe span and judge the canary: bit-exact output
        releases the lane into probation; a failure, timeout (span
        deliberately abandoned — the same orphan-as-kill-evidence
        convention as a hung traffic dispatch), or mismatched payload
        leaves it quarantined. ``c`` is the canary CAPTURED at probe
        start: the engine call may take seconds, and a set_canary
        landing mid-probe must not judge the old inputs' output against
        the new expectation."""
        if exc is not None:
            if not isinstance(exc, watchdog.DispatchTimeout):
                cm.__exit__(type(exc), exc, None)
            metrics.counter("serve_canary", lane=lane.idx,
                            outcome="failed")
            trace.counter("serve_canary_failed", lane=lane.idx)
            return False
        cm.__exit__(None, None, None)
        if not np.array_equal(out, c.expected):
            metrics.counter("serve_canary", lane=lane.idx,
                            outcome="mismatch")
            trace.counter("serve_canary_mismatch", lane=lane.idx)
            return False
        metrics.counter("serve_canary", lane=lane.idx, outcome="ok")
        lane.probation_left = self.probation_batches
        lane._to(PROBATION, "canary-ok")
        trace.point("lane-probe-ok", lane=lane.idx,
                    unit=lane_unit(lane.idx))
        return True

    def probe_lane(self, lane: Lane) -> bool:
        """One canary dispatch on a quarantined lane, synchronously on
        the calling thread (the main-thread SIGALRM watchdog path —
        rehearsals and single-shot tools; the server's overlapped loop
        uses ``probe_lane_async``)."""
        cm = self._probe_open(lane)
        if cm is None:
            return False
        c = self._canary
        try:
            out = lane.engine_call(c.words, c.ctr_words, c.sched, c.key_slots,
                                   f"canary:lane{lane.idx}")
        except Exception as e:  # noqa: BLE001 - a sick lane may raise anything
            return self._probe_settle(lane, cm, c, exc=e)
        return self._probe_settle(lane, cm, c, out=out)

    async def probe_lane_async(self, lane: Lane) -> bool:
        """``probe_lane`` through the lane's worker executor: the event
        loop keeps serving other lanes while the canary runs (a probe of
        a genuinely dead lane costs its watchdog deadline — that wait
        must not stall in-flight traffic). A hung canary's wedged worker
        is abandoned exactly like a hung dispatch's."""
        cm = self._probe_open(lane)
        if cm is None:
            return False
        c = self._canary
        # The probe occupies the LANE (placement skips it, busy time
        # accrues) but does NOT count into the in-flight dispatch
        # metric: probes run outside the server's `max_inflight`
        # semaphore (a rescue probe fires while its dispatch coroutine
        # already holds a slot — acquiring again would deadlock a
        # --max-inflight 1 server), so counting them could report
        # measured overlap above the configured limit in a run that
        # never overlapped a single BATCH.
        lane.inflight += 1
        t0 = lane._clock()
        try:
            out = await lane.run_async(
                lambda: lane.engine_call(c.words, c.ctr_words, c.sched,
                                         c.key_slots,
                                         f"canary:lane{lane.idx}"))
        except Exception as e:  # noqa: BLE001 - a sick lane may raise anything
            return self._probe_settle(lane, cm, c, exc=e)
        finally:
            lane.inflight -= 1
            lane.busy_us += int((lane._clock() - t0) * 1e6)
            self._notify_change()
        return self._probe_settle(lane, cm, c, out=out)

    def probe_due(self) -> bool:
        """Advance the per-placed-batch probe counter; True when a
        canary pass is due AND a probeable (warmed, quarantined) lane
        exists. Synchronous and cheap — the server checks this inline
        per batch and only spawns a ``probe_pass`` task when it fires,
        instead of paying a task allocation per batch for a no-op."""
        self._since_probe += 1
        if self._since_probe < self.probe_every:
            return False
        self._since_probe = 0
        return any(l.state == QUARANTINED and l.warmed
                   for l in self.lanes)

    async def probe_pass(self) -> None:
        """One canary pass over the warmed quarantined lanes, through
        the lane executors — run as its own task so in-flight
        dispatches keep completing (and new batches keep forming) while
        a canary waits out a dead lane's deadline."""
        for lane in self.lanes:
            if lane.state == QUARANTINED and lane.warmed:
                await self.probe_lane_async(lane)

    # -- dispatch with failover --------------------------------------------
    async def dispatch(self, words, ctr_words, sched, key_slots, label: str,
                       bucket: int, blocks: int, requests: int, runs=None,
                       sampled: bool = True, timing: dict | None = None,
                       mode: str = "ctr", inject_words=None, seg_keep=None,
                       prep_len: int | None = None):
        """Place and run one batch, failing over across lanes until it
        succeeds or every lane has been tried. ``sched``/``key_slots``
        are the multi-key pair (keycache.StackedSchedules + per-block
        slot vector). Returns (output words, lane, redispatches).
        Raises LanesExhausted when no lane could serve it — only then
        may the caller answer per-request errors
        (re-dispatch-before-error is the failover contract).

        ``timing``, when a dict, is filled with the batch's
        time-attribution windows (µs): ``worker_wait_us`` (executor
        queue residency of the final attempt), ``device_us`` (the
        block-until-ready fence / native engine-compute window), and
        ``total_us`` (first placement to success, failover included) —
        the server folds them into the per-request ledger and the
        ``serve_stage_us{stage=...}`` histograms.

        Awaitable, for overlap: the guarded engine call (with its
        on-lane RetryPolicy) runs on the placed lane's worker executor,
        so many dispatch coroutines proceed concurrently — up to the
        server's in-flight cap, one per lane. When every not-yet-tried
        placeable lane is BUSY the coroutine waits for a completion
        pulse and re-places (failover-before-error still holds: busy
        healthy lanes are future failover targets, not exhaustion);
        only when no placeable lane exists at all does the last-resort
        canary rescue run, and only when that too fails does
        LanesExhausted surface."""
        causes: list = []
        tried: set[int] = set()
        t_place0 = self.lanes[0]._clock() if self.lanes else 0.0
        while True:
            # Capture the pulse BEFORE placing: a completion landing
            # between a failed placement and the await still wakes us.
            change = self._change
            lane = self.place(exclude=tried)
            if lane is None:
                if self.placeable(tried):
                    await change.wait()  # busy lanes exist: one frees up
                    continue
                lane = await self._rescue(tried)
                if lane is None and any(
                        l.state == QUARANTINED and l.inflight
                        and l.idx not in tried for l in self.lanes):
                    # Another coroutine's canary is IN FLIGHT on a
                    # quarantined lane this batch has not tried: its
                    # success is this batch's failover target, so wait
                    # for the probe's completion pulse and re-place
                    # instead of answering errors — the re-dispatch-
                    # before-error contract holds across CONCURRENT
                    # rescues too (a probe that fails leaves no
                    # in-flight quarantined lane, and the next pass
                    # probes or exhausts honestly).
                    await change.wait()
                    continue
            if lane is None:
                raise LanesExhausted(label, causes)
            # A REDISPATCH is an incident: force-sample it even when no
            # rider was head-sampled, so failover evidence is complete
            # at any OT_TRACE_SAMPLE rate. A first attempt of an
            # unsampled batch opens a DEFERRED span — written only if
            # the outcome turns abnormal (error exit or the force()
            # below), free when it completes clean.
            cm = trace.maybe_span(
                sampled or bool(tried),
                "lane-dispatch", lane=lane.idx, batch=label, bucket=bucket,
                blocks=blocks, requests=requests, engine=self.engine,
                redispatch=bool(tried))
            cm.__enter__()
            # The tail exemplar this dispatch contributes to the
            # latency histograms: span id + the closed attrs, so a
            # p99 bucket names the one span chain that defined it
            # (sampled dispatches only — an unsampled span has no id
            # on disk to resolve).
            ex = ({"span": cm.span_id, "trace": trace.run_id(),
                   "lane": lane.idx, "rung": bucket,
                   "engine": self.engine, "mode": mode}
                  if cm.span_id else None)
            lane.inflight += 1
            self._inflight(+1)
            t0 = lane._clock()
            outcome = "ok"
            attempt_timing: dict = {}

            def unit(lane=lane, attempt_timing=attempt_timing, t0=t0):
                # First line ON the worker thread: executor-queue
                # residency (submit -> unit start) — the ledger's
                # worker_wait stage, per batch.
                attempt_timing["worker_wait_us"] = int(
                    (lane._clock() - t0) * 1e6)
                # Mode kwargs only off the ctr default: the ctr hot
                # path's call shape is unchanged (and with it every
                # engine_call stub/wrapper that predates modes).
                extra = ({} if mode == "ctr"
                         else {"mode": mode, "inject_words": inject_words,
                               "seg_keep": seg_keep,
                               "prep_len": prep_len})
                return lane.policy.run(
                    lambda att: lane.engine_call(words, ctr_words,
                                                 sched, key_slots,
                                                 label, runs=runs,
                                                 timing=attempt_timing,
                                                 **extra))

            try:
                out = await lane.run_async(unit)
            except watchdog.DispatchTimeout as e:
                # The dispatch never ended: the span is ABANDONED, not
                # closed — its orphaned begin is the kill evidence
                # (obs.report --check --expected-orphans lane-dispatch);
                # the wedged worker thread was abandoned with it.
                # force() materialises the begin for an unsampled batch:
                # a hang keeps its orphan at any sample rate.
                cm.force()
                outcome = "timeout"
                metrics.counter("serve_lane_timeout", lane=lane.idx)
                trace.counter("serve_lane_timeout", lane=lane.idx)
                # Flight recorder: the killed dispatch enters the ring
                # BEFORE the trigger dumps, so the bundle's ring always
                # contains the record that caused it (the CI gate).
                # The quarantine that note_timeout() fires a moment
                # later is the SAME incident — its trigger coalesces
                # into this bundle via the cooldown.
                incident.record(lane=lane.idx, rung=bucket,
                                engine=self.engine, mode=mode,
                                outcome="timeout", device_us=0,
                                wall_us=int((lane._clock() - t0) * 1e6),
                                batch=label)
                incident.trigger("watchdog-kill", lane=lane.idx,
                                 rung=bucket, batch=label)
                lane.note_timeout(e, self.journal)
                causes.append((lane.idx, e))
                tried.add(lane.idx)
                continue
            except Exception as e:  # noqa: BLE001 - failover, then contain
                cm.__exit__(type(e), e, None)
                outcome = "failed"
                metrics.counter("serve_lane_failed", lane=lane.idx)
                trace.counter("serve_lane_failed", lane=lane.idx)
                incident.record(lane=lane.idx, rung=bucket,
                                engine=self.engine, mode=mode,
                                outcome="failed", device_us=0,
                                wall_us=int((lane._clock() - t0) * 1e6),
                                batch=label)
                lane.note_failure(e, self.journal)
                causes.append((lane.idx, e))
                tried.add(lane.idx)
                continue
            finally:
                lane.inflight -= 1
                self._inflight(-1)
                dt_us = int((lane._clock() - t0) * 1e6)
                lane.busy_us += dt_us
                # The dispatch seam's live distributions: per-lane
                # latency (log2 buckets, labeled by lane/engine/outcome)
                # and cumulative busy time — the continuous per-lane
                # stage-occupancy breakdown (PAPERS.md, the pipelined-
                # AES stage analysis) the post-hoc report tables only
                # showed after the run ended.
                metrics.observe("serve_dispatch_us", dt_us,
                                lane=lane.idx, engine=self.engine,
                                outcome=outcome, mode=mode,
                                exemplar=ex)
                metrics.counter("serve_lane_busy_us", dt_us,
                                lane=lane.idx)
                self._notify_change()
            # The dispatch window's host/device split (device-time
            # accounting): the span's END event carries it — distinct
            # fields, so a Perfetto/report reader can say how much of a
            # dispatch bar was compute vs host overhead — and the
            # stage histograms stay exact at any sample rate.
            device_us = int(attempt_timing.get("device_us", 0))
            wait_us = int(attempt_timing.get("worker_wait_us", 0))
            host_us = max(dt_us - device_us - wait_us, 0)
            cm.note(device_us=device_us, host_us=host_us,
                    wait_us=wait_us)
            cm.__exit__(None, None, None)
            metrics.counter("serve_device_us", device_us, lane=lane.idx)
            # The cost-model join (obs/costmodel.py): dispatches and
            # device time accumulated PER (rung, engine, mode, nr), so
            # the roofline table can put modeled bytes moved over
            # measured device time per ladder rung — which engine x
            # rung, what utilization, not just one goodput scalar. nr
            # rides the label because the schedule-stack traffic (and
            # the op budget) depend on the key size, and a mixed
            # 128/256-bit run must not price AES-256 dispatches with
            # the AES-128 record.
            nr = int(getattr(sched, "nr", 0) or 0)  # 0: stubbed scheds
            metrics.counter("serve_rung_dispatches", rung=bucket,
                            engine=self.engine, mode=mode, nr=nr)
            metrics.counter("serve_rung_device_us", device_us,
                            rung=bucket, engine=self.engine, mode=mode,
                            nr=nr)
            incident.record(lane=lane.idx, rung=bucket,
                            engine=self.engine, mode=mode, outcome="ok",
                            device_us=device_us, wall_us=dt_us,
                            batch=label)
            metrics.observe("serve_stage_us", wait_us,
                            stage="worker_wait", exemplar=ex)
            metrics.observe("serve_stage_us", host_us, stage="dispatch",
                            exemplar=ex)
            metrics.observe("serve_stage_us", device_us, stage="device",
                            exemplar=ex)
            if timing is not None:
                timing["worker_wait_us"] = wait_us
                timing["device_us"] = device_us
                timing["total_us"] = int(
                    (lane._clock() - t_place0) * 1e6)
            if tried:
                self.redispatches += 1
                metrics.counter("serve_redispatch", lane=lane.idx)
                trace.counter("serve_redispatch", lane=lane.idx,
                              after=len(tried))
            lane.note_success(blocks, redispatch=bool(tried),
                              probation_batches=self.probation_batches)
            return out, lane, len(tried)

    async def _rescue(self, tried: set) -> Lane | None:
        """Last-resort probe when no placeable lane remains: canary the
        quarantined lanes now rather than fail the batch — a single-lane
        server recovering from a transient hang re-proves its lane here
        instead of answering errors forever."""
        for lane in self.lanes:
            if lane.idx in tried or lane.state != QUARANTINED:
                continue
            if await self.probe_lane_async(lane):
                return lane
        return None

    # -- introspection -----------------------------------------------------
    def quarantine_events(self) -> int:
        return sum(1 for l in self.lanes
                   for t in l.transitions if t["to"] == QUARANTINED)

    def stats(self) -> dict:
        return {
            "count": len(self.lanes),
            "placed_across": sum(1 for l in self.lanes if l.dispatches),
            "redispatches": self.redispatches,
            "quarantine_events": self.quarantine_events(),
            "abandoned_workers": sum(
                l.executor.abandoned for l in self.lanes
                if l.executor is not None),
            "states": {s: sum(1 for l in self.lanes if l.state == s)
                       for s in sorted({l.state for l in self.lanes})},
            "per_lane": [l.stats() for l in self.lanes],
        }
