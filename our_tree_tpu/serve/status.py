"""The operator status endpoint: ``/metrics`` + ``/healthz`` over HTTP.

ot-serve's post-hoc story (``obs.report`` over a finished run dir) goes
blind exactly when an operator needs eyes: DURING the run. This module
is the live view — a deliberately tiny HTTP/1.1 responder on the
server's own asyncio loop (``asyncio.start_server``, stdlib only, no
web framework), so it shares fate with the service it describes: if the
event loop is wedged, ``/healthz`` times out, which is itself the
signal.

* ``GET /metrics`` — the ``obs.metrics`` registry rendered as
  Prometheus exposition text (counters exact at any ``OT_TRACE_SAMPLE``
  rate, log2-bucket histograms with cumulative ``le`` bounds), plus the
  live admission/in-flight gauges re-sampled at scrape time. Point any
  Prometheus scraper — or ``curl`` — at it.
* ``GET /healthz`` — one JSON object: per-lane health states (the
  serve/lanes.py state machine), queue depth + shed/lost ledger,
  in-flight count vs limit, keycache stats, compile counts. ``status``
  is ``"ok"`` while at least one warmed placeable lane exists,
  ``"draining"`` once admission closed, else ``"degraded"`` — a load
  balancer's readiness answer in one field.

Reads only: the endpoint never mutates server state, and a handler
failure answers 500 to that one connection — it can never take the
dispatch loop down (every handler error is contained). Binds 127.0.0.1
by default (an operator/scrape port, not a tenant surface); ``port=0``
binds an ephemeral port published as ``.port`` (tests, multi-instance
hosts). Enabled via ``ServerConfig.status_port`` /
``serve.bench --status-port`` (docs/SERVING.md).
"""

from __future__ import annotations

import asyncio
import json

from ..obs import incident, metrics, profiler, trace
from ..resilience import degrade


class HttpStatusEndpoint:
    """The reusable /metrics + /healthz HTTP responder: subclasses
    provide ``healthz()`` (the live JSON document) and may override
    ``metrics_text()`` (default: the shared registry rendered as
    Prometheus text). ot-serve's ``StatusServer`` and the router's
    ``RouterStatus`` (route/status.py) are the two instances — one
    operator surface, two fault domains."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._host = host
        self._port = int(port)
        self._srv: asyncio.AbstractServer | None = None
        self.port: int | None = None  #: the BOUND port (port=0 resolves)
        self.requests = 0

    async def start(self) -> None:
        self._srv = await asyncio.start_server(
            self._handle, self._host, self._port)
        self.port = self._srv.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._srv is not None:
            self._srv.close()
            await self._srv.wait_closed()
            self._srv = None

    # -- the two documents (subclass surface) ------------------------------
    def healthz(self) -> dict:
        """The live health JSON (the /healthz body) — subclass duty."""
        raise NotImplementedError

    def metrics_text(self, exemplars: bool = False) -> str:
        """The /metrics body; subclasses override to re-sample liveness
        gauges at scrape time before rendering. ``exemplars`` rides the
        scraper's content negotiation: OpenMetrics exemplar tails are
        emitted only to a scraper that asked for OpenMetrics (a classic
        0.0.4 parser would reject them)."""
        return metrics.render_prometheus(exemplars=exemplars)

    async def metrics_text_async(self, exemplars: bool = False) -> str:
        """Awaitable /metrics hook (defaults to the sync body): the
        router's FEDERATED scrape overrides this — it must await its
        backends' /metrics over the network, which a sync method on the
        event loop cannot."""
        return self.metrics_text(exemplars=exemplars)

    def incidentz(self) -> dict:
        """The /incidentz body: this process's flight-recorder state
        (obs/incident.py) — live ring length, dump/suppress counts,
        and a light index of the run dir's bundles. Read-only, like
        everything else on this port."""
        d = trace.run_dir()
        return {
            **incident.counts(),
            "run_dir": d,
            "bundles": incident.bundle_index(d) if d else [],
        }

    def alertz(self) -> dict | None:
        """The /alertz body: the live pulse engine's alert rows +
        fired-rule counts (obs/pulse.py ``alerts_doc``). None (the
        default) answers 404 — an endpoint whose process runs no pulse
        engine (OT_PULSE=0, or a process without one) has no alert
        story to tell. The router FEDERATES this per backend
        (route/status.py), like /profilez."""
        return None

    async def alertz_async(self) -> dict | None:
        """Awaitable /alertz hook (defaults to the sync body) — the
        router's federated version must await its backends."""
        return self.alertz()

    def fleetz(self) -> dict | None:
        """The /fleetz body: the fleet supervisor's elasticity document
        (size, thresholds, scale-event ledger — route/fleet.py
        ``FleetSupervisor.fleetz``). None (the default) answers 404:
        only a status endpoint that OWNS a fleet supervisor — the
        router's, with ``--autoscale`` on — has an elasticity story to
        tell; a worker's does not."""
        return None

    async def profilez_async(self, seconds: float) -> tuple[int, dict]:
        """The /profilez handler: arm one bounded capture window
        (obs/profiler.py) on THIS process — 200 armed, 409 while a
        window is already open (overlapping captures are refused, not
        queued), 503 with tracing off. Armed OFF the event loop
        (executor): jax.profiler's first start_trace pays a
        seconds-scale init, and the observation tool must not stall
        the in-flight requests it exists to observe. The ROUTER
        overrides this to federate the request per backend
        (route/status.py) — same pattern as the /metrics fleet
        scrape."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, profiler.profilez,
                                          seconds)

    # -- the responder ------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), timeout=5.0)
            parts = line.decode("latin-1", "replace").split()
            path = parts[1] if len(parts) >= 2 else "/"
            # Drain the request headers, watching only for the Accept
            # content negotiation (the OpenMetrics exemplar opt-in).
            accept = ""
            while True:
                h = await asyncio.wait_for(reader.readline(), timeout=5.0)
                if not h or h in (b"\r\n", b"\n"):
                    break
                hl = h.decode("latin-1", "replace")
                if hl.lower().startswith("accept:"):
                    accept = hl.partition(":")[2].strip().lower()
            self.requests += 1
            if path.split("?")[0] == "/metrics":
                om = "application/openmetrics-text" in accept
                body = await self.metrics_text_async(exemplars=om)
                if om:
                    # OpenMetrics requires the explicit EOF marker.
                    body += "# EOF\n"
                    ctype = ("application/openmetrics-text; "
                             "version=1.0.0; charset=utf-8")
                else:
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                code, reason = 200, "OK"
            elif path.split("?")[0] == "/healthz":
                body = json.dumps(self.healthz(), indent=1,
                                  sort_keys=True) + "\n"
                ctype = "application/json"
                code, reason = 200, "OK"
            elif path.split("?")[0] == "/incidentz":
                # Off the loop: the bundle index re-reads every
                # incident-*.json in the run dir, and the status
                # surface must not stall the dispatches it observes.
                doc = await asyncio.to_thread(self.incidentz)
                body = json.dumps(doc, indent=1,
                                  sort_keys=True) + "\n"
                ctype = "application/json"
                code, reason = 200, "OK"
            elif path.split("?")[0] == "/profilez":
                query = path.partition("?")[2]
                params = dict(p.split("=", 1)
                              for p in query.split("&") if "=" in p)
                try:
                    secs = float(params.get("seconds", 1.0))
                except ValueError:
                    secs = 1.0
                code, doc = await self.profilez_async(secs)
                body = json.dumps(doc, indent=1, sort_keys=True) + "\n"
                ctype = "application/json"
                reason = {200: "OK", 409: "Conflict",
                          503: "Service Unavailable"}.get(code, "OK")
            elif path.split("?")[0] == "/alertz":
                doc = await self.alertz_async()
                if doc is None:
                    body = "no pulse engine on this endpoint\n"
                    ctype = "text/plain"
                    code, reason = 404, "Not Found"
                else:
                    body = json.dumps(doc, indent=1, sort_keys=True) + "\n"
                    ctype = "application/json"
                    code, reason = 200, "OK"
            elif path.split("?")[0] == "/fleetz":
                doc = self.fleetz()
                if doc is None:
                    body = "no fleet supervisor on this endpoint\n"
                    ctype = "text/plain"
                    code, reason = 404, "Not Found"
                else:
                    body = json.dumps(doc, indent=1, sort_keys=True) + "\n"
                    ctype = "application/json"
                    code, reason = 200, "OK"
            else:
                body = ("not found: try /metrics, /healthz, /incidentz, "
                        "/profilez, /alertz or /fleetz\n")
                ctype = "text/plain"
                code, reason = 404, "Not Found"
        except Exception:  # noqa: BLE001 - a bad scrape must not matter
            body, ctype, code, reason = ("status endpoint error\n",
                                         "text/plain", 500,
                                         "Internal Server Error")
        try:
            raw = body.encode("utf-8")
            writer.write(
                (f"HTTP/1.1 {code} {reason}\r\n"
                 f"Content-Type: {ctype}\r\n"
                 f"Content-Length: {len(raw)}\r\n"
                 "Connection: close\r\n\r\n").encode("latin-1") + raw)
            await writer.drain()
            writer.close()
        except Exception:  # noqa: BLE001 - peer went away mid-reply
            pass


class StatusServer(HttpStatusEndpoint):
    """The serve-side /metrics + /healthz responder riding the serve
    event loop."""

    def __init__(self, server, port: int, host: str = "127.0.0.1"):
        super().__init__(port, host)
        self._server = server
        #: transfer-shed watermark from the previous /healthz poll —
        #: "sustained" shed means sheds grew since the last poll AND
        #: reassembly is still pinned at its budget: backpressure that
        #: is happening NOW, not a count from an old burst.
        self._transfer_sheds_seen = 0
        #: same watermark idiom for the session plane: "shedding" only
        #: when sheds grew since the last poll AND the keystream budget
        #: is still pinned — live backpressure, not an old burst.
        self._session_sheds_seen = 0

    # -- the two documents -------------------------------------------------
    def healthz(self) -> dict:
        """The live health JSON (also the /healthz body)."""
        s = self._server
        pool = s.pool
        lanes_doc: dict = {"count": 0, "states": {}, "per_lane": []}
        placeable = 0
        if pool is not None:
            placeable = len(pool.placeable())
            lanes_doc = {
                "count": len(pool.lanes),
                "placeable": placeable,
                "states": {str(l.idx): l.state for l in pool.lanes},
                "inflight": pool.inflight_now,
                "max_inflight_seen": pool.max_inflight_seen,
                "redispatches": pool.redispatches,
                "quarantine_events": pool.quarantine_events(),
            }
        # The transfer plane's live state (the /healthz blind spot fix):
        # held reassembly bytes vs budget, live ledger rows, sheds.
        transfers_doc = None
        shedding = False
        if s.transfers is not None:
            t = s.transfers.stats()
            budget = int(getattr(s.transfers, "reassembly_budget_bytes",
                                 0) or 0)
            sheds = int(t.get("shed", 0))
            pinned = (budget > 0
                      and int(t.get("held_bytes", 0)) >= budget * 0.9)
            shedding = pinned and sheds > self._transfer_sheds_seen
            self._transfer_sheds_seen = sheds
            transfers_doc = {
                "held_bytes": int(t.get("held_bytes", 0)),
                "held_peak_bytes": int(t.get("held_peak_bytes", 0)),
                "budget_bytes": budget,
                "ledger_live": int(t.get("ledger_live", 0)),
                "shed": sheds,
                "refused": int(t.get("refused", 0)),
                "shedding": shedding,
            }
        # The session plane's live state (serve/session.py): open
        # sessions, held keystream bytes vs budget, sheds — the same
        # blind-spot rule as transfers: state a load balancer must see
        # BEFORE routing more stateful opens here.
        sessions_doc = None
        if getattr(s, "sessions", None) is not None:
            st = s.sessions.stats()
            budget = int(st.get("budget_bytes", 0) or 0)
            sheds = int(st.get("shed", 0))
            pinned = (budget > 0
                      and int(st.get("held_bytes", 0)) >= budget * 0.9)
            sess_shedding = pinned and sheds > self._session_sheds_seen
            self._session_sheds_seen = sheds
            shedding = shedding or sess_shedding
            sessions_doc = {
                "open": int(st.get("open", 0)),
                "held_bytes": int(st.get("held_bytes", 0)),
                "budget_bytes": budget,
                "shed": sheds,
                "refused": int(st.get("refused", 0)),
                "evicted": int(st.get("evicted", 0)),
                "hit_rate": st["prefetch"]["hit_rate"],
                "replays": int(st["prefetch"]["replays"]),
                "shedding": sess_shedding,
            }
        if s.queue.closed:
            status = "draining"
        elif placeable > 0 and not shedding:
            status = "ok"
        else:
            # No placeable lane, OR the transfer plane is pinned at its
            # reassembly budget and actively shedding new transfers —
            # either way this worker should stop receiving load.
            status = "degraded"
        doc = {
            "status": status,
            "engine": s.engine,
            "lanes": lanes_doc,
            "queue": s.queue.stats(),
            "inflight_limit": s.inflight_limit,
            "batches": {"ok": s.batches, "failed": s.batches_failed,
                        "timed_out": s.batches_timed_out},
            "keycache": s.keycache.stats(),
            "compiles": {"warmup": s.warmup_compiles,
                         "steady": s.steady_compiles()},
            "degraded": degrade.events(),
        }
        if transfers_doc is not None:
            doc["transfers"] = transfers_doc
        if sessions_doc is not None:
            doc["sessions"] = sessions_doc
        pulse_t = getattr(s, "pulse", None)
        if pulse_t is not None:
            # The live capacity estimate (obs/pulse.py): what the fleet
            # supervisor's headroom policy reads off the gossip scrape.
            doc["capacity"] = pulse_t.engine.capacity()
        return doc

    def alertz(self) -> dict | None:
        pulse_t = getattr(self._server, "pulse", None)
        return pulse_t.engine.alerts_doc() if pulse_t is not None else None

    def metrics_text(self, exemplars: bool = False) -> str:
        """The /metrics body: the registry plus scrape-time liveness
        gauges (queue depth and in-flight are refreshed HERE so a
        scrape between requests still sees current pressure, not the
        last event's)."""
        s = self._server
        metrics.gauge("serve_queue_depth", s.queue.depth())
        if s.pool is not None:
            metrics.gauge("serve_inflight", s.pool.inflight_now)
        return metrics.render_prometheus(exemplars=exemplars)
