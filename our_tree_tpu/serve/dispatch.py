"""Per-lane dispatch executors: the worker seam under overlapped serving.

The paper's whole decomposition — independent chunks crypted
concurrently (``aes-modes/test.c:33-35``) — only pays at the lane level
if more than one lane can be *in flight* at once. PR 6's lanes were
fault domains with main-thread dispatch (one device busy at a time, the
watchdog's SIGALRM contract); this module is the throughput half: each
lane owns ONE worker thread, the batcher loop submits engine calls and
keeps forming batches while up to ``--max-inflight`` dispatches run
concurrently, and completions feed back into the asyncio loop as
futures.

The watchdog contract moves with the dispatch. On the main thread a
deadline expiry delivers ``DispatchTimeout`` via SIGALRM; a worker
thread cannot be signalled that way (CPython runs signal handlers on
the main thread only), and a genuinely wedged device call cannot be
interrupted in-process at all. So the kill path here is **fail the
future, abandon the thread**: the executor registers a per-thread kill
hook (``watchdog.thread_kill_hook``) around every unit it runs, and
when that unit's ``watchdog.deadline`` — armed inside
``Lane.engine_call`` exactly as on the main thread, multiplexed by the
watchdog's per-entry-thread scheduler — expires, the expiry thread
dumps all stacks, stamps the degrade ledger, fails the unit's future
with ``DispatchTimeout`` (the asyncio waiter proceeds to failover
immediately), and this executor marks its worker ABANDONED. The wedged
thread is left behind as kill evidence (its ``lane-dispatch`` span
stays orphaned — the same convention as a SIGKILLed sweep child); a
fresh worker is spawned lazily on the lane's next use (the canary probe
that would release the lane needs a live thread). If the abandoned
thread ever wakes, it notices its generation is stale, discards its
result, and exits — it never races the replacement for the lane's
device.

otlint enforces the seam shape (``serve-lane-seam`` /
``dispatch-watchdog``, docs/ANALYSIS.md): worker threads in ``serve/``
exist only here, and the executor's unit invocation (``unit()``) is
legal only inside the ``watchdog.thread_kill_hook`` guard — a worker
dispatch with no kill path is a hang with no evidence.

Stdlib-only: the device contact stays in ``serve/lanes.py``
(``Lane.engine_call``); this module only runs callables on a guarded
thread.
"""

from __future__ import annotations

import concurrent.futures
import queue as _queue
import threading
import time

from ..obs import metrics
from ..resilience import watchdog


def _resolve(fut: concurrent.futures.Future, result=None, exc=None) -> None:
    """Settle ``fut`` from whichever side got there first: the worker
    completing or the watchdog kill path failing it. The loser's write
    is discarded (the future's internal lock arbitrates)."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
    except concurrent.futures.InvalidStateError:
        pass  # already settled by the other side


class LaneExecutor:
    """One worker thread running one lane's engine calls in FIFO order.

    ``submit(unit)`` returns a ``concurrent.futures.Future`` the asyncio
    side awaits via ``asyncio.wrap_future``. The worker is spawned
    lazily and replaced after a kill (``abandoned`` counts the wedged
    threads left behind). ``close()`` ends an idle worker; a wedged one
    is already abandoned and exits on wake via its stale generation.
    """

    def __init__(self, name: str, lane: int | None = None):
        self._name = name
        self._lane = lane
        self._lock = threading.Lock()
        self._gen = 0
        self._q: _queue.SimpleQueue | None = None
        self._thread: threading.Thread | None = None
        self.abandoned = 0

    def submit(self, unit) -> concurrent.futures.Future:
        """Queue one callable for the worker; spawns/replaces the worker
        if none is live (first use, post-kill, or post-close)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._gen += 1
                self._q = _queue.SimpleQueue()
                self._thread = threading.Thread(
                    target=self._run, args=(self._gen, self._q),
                    daemon=True, name=self._name)
                self._thread.start()
            self._q.put((fut, unit, time.monotonic()))
        return fut

    def close(self) -> None:
        """Stop the current worker after its queued work (idempotent).
        An abandoned (wedged) worker needs no stop — it exits on wake."""
        with self._lock:
            if self._q is not None and self._thread is not None \
                    and self._thread.is_alive():
                self._q.put(None)
            self._thread = None
            self._q = None

    # -- the worker ---------------------------------------------------------
    def _run(self, gen: int, q: _queue.SimpleQueue) -> None:
        while True:
            item = q.get()
            if item is None:
                return  # close(): drained and dismissed
            fut, unit, t_submit = item
            if not fut.set_running_or_notify_cancel():
                continue
            # Executor-queue residency: how long a unit waited for its
            # worker. With the pool's one-batch-per-lane discipline this
            # is ~0; growth here means submits are racing the lane's
            # own completion (obs/metrics.py, /metrics).
            metrics.observe("serve_worker_wait_us",
                            (time.monotonic() - t_submit) * 1e6,
                            lane=self._lane)
            # The kill path: when a watchdog.deadline armed INSIDE this
            # unit (Lane.engine_call) expires, the expiry thread calls
            # the hook — fail the future, mark this worker abandoned —
            # instead of the main-thread SIGALRM delivery.
            def kill(exc, fut=fut):
                self._abandon(gen)
                _resolve(fut, exc=exc)

            with watchdog.thread_kill_hook(kill):
                try:
                    result = unit()
                except BaseException as e:  # noqa: BLE001 - future carries it
                    _resolve(fut, exc=e)
                else:
                    _resolve(fut, result=result)
            with self._lock:
                stale = self._gen != gen
            if stale:
                # Retired mid-call (the kill path fired, or close() +
                # submit replaced this worker) but the call returned
                # after all: a fresh worker owns the lane now — fail
                # anything still queued HERE (nobody else will ever
                # read this queue) and leave, never double-serving.
                self._fail_pending(q, "worker retired")
                return

    def _fail_pending(self, q: _queue.SimpleQueue | None, why: str) -> None:
        """Fail every (fut, unit) still queued on a retired queue: the
        units never ran, so their deadlines never armed and no watchdog
        will ever unblock their waiters — a stranded future would block
        forever. close() sentinels are skipped."""
        while q is not None:
            try:
                item = q.get_nowait()
            except _queue.Empty:
                return
            if item is None:
                continue  # a close() sentinel
            _resolve(item[0], exc=RuntimeError(
                f"{self._name}: {why} before this unit ran"))

    def _abandon(self, gen: int) -> None:
        """Retire generation ``gen``'s worker (watchdog kill path): the
        next submit spawns a replacement; the wedged thread's eventual
        wake sees the stale generation, fails anything still queued on
        its retired queue, and exits. Units queued behind the wedged one
        are also failed here (the wedged thread may never wake). Today
        the lane pool holds one batch per lane so the queue depth is 1,
        but the executor's FIFO contract must not depend on that distant
        discipline."""
        with self._lock:
            if self._gen != gen:
                return
            self._gen += 1
            self._thread = None
            q, self._q = self._q, None
            self.abandoned += 1
        self._fail_pending(q, "worker abandoned (watchdog kill)")
