"""``python -m our_tree_tpu.serve.bench`` — the serving benchmark.

Closed-loop loadgen against an in-process Server: mixed request sizes,
multi-tenant keys, p50/p95/p99 latency, goodput GB/s, batch-occupancy
histogram, per-LANE dispatch/goodput breakdown with the health
transition log — and two hard contracts the run exits 1 on:

* **zero recompiles**: after the ladder warmup (every lane x rung),
  steady-state serving must trigger no backend compile at all (the
  ``server.compile_count`` monitor; ``--allow-recompiles`` waives it,
  e.g. an exotic key size outside the warmed set);
* **zero lost requests**: every ACCEPTED request must be answered —
  payload or coded error — even across a faulted run
  (``queue.stats()["lost"]``, counted at the one resolution seam).
  A server that drops work silently is broken in a way error counts
  cannot show.

Output convention follows the repo-root bench: human-readable ``#``
lines, then ONE parseable JSON line last on stdout (the CI contract),
plus a ``SERVE_r*.json`` artifact alongside the driver's
``BENCH_r*.json`` (``--artifact`` overrides the path; otherwise the
next free index at the repo root).

Fault rehearsals (docs/SERVING.md, the CI ``serve`` job):

* ``OT_FAULTS=dispatch_fail:1 ... --retries 1 --lanes 1`` — the armed
  batch dies with no failover target, its requests get
  ``dispatch-failed`` responses, the run completes rc 0
  (server-stays-up IS the contract; the artifact records the errors).
* ``OT_FAULTS=dispatch_hang:1 ... --lanes 1 --dispatch-deadline 3`` —
  the armed batch wedges; the watchdog kills it at the deadline, the
  lane is quarantined (then canary-released), its requests get
  ``deadline`` errors, and the abandoned ``lane-dispatch`` span is the
  run's ONLY orphan (``obs.report --check --expected-orphans
  lane-dispatch``).
* ``XLA_FLAGS=--xla_force_host_platform_device_count=8
  OT_FAULTS=lane_hang:1@lane=3 ... --lanes 8`` — the LANE-KILL drive:
  lane 3 wedges mid-batch, is quarantined, and its batch re-dispatches
  bit-exactly on a healthy lane — ZERO request errors, zero lost,
  exactly one quarantine event, lanes 0-2,4-7 keep serving.

``--unquarantine lane:<i>`` (with ``--journal``) is the serve-side
release edit: it drops the named lanes' failure rows from the journal —
the SAME ``resilience.journal.clear_failures`` edit behind
``harness.bench --unquarantine``, so operators have one quarantine
model — and exits without serving.
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import re
import sys

from ..obs import costmodel, incident, metrics, profiler, slo, trace
from ..resilience import degrade, watchdog
from ..resilience import journal as journal_mod
from . import batcher, loadgen
from .server import Server, ServerConfig


def _repo_root() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _next_artifact(root: str) -> str:
    """The next free ``SERVE_r<NN>.json`` at the repo root."""
    taken = [0]
    for p in glob.glob(os.path.join(root, "SERVE_r*.json")):
        m = re.match(r"SERVE_r(\d+)\.json$", os.path.basename(p))
        if m:
            taken.append(int(m.group(1)))
    return os.path.join(root, f"SERVE_r{max(taken) + 1:02d}.json")


async def _arm_profile_window(start_s: float, dur_s: float) -> None:
    """The --profile-window arm: wait out the offset, then open one
    bounded capture (obs/profiler.py — the same window /profilez and
    the incident recorder arm). Refusals are reported, never fatal: a
    profile flag must not fail the drive it observes."""
    await asyncio.sleep(start_s)
    try:
        out = profiler.start_window(dur_s, armed_by="cli")
        print(f"# profile-window: armed {dur_s:g}s "
              f"(tier={out['tier']})", file=sys.stderr)
    except (profiler.CaptureBusy, profiler.CaptureDisabled) as e:
        print(f"# profile-window: not armed: {e}", file=sys.stderr)


async def _drive(args, probes):
    cfg = ServerConfig(
        engine=args.engine,
        min_bucket_blocks=args.bucket_min,
        max_bucket_blocks=args.bucket_max,
        key_slots=args.key_slots,
        native_threads=args.native_threads,
        max_depth=args.queue_depth,
        tenant_depth_frac=args.tenant_depth_frac,
        low_priority_tenants=tuple(args.low_priority_tenant or ()),
        priority_depth_frac=args.priority_depth_frac,
        request_deadline_s=args.deadline,
        dispatch_deadline_s=args.dispatch_deadline,
        retries=args.retries,
        lanes=args.lanes,
        probe_every=args.probe_every,
        journal=args.journal,
        max_inflight=args.max_inflight,
        status_port=args.status_port,
        modes=args.mode_list,
        ceiling_gbps=args.ceiling_gbps,
        session_window_bytes=args.session_window_bytes,
        session_quantum_bytes=args.session_quantum_bytes,
        session_prefetch_slots=args.session_prefetch_slots,
        session_budget_bytes=args.session_budget_bytes,
        session_per_tenant=args.session_per_tenant)
    server = Server(cfg)
    await server.start()
    arm_task = None
    if args.profile_window is not None:
        arm_task = asyncio.ensure_future(
            _arm_profile_window(*args.profile_window))
    report = await loadgen.run(
        server, args.requests, concurrency=args.concurrency,
        sizes=args.sizes, tenants=args.tenants,
        keys_per_tenant=args.keys_per_tenant, seed=args.seed,
        verify_every=args.verify_every, probes=probes,
        arrival_rate=args.arrival_rate, modes=args.mix_modes,
        sessions=args.sessions, session_chunks=args.session_chunks,
        session_chunk_bytes=args.session_chunk_bytes,
        session_scripts=args.session_scripts)
    if arm_task is not None and not arm_task.done():
        arm_task.cancel()  # the drive ended before the window's offset
        try:
            await arm_task
        except asyncio.CancelledError:
            pass
    await server.stop()
    # A window still capturing at drain (a long --profile-window, a
    # late /profilez) closes CLEANLY here — shortened, summarised,
    # never lost — before the artifact is stamped.
    profiler.finish()
    return server, report


def _lane_summary(stats: dict, wall_s: float) -> dict:
    """The artifact's ``lanes`` section: pool aggregates plus per-lane
    goodput (dispatched bytes over the run's wall — the placement
    evidence the ISSUE's "batches placed across >= 2 lanes" gate
    reads) and busy-fraction (in-flight wall time over run wall — the
    overlap evidence: fractions summing well past 1.0 across lanes is
    what "dispatches actually overlapped" looks like per device)."""
    pool = dict(stats["lanes"])
    for row in pool.get("per_lane", []):
        row["goodput_gbps"] = (round(row["bytes"] / 1e9 / wall_s, 4)
                               if wall_s > 0 else 0.0)
        row["busy_fraction"] = (round(row["busy_s"] / wall_s, 4)
                                if wall_s > 0 else 0.0)
    return pool


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m our_tree_tpu.serve.bench",
        description="closed-loop serving benchmark (docs/SERVING.md)")
    ap.add_argument("--requests", type=int, default=500)
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--arrival-rate", type=float, default=None,
                    metavar="REQ_PER_S",
                    help="open-loop mode: submit requests at this fixed "
                         "rate regardless of service rate (outstanding "
                         "unbounded; --concurrency is ignored). Closed "
                         "loop with few clients self-throttles to the "
                         "service rate and cannot expose overlap gains — "
                         "this is the saturation run's offered-load knob")
    ap.add_argument("--max-inflight", type=int, default=None, metavar="N",
                    help="dispatches in flight at once across the lane "
                         "pool (default: one per lane — full overlap; "
                         "1 = the serialized pre-overlap control run)")
    ap.add_argument("--min-inflight", type=int, default=None, metavar="N",
                    help="fail (exit 1) if the measured max in-flight "
                         "concurrency ends below N — the overlap gate: "
                         "a multi-lane run whose dispatches never "
                         "overlapped (max_inflight 1) is serialized "
                         "serving wearing lanes")
    ap.add_argument("--mixed-sizes", action="store_true",
                    help=f"request sizes drawn from {loadgen.MIXED_SIZES} "
                         "(the ladder-exercising menu)")
    ap.add_argument("--sizes", default=None, metavar="B1,B2",
                    help="explicit request-size menu in bytes (comma "
                         "list; overrides --mixed-sizes/--size-bytes). "
                         "The mixed-MODE drive wants the top size one "
                         "rung under the ceiling: a GCM request carries "
                         "its J0 row, so a payload exactly filling the "
                         "ceiling refuses too-large in gcm modes")
    ap.add_argument("--size-bytes", type=int, default=4096,
                    help="fixed request size when --mixed-sizes is off")
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--keys-per-tenant", type=int, default=2)
    ap.add_argument("--tenant-heavy", action="store_true",
                    help="multi-tenant-heavy mix: many tenants, one key "
                         "each, small sizes "
                         f"{loadgen.TENANT_HEAVY_SIZES} — full rungs can "
                         "only come from multi-key packing (the "
                         "coalesce_efficiency rehearsal)")
    ap.add_argument("--modes", default="ctr", metavar="M1,M2",
                    help="served-mode MIX (comma list from ctr, gcm, "
                         "gcm-open, cbc): the server enables and warms "
                         "exactly these ladders, and the loadgen draws "
                         "each request's mode uniformly from them — the "
                         "mixed-workload drive (docs/SERVING.md AEAD "
                         "section). gcm probes pin ciphertext AND tag "
                         "bit-exactly against the pure-host reference")
    ap.add_argument("--engine", default="auto",
                    help="serve engine tier: auto (ranked jax ladder on "
                         "an accelerator, native AESNI host tier on "
                         "CPU), native, or any registered jax engine "
                         "name (docs/SERVING.md tier table)")
    ap.add_argument("--key-slots", type=int, default=None, metavar="K",
                    help="key slots per dispatch (the fixed K "
                         "dimension; default "
                         f"{batcher.DEFAULT_KEY_SLOTS})")
    ap.add_argument("--native-threads", type=int, default=0,
                    help="native-tier ECB threads per slot run "
                         "(0 = size-based default)")
    ap.add_argument("--bucket-min", type=int, default=32, metavar="BLOCKS")
    ap.add_argument("--bucket-max", type=int, default=4096, metavar="BLOCKS")
    ap.add_argument("--queue-depth", type=int, default=1024)
    ap.add_argument("--tenant-depth-frac", type=float, default=1.0,
                    metavar="FRAC",
                    help="one tenant's max share of the queue depth: "
                         "past FRAC*depth queued requests that tenant "
                         "sheds itself (serve_shed{reason=tenant}) while "
                         "other tenants keep being admitted (1.0 = "
                         "global shed only)")
    ap.add_argument("--low-priority-tenant", action="append", default=None,
                    metavar="TENANT",
                    help="mark TENANT low priority: sheds first past "
                         "--priority-depth-frac of the queue "
                         "(serve_shed{reason=priority}; repeatable)")
    ap.add_argument("--priority-depth-frac", type=float, default=0.5,
                    metavar="FRAC",
                    help="queue-depth fraction past which low-priority "
                         "requests shed (1.0 disables the tier split)")
    ap.add_argument("--deadline", type=float, default=30.0,
                    help="per-request residency deadline, seconds")
    ap.add_argument("--dispatch-deadline", type=float,
                    default=watchdog.default_deadline_s() or 10.0,
                    help="watchdog deadline per lane engine call, seconds "
                         "(default: OT_DISPATCH_DEADLINE, else 10)")
    ap.add_argument("--retries", type=int, default=2,
                    help="dispatch attempts per batch PER LANE "
                         "(1 = no on-lane retry; cross-lane failover "
                         "happens regardless)")
    ap.add_argument("--lanes", type=int, default=None, metavar="N",
                    help="dispatch lanes (default: one per visible "
                         "device; N may exceed the device count for "
                         "single-device rehearsal)")
    ap.add_argument("--probe-every", type=int, default=8, metavar="BATCHES",
                    help="canary-probe quarantined lanes every N batches")
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="serve journal (lane quarantine persistence; "
                         "docs/RESILIENCE.md)")
    ap.add_argument("--unquarantine", action="append", default=None,
                    metavar="LANE",
                    help="release the named lane (e.g. lane:3) by "
                         "dropping its failure rows from --journal "
                         "(repeatable), then exit — the same "
                         "clear_failures edit harness.bench uses")
    ap.add_argument("--profile-window", default=None, metavar="START:DUR",
                    help="arm ONE bounded device-profiling capture "
                         "(obs/profiler.py) DUR seconds long, START "
                         "seconds into the drive: jax.profiler trace "
                         "where available (TensorBoard/Perfetto), host "
                         "stack sampling on the native tier, plus the "
                         "per-rung kernel-wall window summary either "
                         "way — landing in the OT_TRACE_DIR run layout "
                         "and stamped into the artifact's `profile` "
                         "section (requires OT_TRACE_DIR)")
    ap.add_argument("--status-port", type=int, default=None, metavar="PORT",
                    help="serve the operator status endpoint on "
                         "127.0.0.1:PORT for the duration of the drive: "
                         "/metrics (Prometheus text from the obs.metrics "
                         "registry) and /healthz (lane health, queue, "
                         "in-flight, keycache as JSON) — the live view "
                         "the CI mid-drive curl gates on (0 = ephemeral)")
    ap.add_argument("--slo", default=None, metavar="BASELINE.json",
                    help="after the drive, gate this run's p50/p95/p99, "
                         "goodput, error/lost/recompile counts against "
                         "the committed SERVE_r*.json baseline with "
                         "per-metric tolerances (obs/slo.py) and exit 1 "
                         "on any regression — the SLO gate CI runs "
                         "against SERVE_r04_control.json")
    ap.add_argument("--slo-tolerance", default=None, metavar="SPEC",
                    help="per-metric tolerance overrides for --slo, "
                         "e.g. 'p95_ms=2.0,goodput_gbps=0.5' (fractions "
                         "of the baseline; counts are never tolerated)")
    ap.add_argument("--verify-every", type=int, default=8,
                    help="every Nth request replays a pinned probe and "
                         "checks bit-exactness (0 = off)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--artifact", default=None, metavar="PATH",
                    help="artifact path (default: next SERVE_r*.json at "
                         "the repo root)")
    ap.add_argument("--allow-recompiles", action="store_true",
                    help="do not fail on post-warmup backend compiles")
    ap.add_argument("--ceiling-gbps", type=float, default=None,
                    metavar="GBPS",
                    help="the device roofline to report utilization "
                         "against (scripts/vpu_ceiling.py names it for "
                         "a measured TPU): the artifact's device "
                         "section records device-time goodput / GBPS")
    ap.add_argument("--min-coalesce", type=float, default=None,
                    metavar="FRAC",
                    help="fail (exit 1) if coalesce_efficiency — payload "
                         "blocks over dispatched blocks, rung padding "
                         "included — ends below FRAC (the CI multi-key "
                         "drive gates 0.5: a rung-packer regression "
                         "re-fragmenting tenants shows up here first)")
    ap.add_argument("--sessions", type=int, default=0, metavar="N",
                    help="run N concurrent rc4 streaming sessions beside "
                         "the ordinary traffic (requires rc4 in --modes); "
                         "every data chunk is verified against the "
                         "pinned host-PRGA script (serve/session.py)")
    ap.add_argument("--session-chunks", type=int, default=8, metavar="M",
                    help="data chunks per session (default 8)")
    ap.add_argument("--session-chunk-bytes", default="256,1024,4096",
                    metavar="B1,B2",
                    help="chunk sizes the session scripts cycle through "
                         "(16-byte multiples; default 256,1024,4096)")
    ap.add_argument("--session-window-bytes", type=int, default=65536)
    ap.add_argument("--session-quantum-bytes", type=int, default=4096)
    ap.add_argument("--session-prefetch-slots", type=int, default=8)
    ap.add_argument("--session-budget-bytes", type=int, default=8 << 20)
    ap.add_argument("--session-per-tenant", type=int, default=16)
    ap.add_argument("--min-session-hit-rate", type=float, default=None,
                    metavar="FRAC",
                    help="fail (exit 1) if the keystream prefetch hit "
                         "rate ends below FRAC (the CI session drive "
                         "gates 0.9: chunks stalling on demand refills "
                         "mean the prefetcher stopped running ahead)")
    ap.add_argument("--min-session-replays", type=int, default=None,
                    metavar="N",
                    help="fail (exit 1) unless at least N keystream "
                         "refills replayed from a carry checkpoint on a "
                         "healthy lane (the failover drive asserts the "
                         "bit-exact replay path actually exercised)")
    args = ap.parse_args(argv)
    if args.tenant_heavy:
        args.sizes = loadgen.TENANT_HEAVY_SIZES
        args.tenants = max(args.tenants, 24)
        args.keys_per_tenant = 1
    elif args.sizes:
        try:
            args.sizes = tuple(int(s) for s in args.sizes.split(",") if s)
        except ValueError:
            ap.error(f"--sizes wants a comma list of byte counts, "
                     f"got {args.sizes!r}")
    else:
        args.sizes = (loadgen.MIXED_SIZES if args.mixed_sizes
                      else (args.size_bytes,))
    if args.key_slots is None:
        args.key_slots = batcher.DEFAULT_KEY_SLOTS
    args.mode_list = tuple(m.strip() for m in args.modes.split(",")
                           if m.strip()) or ("ctr",)
    if args.profile_window is not None:
        try:
            start_s, _, dur_s = args.profile_window.partition(":")
            args.profile_window = (max(float(start_s), 0.0),
                                   max(float(dur_s), 0.05))
        except ValueError:
            ap.error(f"--profile-window wants <start_s>:<dur_s>, got "
                     f"{args.profile_window!r}")
        if not trace.enabled():
            ap.error("--profile-window needs OT_TRACE_DIR: the capture "
                     "artifacts land in the trace run layout")
    if "gcm-open" in args.mode_list and not args.verify_every:
        ap.error("--modes gcm-open requires --verify-every > 0: open "
                 "traffic replays the per-size sealed probe pairs "
                 "(a made-up tag would answer auth-failed by design)")
    if args.sessions and "rc4" not in args.mode_list:
        ap.error("--sessions requires rc4 in --modes: session traffic "
                 "IS the rc4 mode (serve/session.py)")
    try:
        args.session_chunk_bytes = tuple(
            int(s) for s in args.session_chunk_bytes.split(",") if s)
    except ValueError:
        ap.error(f"--session-chunk-bytes wants a comma list of byte "
                 f"counts, got {args.session_chunk_bytes!r}")
    if any(b <= 0 or b % 16 for b in args.session_chunk_bytes):
        ap.error("--session-chunk-bytes must be positive 16-byte "
                 "multiples (the queue refuses partial blocks)")
    # rc4 never rides the uniform per-request mode draw — a session
    # chunk without an open session is a refusal by design. The server
    # still enables the mode (args.mode_list); only the random mix and
    # the pinned probes exclude it.
    args.mix_modes = (tuple(m for m in args.mode_list if m != "rc4")
                      or ("ctr",))
    if args.mode_list == ("rc4",) and args.requests:
        ap.error("--modes rc4 alone serves only session traffic: pass "
                 "--requests 0, or add a stateless mode for the "
                 "ordinary mix (e.g. --modes ctr,rc4)")

    if args.unquarantine:
        if not args.journal:
            ap.error("--unquarantine requires --journal "
                     "(the ledger being edited)")
        trace.ensure_run()
        cleared = journal_mod.clear_failures(args.journal,
                                             args.unquarantine)
        for unit, n in sorted(cleared.items()):
            if n:  # a release point for a unit never quarantined would
                # pollute every trace audit that reconstructs releases
                trace.point("quarantine-release", unit=unit, cleared=n)
            print(f"# unquarantine: {unit}: cleared {n} failure row(s)"
                  + ("" if n else " (none recorded)"))
        return 0

    trace.ensure_run()
    # Captures from BEFORE this drive (an embedding test harness's
    # earlier run in the same process) are not this artifact's story.
    profile_before = profiler.last_summary()
    # Reference outputs BEFORE the server's warmup marker: the
    # byte-exact models path compiles per probe size (the AEAD/CBC
    # references are pure-host numpy — no compile either way), and
    # those compiles belong to the harness, not to steady-state serving.
    probes = (loadgen.make_probes(args.sizes, args.seed, args.mode_list)
              if args.verify_every else [])
    # Session scripts too: the host-PRGA references are pure numpy (no
    # compile either way), but pinning them here keeps the one rule —
    # everything pre-computed, nothing reference-shaped after warmup.
    args.session_scripts = (loadgen.make_session_probes(
        args.sessions, args.session_chunks, args.seed,
        chunk_sizes=args.session_chunk_bytes, tenants=args.tenants)
        if args.sessions else None)
    server, report = asyncio.run(_drive(args, probes))
    stats = server.stats()
    lanes = _lane_summary(stats, report.wall_s)
    lost = stats["queue"]["lost"]

    overlap = stats["overlap"]
    loop_desc = (f"open-loop {args.arrival_rate:g}/s"
                 if args.arrival_rate else
                 f"concurrency={args.concurrency}")
    print(f"# serve: engine={stats['engine']} ladder={stats['rungs']} "
          f"lanes={lanes['count']} {loop_desc} "
          f"tenants={args.tenants}")
    print(f"# overlap: max_inflight={overlap['max_inflight']} "
          f"(limit {overlap['inflight_limit']}) lane busy-fractions "
          + " ".join(f"{row['busy_fraction']:.2f}"
                     for row in lanes["per_lane"]))
    print(f"# requests={report.requests} ok={report.ok} "
          f"errors={report.errors or '{}'} lost={lost} "
          f"verified={report.verified} mismatches={report.mismatches}")
    print(f"# latency ms: p50={report.p50_ms} p95={report.p95_ms} "
          f"p99={report.p99_ms}  goodput={report.goodput_gbps:.4f} GB/s "
          f"wall={report.wall_s:.3f}s")
    coal = stats["coalesce"]
    print(f"# batches={stats['batches']} "
          f"failed={stats['batches_failed']} "
          f"timed_out={stats['batches_timed_out']} "
          f"redispatches={lanes['redispatches']} "
          f"quarantines={lanes['quarantine_events']} "
          f"compiles: warmup={stats['compiles']['warmup']} "
          f"steady={stats['compiles']['steady']}")
    print(f"# coalesce: efficiency={coal['efficiency']:.4f} "
          f"({coal['payload_blocks']}/{coal['dispatched_blocks']} blocks) "
          f"slot_fill={coal['slot_fill']:.4f} "
          f"({coal['slots_used']}/{stats['batches']}x{coal['key_slots']} "
          f"slots)")
    for row in lanes["per_lane"]:
        tr = "".join(f" [{t['prev']}->{t['to']}:{t['why']}]"
                     for t in row["transitions"])
        print(f"#   lane {row['lane']} ({row['device']}): "
              f"{row['dispatches']} dispatch(es), {row['blocks']} blocks, "
              f"{row['goodput_gbps']:.4f} GB/s, state={row['state']}{tr}")
    for bucket, h in stats["occupancy"].items():
        print(f"#   bucket {bucket:>5}: {h['batches']} batch(es), "
              f"mean occupancy {h['mean_occupancy']:.2%}")
    # The registry view (obs/metrics.py): exact whatever OT_TRACE_SAMPLE
    # says — dispatch-latency percentiles interpolated from the log2
    # buckets, admission pressure, keycache totals.
    disp = metrics.hist_merged("serve_dispatch_us")
    if disp:
        print("# metrics: dispatch_us "
              f"p50={metrics.percentile_from_buckets(disp, 50):.0f} "
              f"p95={metrics.percentile_from_buckets(disp, 95):.0f} "
              f"p99={metrics.percentile_from_buckets(disp, 99):.0f} "
              f"({sum(disp.values())} obs)  "
              f"queue_depth_peak={stats['queue'].get('depth_peak', 0)}  "
              f"requests={metrics.counter_total('serve_requests'):.0f}")
    # Device-time accounting (serve/lanes.py): the block-until-ready
    # fence / native engine-compute window, summed across lanes and
    # split out from host busy time — with the served bytes over it as
    # device-time goodput, reportable against the roofline
    # (scripts/vpu_ceiling.py) to say how much of the gap to the
    # offline BENCH_r* number is device vs host/queue/wire.
    stages = metrics.stage_percentiles()
    device_s = sum(row.get("device_s", 0.0) for row in lanes["per_lane"])
    busy_s = sum(row.get("busy_s", 0.0) for row in lanes["per_lane"])
    served_bytes = metrics.counter_total("serve_served_bytes")
    device_gbps = (served_bytes / 1e9 / device_s) if device_s > 0 else 0.0
    device = {
        "device_s": round(device_s, 6),
        "busy_s": round(busy_s, 6),
        "host_s": round(max(busy_s - device_s, 0.0), 6),
        "device_gbps": round(device_gbps, 4),
        "ceiling_gbps": args.ceiling_gbps,
        "utilization": (round(device_gbps / args.ceiling_gbps, 4)
                        if args.ceiling_gbps else None),
    }
    print(f"# device: device_s={device['device_s']:.3f} "
          f"host_s={device['host_s']:.3f} "
          f"device_goodput={device_gbps:.4f} GB/s"
          + (f" utilization={device['utilization']:.1%} of "
             f"{args.ceiling_gbps:g} GB/s roofline"
             if args.ceiling_gbps else ""))
    if stages:
        print("# stages: " + "  ".join(
            f"{s}:p95={st['p95_us']:.0f}µs"
            for s, st in stages.items()))

    # The cost/attribution plane (obs/costmodel.py): modeled HBM bytes
    # per dispatch x measured per-rung dispatch counts over per-rung
    # DEVICE time — achieved GB/s *moved* (traffic, not payload: CTR's
    # counter+keystream overhead is the difference) and utilization
    # against the measured roofline, per engine x mode x rung. This is
    # the artifact section that decomposes a serve number below the
    # offline BENCH_r* figure into "which kernel, what utilization".
    cost = costmodel.cost_section(server.cost_records,
                                  metrics.snapshot()["counters"],
                                  ceiling_gbps=args.ceiling_gbps)
    for row in cost["rows"]:
        util = (f" util={row['utilization']:.1%}"
                if row["utilization"] is not None else "")
        print(f"# cost: {row['engine']}/{row['mode']} r{row['rung']}: "
              f"{row['dispatches']} disp x "
              f"{row['modeled_dispatch_bytes'] / 1e6:.3f} MB modeled, "
              f"device {row['device_s']:.3f}s -> "
              f"{row['achieved_gbps']:.3f} GB/s moved{util}")

    # Warmup compile cost (the jax.monitoring listener routed into
    # serve_compile_us{engine, rung}): per-rung compile counts and
    # totals — the startup bill that dominates TPU warmup and was
    # invisible behind the bare compile COUNT until now.
    comp_items = metrics.hist_items("serve_compile_us")
    compile_by_rung: dict = {}
    for labels, h in comp_items:
        key = str(labels.get("rung", 0))
        agg = compile_by_rung.setdefault(key, {"count": 0, "us": 0.0})
        agg["count"] += h["count"]
        agg["us"] += h["sum"]
    if compile_by_rung:
        total_us = sum(a["us"] for a in compile_by_rung.values())
        print(f"# compile: {sum(a['count'] for a in compile_by_rung.values())} "
              f"compile(s), {total_us / 1e6:.2f}s total  "
              + "  ".join(
                  f"r{k}:{a['count']}x{a['us'] / 1e6:.2f}s"
                  for k, a in sorted(compile_by_rung.items(),
                                     key=lambda kv: int(kv[0]))))
        compile_by_rung = {k: {"count": a["count"],
                               "total_us": round(a["us"], 1)}
                           for k, a in compile_by_rung.items()}

    # The profile section (obs/profiler.py): the armed window's capture
    # summary — span, tier, per-rung kernel wall inside the window —
    # joined against the cost model so modeled utilization gets a
    # measured in-window cross-check. Present iff a window actually
    # captured (--profile-window, a /profilez hit, or an incident arm).
    profile_doc = profiler.last_summary()
    if profile_doc is profile_before:
        profile_doc = None  # nothing captured DURING this drive
    profile_section = None
    if profile_doc is not None:
        profile_section = {
            "capture": profile_doc,
            "crosscheck": profiler.crosscheck(
                profile_doc, server.cost_records,
                ceiling_gbps=args.ceiling_gbps),
        }
        print(f"# profile: tier={profile_doc['tier']} "
              f"window={profile_doc['seconds']:g}s "
              f"({profile_doc['armed_by']}), "
              f"{len(profile_doc['rungs'])} rung row(s), "
              f"device {profile_doc['device_us'] / 1e6:.3f}s of "
              f"{profile_doc['busy_us'] / 1e6:.3f}s busy in-window")
        for row in profile_section["crosscheck"]["rows"]:
            if row["window_gbps"] is None:
                continue
            util = (f" util={row['utilization']:.1%}"
                    if row["utilization"] is not None else "")
            print(f"# profile: {row['engine']}/{row['mode']} "
                  f"r{row['rung']}: {row['dispatches']} disp in-window "
                  f"-> {row['window_gbps']:.3f} GB/s moved{util}")

    # The per-workload split (mode rides serve_requests/serve_refused/
    # serve_batch_blocks/serve_dispatch_us): the mixed-mode drive's
    # evidence that every enabled mode actually carried traffic.
    per_mode = {
        "requests": metrics.counter_by_label("serve_requests", "mode"),
        "auth_failed": metrics.counter_by_label("serve_auth_failed",
                                                "mode"),
    }
    if len(args.mode_list) > 1 or args.mode_list != ("ctr",):
        print("# modes: " + "  ".join(
            f"{m}:{int(n)}" for m, n in per_mode["requests"].items())
            + ("" if not per_mode["auth_failed"] else
               "  auth_failed: " + "  ".join(
                   f"{m}:{int(n)}"
                   for m, n in per_mode["auth_failed"].items())))

    # The stateful-session plane (serve/session.py): client-side script
    # outcomes (report.sessions) next to the store's own view — opens,
    # evictions, the keystream prefetch hit rate, and carry replays
    # (the failover drive's ">= 1 replay" evidence lands here).
    sess_stats = stats.get("sessions")
    if args.sessions and sess_stats is not None:
        pf = sess_stats["prefetch"]
        hr = pf["hit_rate"]
        print(f"# sessions: opened={sess_stats['opened']} "
              f"closed={sess_stats['closed']} "
              f"chunks={sess_stats['chunks']} "
              f"evicted={sess_stats['evicted']} "
              f"shed={sess_stats['shed']} "
              f"prefetch: dispatches={pf['dispatches']} "
              f"hit_rate={'n/a' if hr is None else f'{hr:.4f}'} "
              f"stalls={pf['stalls']} replays={pf['replays']}")

    # The live analytics verdict (obs/pulse.py): one final tick over
    # the end-of-run registry, then the alert ledger + the measured
    # per-worker capacity estimate. A healthy drive commits zero
    # alerts — obs.history gates the count at zero forever after.
    pulse_section = None
    capacity_section = None
    if server.pulse is not None:
        server.pulse.tick()
        adoc = server.pulse.engine.alerts_doc()
        pulse_section = {"total": adoc["total"], "fired": adoc["fired"],
                         "rows": adoc["alerts"], "frames": adoc["frames"]}
        capacity_section = server.pulse.engine.capacity()
        fired_s = (" ".join(f"{r}:{n}"
                            for r, n in adoc["fired"].items())
                   or "none")
        print(f"# pulse: {adoc['total']} alert(s) over "
              f"{adoc['frames']} frame(s) ({fired_s})")
        for row in capacity_section["rows"]:
            print(f"# capacity: {row['engine']}/{row['mode']}: "
                  f"{row['ewma_blocks_per_s']:.1f} blocks/s baseline "
                  f"({row['blocks_per_s']:.1f} last window)")

    artifact = {
        "config": {
            "requests": args.requests, "concurrency": args.concurrency,
            "sizes": list(args.sizes), "tenants": args.tenants,
            "keys_per_tenant": args.keys_per_tenant,
            "engine": stats["engine"], "rungs": stats["rungs"],
            "key_slots": args.key_slots,
            "tenant_heavy": bool(args.tenant_heavy),
            "retries": args.retries,
            "dispatch_deadline_s": args.dispatch_deadline,
            "lanes": lanes["count"], "probe_every": args.probe_every,
            "max_inflight": args.max_inflight,
            "arrival_rate": args.arrival_rate,
            "modes": list(args.mode_list),
            "seed": args.seed,
            **({"sessions": args.sessions,
                "session_chunks": args.session_chunks,
                "session_chunk_bytes": list(args.session_chunk_bytes),
                "session_quantum_bytes": args.session_quantum_bytes,
                "session_prefetch_slots": args.session_prefetch_slots}
               if args.sessions else {}),
        },
        "modes": per_mode,
        "load": report.to_json(),
        "overlap": overlap,
        "coalesce": coal,
        "batches": {k: stats[k] for k in
                    ("batches", "batches_failed", "batches_timed_out")},
        "lanes": lanes,
        "occupancy": stats["occupancy"],
        "queue": stats["queue"],
        "keycache": stats["keycache"],
        "compiles": stats["compiles"],
        # The session store's view (serve/session.py.stats(); the
        # client-side script outcomes ride load.sessions). None when
        # rc4 is not an enabled mode.
        "sessions": stats.get("sessions"),
        # The time-attribution stages (serve_stage_us{stage=...}, exact
        # at any sample rate) and the device-time split — the
        # saturation-run decomposition surface (docs/OBSERVABILITY.md).
        "stages": stages,
        "device": device,
        # The roofline attribution: modeled HBM traffic per dispatch,
        # achieved GB/s moved from device time, utilization vs the
        # measured ceiling — per engine x mode x rung (obs/costmodel.py;
        # obs/slo.py gates the rows' achieved_gbps per engine x rung).
        "cost": cost,
        "compiles_by_rung": compile_by_rung,
        "degraded": degrade.events(),
        # The live pulse verdict: alert totals (zero on a healthy
        # drive — the count series obs.history tolerates no growth on)
        # and the measured per-worker capacity model (obs/pulse.py).
        "alerts": pulse_section,
        "capacity": capacity_section,
        # The armed profile window's summary + costmodel cross-check
        # (None when no window captured this run).
        "profile": profile_section,
        # The full registry snapshot: exact counters/gauges + log2
        # histogram buckets per label set — present traced or not (the
        # registry always counts; only the JSONL flusher needs
        # OT_TRACE_DIR), which is what lets the A/B overhead harness
        # prove counter totals byte-identical across sample rates.
        "metrics": metrics.snapshot(),
    }
    if trace.enabled():
        artifact["obs"] = trace.metrics_snapshot()
        artifact["trace_sample"] = trace.sample_rate()
    path = args.artifact or _next_artifact(_repo_root())
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(f"# artifact: {path}", file=sys.stderr)

    # The SLO regression gate (obs/slo.py) runs BEFORE the JSON line so
    # the one-parseable-line-last stdout contract holds: this run vs the
    # committed baseline artifact. Count metrics (errors, lost,
    # recompiles, mismatches) tolerate nothing; latency/goodput compare
    # within per-metric tolerances (--slo-tolerance for cross-host CI
    # bands). A regression fails the bench like a correctness violation
    # does — SERVE_r* numbers can no longer silently rot.
    slo_rc = 0
    if args.slo:
        try:
            slo_rc = slo.gate(args.slo, artifact, args.slo_tolerance)
        except (OSError, ValueError, KeyError) as e:
            print(f"# slo: gate unusable: {e}", file=sys.stderr)
            slo_rc = 1
        if slo_rc:
            # An SLO breach is an incident: dump the flight-recorder
            # bundle (ring + metrics + cost records) beside the trace
            # so the regression's dispatch history survives triage.
            incident.trigger("slo-breach",
                             baseline=os.path.basename(args.slo))

    line = {"unit": "serve", "engine": stats["engine"],
            "requests": report.requests, "ok": report.ok,
            "errors": dict(sorted(report.errors.items())),
            "lost": lost,
            "p50_ms": report.p50_ms, "p95_ms": report.p95_ms,
            "p99_ms": report.p99_ms,
            "goodput_gbps": round(report.goodput_gbps, 4),
            "coalesce_efficiency": coal["efficiency"],
            "max_inflight": overlap["max_inflight"],
            "inflight_limit": overlap["inflight_limit"],
            "batches": stats["batches"],
            "lanes": lanes["count"],
            "lanes_used": lanes["placed_across"],
            "redispatches": lanes["redispatches"],
            "quarantines": lanes["quarantine_events"],
            "recompiles": stats["compiles"]["steady"],
            "mismatches": report.mismatches}
    if args.mode_list != ("ctr",):
        line["modes"] = {m: int(n)
                         for m, n in per_mode["requests"].items()}
    if args.sessions and sess_stats is not None:
        pf = sess_stats["prefetch"]
        line["sessions"] = {
            "opened": sess_stats["opened"],
            "closed": sess_stats["closed"],
            "chunks": sess_stats["chunks"],
            "evicted": sess_stats["evicted"],
            "shed": sess_stats["shed"],
            "hit_rate": pf["hit_rate"],
            "stalls": pf["stalls"],
            "replays": pf["replays"],
            **{k: int(v) for k, v in report.sessions.items()
               if k in ("open_failed", "chunk_failed", "mismatches")
               and v},
        }
    if pulse_section is not None and pulse_section["total"]:
        line["alerts"] = pulse_section["fired"]
    if args.slo:
        line["slo"] = "fail" if slo_rc else "pass"
    if degrade.events():
        line["degraded"] = degrade.events()
    if trace.enabled():
        line["obs"] = trace.metrics_snapshot()
    print(json.dumps(line))

    rc = 0
    if report.mismatches:
        print(f"# FAIL: {report.mismatches} probe response(s) mismatched "
              "the byte-exact reference", file=sys.stderr)
        rc = 1
    if lost:
        print(f"# FAIL: {lost} request(s) LOST — accepted but answered "
              "neither payload nor error (the drain/failover contract "
              "is broken)", file=sys.stderr)
        rc = 1
    if stats["compiles"]["steady"] and not args.allow_recompiles:
        print(f"# FAIL: {stats['compiles']['steady']} post-warmup backend "
              "compile(s) — the bucket ladder's zero-recompile contract "
              "is broken (--allow-recompiles to waive)", file=sys.stderr)
        rc = 1
    if (args.min_coalesce is not None
            and coal["efficiency"] < args.min_coalesce):
        print(f"# FAIL: coalesce_efficiency {coal['efficiency']:.4f} < "
              f"{args.min_coalesce} — the rung-packer is fragmenting "
              "(key groups not sharing batches, or padding dominating)",
              file=sys.stderr)
        rc = 1
    if (args.min_inflight is not None
            and overlap["max_inflight"] < args.min_inflight):
        print(f"# FAIL: max in-flight concurrency "
              f"{overlap['max_inflight']} < {args.min_inflight} — "
              "dispatches never overlapped: a multi-lane run serialized "
              "behind one dispatch at a time (the pre-overlap behaviour "
              "the lane executors exist to end)", file=sys.stderr)
        rc = 1
    if slo_rc:
        print(f"# FAIL: SLO regression against {args.slo} "
              "(see the # slo table above)", file=sys.stderr)
        rc = 1
    if args.min_session_hit_rate is not None:
        hr = (sess_stats or {}).get("prefetch", {}).get("hit_rate")
        if hr is None or hr < args.min_session_hit_rate:
            print(f"# FAIL: keystream prefetch hit rate "
                  f"{'n/a' if hr is None else f'{hr:.4f}'} < "
                  f"{args.min_session_hit_rate} — chunks stalled on "
                  "demand refills (the prefetcher stopped running "
                  "ahead of consumption)", file=sys.stderr)
            rc = 1
    if args.min_session_replays is not None:
        rp = (sess_stats or {}).get("prefetch", {}).get("replays", 0)
        if rp < args.min_session_replays:
            print(f"# FAIL: {rp} keystream carry replay(s) < "
                  f"{args.min_session_replays} — the failover drive "
                  "never exercised the bit-exact replay path "
                  "(serve/session.py carry checkpoints)",
                  file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
