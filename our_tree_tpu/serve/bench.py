"""``python -m our_tree_tpu.serve.bench`` — the serving benchmark.

Closed-loop loadgen against an in-process Server: mixed request sizes,
multi-tenant keys, p50/p95/p99 latency, goodput GB/s, batch-occupancy
histogram — and the zero-recompile CONTRACT: after the ladder warmup,
steady-state serving must trigger no backend compile at all (the
``server.compile_count`` monitor; the run exits 1 if it does, unless
``--allow-recompiles`` says a recompile is expected, e.g. an exotic key
size outside the warmed set).

Output convention follows the repo-root bench: human-readable ``#``
lines, then ONE parseable JSON line last on stdout (the CI contract),
plus a ``SERVE_r*.json`` artifact alongside the driver's
``BENCH_r*.json`` (``--artifact`` overrides the path; otherwise the
next free index at the repo root).

Fault rehearsals (docs/SERVING.md, the CI ``serve`` job):

* ``OT_FAULTS=dispatch_fail:1 ... --retries 1`` — the armed batch dies,
  its requests get ``dispatch-failed`` responses, the run completes rc 0
  (server-stays-up IS the contract; the artifact records the errors).
* ``OT_FAULTS=dispatch_hang:1 ... --dispatch-deadline 3`` — the armed
  batch wedges; the watchdog kills it at the deadline, its requests get
  ``deadline`` errors, the abandoned ``batch-dispatched`` span is the
  run's ONLY orphan (``obs.report --check --expected-orphans
  batch-dispatched``).
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import re
import sys

from ..obs import trace
from ..resilience import degrade, watchdog
from . import loadgen
from .server import Server, ServerConfig


def _repo_root() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _next_artifact(root: str) -> str:
    """The next free ``SERVE_r<NN>.json`` at the repo root."""
    taken = [0]
    for p in glob.glob(os.path.join(root, "SERVE_r*.json")):
        m = re.match(r"SERVE_r(\d+)\.json$", os.path.basename(p))
        if m:
            taken.append(int(m.group(1)))
    return os.path.join(root, f"SERVE_r{max(taken) + 1:02d}.json")


async def _drive(args, probes):
    cfg = ServerConfig(
        engine=args.engine,
        min_bucket_blocks=args.bucket_min,
        max_bucket_blocks=args.bucket_max,
        max_depth=args.queue_depth,
        request_deadline_s=args.deadline,
        dispatch_deadline_s=args.dispatch_deadline,
        retries=args.retries)
    server = Server(cfg)
    await server.start()
    report = await loadgen.run(
        server, args.requests, concurrency=args.concurrency,
        sizes=args.sizes, tenants=args.tenants,
        keys_per_tenant=args.keys_per_tenant, seed=args.seed,
        verify_every=args.verify_every, probes=probes)
    await server.stop()
    return server, report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m our_tree_tpu.serve.bench",
        description="closed-loop serving benchmark (docs/SERVING.md)")
    ap.add_argument("--requests", type=int, default=500)
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--mixed-sizes", action="store_true",
                    help=f"request sizes drawn from {loadgen.MIXED_SIZES} "
                         "(the ladder-exercising menu)")
    ap.add_argument("--size-bytes", type=int, default=4096,
                    help="fixed request size when --mixed-sizes is off")
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--keys-per-tenant", type=int, default=2)
    ap.add_argument("--engine", default="auto")
    ap.add_argument("--bucket-min", type=int, default=32, metavar="BLOCKS")
    ap.add_argument("--bucket-max", type=int, default=4096, metavar="BLOCKS")
    ap.add_argument("--queue-depth", type=int, default=1024)
    ap.add_argument("--deadline", type=float, default=30.0,
                    help="per-request residency deadline, seconds")
    ap.add_argument("--dispatch-deadline", type=float,
                    default=watchdog.default_deadline_s() or 10.0,
                    help="watchdog deadline per engine call, seconds "
                         "(default: OT_DISPATCH_DEADLINE, else 10)")
    ap.add_argument("--retries", type=int, default=2,
                    help="dispatch attempts per batch (1 = no retry)")
    ap.add_argument("--verify-every", type=int, default=8,
                    help="every Nth request replays a pinned probe and "
                         "checks bit-exactness (0 = off)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--artifact", default=None, metavar="PATH",
                    help="artifact path (default: next SERVE_r*.json at "
                         "the repo root)")
    ap.add_argument("--allow-recompiles", action="store_true",
                    help="do not fail on post-warmup backend compiles")
    args = ap.parse_args(argv)
    args.sizes = (loadgen.MIXED_SIZES if args.mixed_sizes
                  else (args.size_bytes,))

    trace.ensure_run()
    # Reference ciphertexts BEFORE the server's warmup marker: the
    # byte-exact models path compiles per probe size, and those compiles
    # belong to the harness, not to steady-state serving.
    probes = (loadgen.make_probes(args.sizes, args.seed)
              if args.verify_every else [])
    server, report = asyncio.run(_drive(args, probes))
    stats = server.stats()

    print(f"# serve: engine={stats['engine']} ladder={stats['rungs']} "
          f"concurrency={args.concurrency} tenants={args.tenants}")
    print(f"# requests={report.requests} ok={report.ok} "
          f"errors={report.errors or '{}'} verified={report.verified} "
          f"mismatches={report.mismatches}")
    print(f"# latency ms: p50={report.p50_ms} p95={report.p95_ms} "
          f"p99={report.p99_ms}  goodput={report.goodput_gbps:.4f} GB/s "
          f"wall={report.wall_s:.3f}s")
    print(f"# batches={stats['batches']} "
          f"failed={stats['batches_failed']} "
          f"timed_out={stats['batches_timed_out']} "
          f"compiles: warmup={stats['compiles']['warmup']} "
          f"steady={stats['compiles']['steady']}")
    for bucket, h in stats["occupancy"].items():
        print(f"#   bucket {bucket:>5}: {h['batches']} batch(es), "
              f"mean occupancy {h['mean_occupancy']:.2%}")

    artifact = {
        "config": {
            "requests": args.requests, "concurrency": args.concurrency,
            "sizes": list(args.sizes), "tenants": args.tenants,
            "keys_per_tenant": args.keys_per_tenant,
            "engine": stats["engine"], "rungs": stats["rungs"],
            "retries": args.retries,
            "dispatch_deadline_s": args.dispatch_deadline,
            "seed": args.seed,
        },
        "load": report.to_json(),
        "batches": {k: stats[k] for k in
                    ("batches", "batches_failed", "batches_timed_out")},
        "occupancy": stats["occupancy"],
        "queue": stats["queue"],
        "keycache": stats["keycache"],
        "compiles": stats["compiles"],
        "degraded": degrade.events(),
    }
    if trace.enabled():
        artifact["obs"] = trace.metrics_snapshot()
    path = args.artifact or _next_artifact(_repo_root())
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(f"# artifact: {path}", file=sys.stderr)

    line = {"unit": "serve", "engine": stats["engine"],
            "requests": report.requests, "ok": report.ok,
            "errors": dict(sorted(report.errors.items())),
            "p50_ms": report.p50_ms, "p95_ms": report.p95_ms,
            "p99_ms": report.p99_ms,
            "goodput_gbps": round(report.goodput_gbps, 4),
            "batches": stats["batches"],
            "recompiles": stats["compiles"]["steady"],
            "mismatches": report.mismatches}
    if degrade.events():
        line["degraded"] = degrade.events()
    if trace.enabled():
        line["obs"] = trace.metrics_snapshot()
    print(json.dumps(line))

    rc = 0
    if report.mismatches:
        print(f"# FAIL: {report.mismatches} probe response(s) mismatched "
              "the byte-exact reference", file=sys.stderr)
        rc = 1
    if stats["compiles"]["steady"] and not args.allow_recompiles:
        print(f"# FAIL: {stats['compiles']['steady']} post-warmup backend "
              "compile(s) — the bucket ladder's zero-recompile contract "
              "is broken (--allow-recompiles to waive)", file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
