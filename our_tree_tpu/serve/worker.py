"""``python -m our_tree_tpu.serve.worker`` — one ot-serve BACKEND process.

The router's unit of horizontal scale (docs/SERVING.md): a whole
``serve.Server`` — lanes, batcher, keycache, status endpoint — wrapped
in a TCP request frontend speaking the framed wire protocol
(``serve/wire.py``), so N of these processes behind ``route/proxy.py``
are N independent per-HOST fault domains, exactly as N lanes inside one
process are N per-DEVICE fault domains. The worker adds no policy of
its own: admission, batching, dispatch, health, and drain are all the
Server's; this module only moves frames.

Lifecycle contract (what ``route/bench.py``'s spawner and the
``resilience.isolate.spawn_service`` handle rely on):

* **READY line.** After warmup, ONE JSON line on stdout::

      {"kind": "ot-serve-worker", "port": P, "status_port": S,
       "engine": "...", "lanes": N, "pid": ...}

  with the BOUND ports (``--port 0`` / ``--status-port 0`` bind
  ephemerally — how a multi-worker host avoids port coordination).
* **Graceful drain on SIGTERM/SIGINT.** The request listener closes
  (new connections refused), in-flight connections finish their framed
  exchanges — a submit after admission closed answers ``shutdown``,
  never silence — then ``Server.stop()`` drains every accepted request.
  While draining, ``/healthz`` answers ``status: "draining"`` (the
  queue closes first), so a router's gossip sees the backend leave
  placement BEFORE it disappears.
* **EXIT line + rc.** One final JSON line
  (``{"kind": "ot-serve-worker-exit", "lost": L, ...}``) and exit 0
  iff ``lost == 0`` — the same zero-lost drain gate serve.bench
  enforces, so a router drive can assert no backend silently dropped
  work.

Per-connection containment: a wire protocol violation closes THAT
connection (the peer is not trustworthy past a torn frame); a handler
error answers a coded error frame when it still can. Neither can take
the dispatch loop down.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

import numpy as np

from ..obs import trace
from ..resilience import watchdog
from ..resilience.policy import Budget
from . import batcher, transfer, wire
from .queue import (ERR_BAD_REQUEST, ERR_DEADLINE, ERR_TOO_LARGE,
                    ERR_TRANSFER_MODE)
from .server import Server, ServerConfig


class RequestFrontend:
    """The TCP listener that feeds ``Server.submit`` from wire frames.

    Importable for in-process tests (tests/test_route.py runs several
    Servers + frontends inside one event loop); the module ``main`` is
    the process entry the router's spawner uses."""

    def __init__(self, server: Server, port: int, host: str = "127.0.0.1"):
        self._server = server
        self._host = host
        self._port = int(port)
        self._srv: asyncio.AbstractServer | None = None
        self._conns: set[asyncio.Task] = set()
        self.port: int | None = None
        self.connections = 0
        self.frames = 0
        self.protocol_errors = 0

    async def start(self) -> None:
        max_blocks = self._server.rungs[-1]
        self._max_len = max(max_blocks * 16, wire.MAX_PAYLOAD)
        self._srv = await asyncio.start_server(
            self._on_conn, self._host, self._port)
        self.port = self._srv.sockets[0].getsockname()[1]

    async def stop(self, grace_s: float = 5.0) -> None:
        """Close the listener, let in-flight connections finish their
        current exchanges (their submits resolve via the server's still-
        running batcher loop — a shutdown answer is still an answer),
        then CANCEL connections still open past ``grace_s``: an idle
        client parked between frames holds no in-flight request, and a
        drain that waits on it forever would end in the spawner's group
        SIGKILL and a false failed-drain verdict."""
        if self._srv is not None:
            self._srv.close()
            await self._srv.wait_closed()
            self._srv = None
        if self._conns:
            _done, pending = await asyncio.wait(
                list(self._conns), timeout=max(grace_s, 0.0))
            for task in pending:
                task.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)

    def _on_conn(self, reader, writer) -> None:
        self.connections += 1
        task = asyncio.ensure_future(self._serve_conn(reader, writer))
        self._conns.add(task)
        task.add_done_callback(self._conns.discard)

    async def _serve_conn(self, reader, writer) -> None:
        """Frames on one connection, sequentially: the wire protocol is
        strict request/response, so ordering is the framing (the router
        opens one exchange per in-flight request)."""
        try:
            while True:
                try:
                    frame = await wire.read_frame(reader, self._max_len)
                except wire.FrameTooLarge as e:
                    # The declared length was validated BEFORE any
                    # allocation (wire.read_frame) and the header parsed,
                    # so the stream is still framed: answer a TYPED
                    # error frame, and when the declared payload is
                    # modest enough to drain, keep the connection —
                    # one mis-sized request must not reset a peer's
                    # whole multiplexed session.
                    self.protocol_errors += 1
                    try:
                        writer.write(wire.encode_frame(
                            {"ok": False, "error": ERR_TOO_LARGE,
                             "detail": f"wire: {e}"}))
                        await writer.drain()
                    except Exception:  # noqa: BLE001 - peer already gone
                        return
                    if 0 <= e.declared <= 4 * self._max_len and \
                            await wire.skip_payload(reader, e.declared):
                        continue
                    return
                except wire.WireError as e:
                    self.protocol_errors += 1
                    try:
                        writer.write(wire.encode_frame(
                            {"ok": False, "error": ERR_BAD_REQUEST,
                             "detail": f"wire: {e}"}))
                        await writer.drain()
                    except Exception:  # noqa: BLE001 - peer already gone
                        pass
                    return
                if frame is None:
                    return  # clean EOF between frames
                header, payload = frame
                self.frames += 1
                if header.get("tx"):
                    await self._serve_transfer(reader, writer, header)
                    continue
                if header.get("ss"):
                    await self._serve_session(writer, header, payload)
                    continue
                await self._answer(writer, header, payload)
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - peer already gone
                pass

    async def _answer(self, writer, header: dict, payload: bytes) -> None:
        t_rx = trace.now_us()  # frame receipt (the "tr" stamp)
        try:
            key = bytes.fromhex(str(header.get("k", "")))
            nonce = bytes.fromhex(str(header.get("n", "")))
            # The AEAD fields (serve/wire.py): absent = empty, and a
            # malformed hex field degrades to b"" — admission's
            # per-mode validation answers the coded error.
            iv = bytes.fromhex(str(header.get("iv", "")))
            aad = bytes.fromhex(str(header.get("a", "")))
            tag = bytes.fromhex(str(header.get("tg", "")))
        except ValueError:
            key, nonce = b"", b""
            iv = aad = tag = b""
        mode = str(header.get("m") or "ctr")
        try:
            deadline = header.get("deadline_s")
            deadline = float(deadline) if deadline is not None else None
        except (TypeError, ValueError):
            # A malformed deadline answers a coded error like every
            # other malformed field — the containment contract says a
            # bad peer gets a frame, never a dropped connection.
            writer.write(wire.encode_frame(
                {"ok": False, "error": ERR_BAD_REQUEST,
                 "detail": "deadline_s is not a number"}))
            await writer.drain()
            return
        # Cross-process observability propagation (serve/wire.py): the
        # router's admission-time sampling decision ("sm") and span id
        # ("ps") replace a local coin flip, so this request's backend
        # spans join the router's trace; "pr" rides the priority tier.
        sampled = header.get("sm")
        sampled = bool(sampled) if sampled is not None else None
        parent = header.get("ps")
        parent = str(parent) if parent else None
        priority = 0 if header.get("pr") == 0 else None
        resp = await self._server.submit(
            str(header.get("t", "")), key, nonce,
            memoryview(payload), deadline_s=deadline,
            sampled=sampled, parent=parent, priority=priority,
            mode=mode, iv=iv, aad=aad, tag=tag)
        if resp.ok:
            out = {"ok": True, "batch": resp.batch}
            if resp.tag is not None:  # gcm seal: the tag rides back
                out["tg"] = resp.tag.hex()
            body = resp.payload.tobytes()
        else:
            out = {"ok": False, "error": resp.error, "detail": resp.detail,
                   "batch": resp.batch}
            body = b""
        # The reply-side handshake: backend receive + reply clocks and
        # pid on every frame. TWO timestamps on purpose — the NTP
        # four-timestamp form ((tr - send) + (ts - recv)) / 2 cancels
        # the server's processing time out of the router's clock-skew
        # estimate, where a single reply stamp would bias it by half
        # the service time. Plus the per-request ledger when asked for.
        out["tr"] = t_rx
        out["ts"] = trace.now_us()
        out["pid"] = os.getpid()
        if header.get("lg") and resp.ledger is not None:
            out["lg"] = resp.ledger
        writer.write(wire.encode_frame(out, body))
        await writer.drain()

    async def _serve_session(self, writer, header: dict,
                             payload: bytes) -> None:
        """The ``ss`` stateful-session sub-protocol (mode ``rc4``,
        serve/session.py). UNLIKE ``tx``, every ``ss`` frame is its own
        one-frame exchange, so a connection interleaves many sessions'
        frames (and ordinary requests) freely — which is the point: the
        batcher coalesces CONCURRENT sessions' data chunks into shared
        XOR dispatches.

        * ``{"ss": "open", t, sid, k}`` — host KSA + full-window
          keystream prefill; answers ``ok`` or a typed shed/refusal.
        * ``{"ss": "data", t, sid, len}`` + payload — XOR the chunk
          against the session's next ``len`` cached keystream bytes;
          the ciphertext rides back on the answer frame. Chunks are
          STATEFUL: each consumes the stream where the last left off,
          so a failed chunk's session should be closed and reopened
          (the stream position does not rewind).
        * ``{"ss": "close", t, sid}`` — release the session.
        """
        t_rx = trace.now_us()
        op = str(header.get("ss") or "")
        tenant = str(header.get("t", ""))
        try:
            sid = int(header.get("sid"))
        except (TypeError, ValueError):
            writer.write(wire.encode_frame(
                {"ss": op, "ok": False, "error": ERR_BAD_REQUEST,
                 "detail": "ss frames need an integer sid"}))
            await writer.drain()
            return
        sampled = header.get("sm")
        sampled = bool(sampled) if sampled is not None else None
        parent = header.get("ps")
        parent = str(parent) if parent else None
        body = b""
        if op == "open":
            try:
                key = bytes.fromhex(str(header.get("k", "")))
            except ValueError:
                key = b""
            resp = await self._server.open_session(tenant, sid, key)
        elif op == "data":
            try:
                deadline = header.get("deadline_s")
                deadline = (float(deadline) if deadline is not None
                            else None)
            except (TypeError, ValueError):
                writer.write(wire.encode_frame(
                    {"ss": op, "ok": False, "error": ERR_BAD_REQUEST,
                     "detail": "deadline_s is not a number"}))
                await writer.drain()
                return
            resp = await self._server.submit(
                tenant, b"", b"", memoryview(payload),
                deadline_s=deadline, sampled=sampled, parent=parent,
                mode="rc4", sid=sid)
            if resp.ok:
                body = resp.payload.tobytes()
        elif op == "close":
            resp = await self._server.close_session(tenant, sid)
        else:
            writer.write(wire.encode_frame(
                {"ss": op, "ok": False, "error": ERR_BAD_REQUEST,
                 "detail": f"unknown ss op {op!r} "
                           f"(known: open, data, close)"}))
            await writer.drain()
            return
        out = {"ss": op, "ok": resp.ok, "sid": sid,
               "tr": t_rx, "ts": trace.now_us(), "pid": os.getpid()}
        if resp.ok:
            if resp.batch:
                out["batch"] = resp.batch
            if resp.detail:
                out["detail"] = resp.detail
        else:
            out["error"] = resp.error
            out["detail"] = resp.detail
        if header.get("lg") and resp.ledger is not None:
            out["lg"] = resp.ledger
        writer.write(wire.encode_frame(out, body))
        await writer.drain()

    async def _serve_transfer(self, reader, writer, header: dict) -> None:
        """The ``tx`` resumable-transfer sub-protocol, one exchange:

        1. client: ``{"tx": "begin", "tid"?, t, k, n|iv, m, total}``
        2. worker: ``{"tx": "begin-ack", tid, chunks, chunk_blocks,
           acked: [...]}`` — the acked bitmap from the transfer ledger
           is the RESUME contract (a fresh tid acks nothing).
        3. client: one ``{"tx": "chunk", "i", "len"}`` + payload frame
           per UNACKED chunk, any order.
        4. worker: in-order ``{"tx": "out", "i", "len"}`` + payload
           frames as the contiguous prefix completes (each one follows
           a durable ledger ack), then a final ``{"tx": "done", ...}``
           verdict with the transfer tallies.

        A mid-exchange failure — worker SIGKILL, cut connection,
        injected ``transfer_abort`` — leaves the fsync'd acks behind:
        the client reconnects, re-presents its token at step 1, and
        steps 3-4 cover only what never acked. The spliced client-side
        output is byte-identical to an uninterrupted run."""
        async def refuse(code: str, why: str) -> None:
            writer.write(wire.encode_frame(
                {"tx": "done", "ok": False, "error": code, "detail": why}))
            await writer.drain()

        if header.get("tx") != "begin":
            await refuse(ERR_BAD_REQUEST, (
                f"tx exchange must open with begin, got "
                f"{header.get('tx')!r}"))
            return
        tm = self._server.transfers
        if tm is None:
            await refuse(ERR_TOO_LARGE, "transfers disabled on this server")
            return
        try:
            key = bytes.fromhex(str(header.get("k", "")))
            nonce = bytes.fromhex(str(header.get("n", "")))
            iv = bytes.fromhex(str(header.get("iv", "")))
        except ValueError:
            key, nonce, iv = b"", b"", b""
        mode = str(header.get("m") or "ctr")
        try:
            total = int(header.get("total", 0))
            deadline = header.get("deadline_s")
            deadline = float(deadline) if deadline is not None else None
        except (TypeError, ValueError):
            await refuse(ERR_BAD_REQUEST, "total/deadline_s malformed")
            return
        # Refuse unservable exchanges at BEGIN — before the client
        # uploads a single chunk it would only have wasted.
        if mode not in transfer.TRANSFER_MODES:
            await refuse(ERR_TRANSFER_MODE, (
                f"mode {mode!r} is not chunkable "
                f"(transfer modes: {transfer.TRANSFER_MODES})"))
            return
        if total <= 0 or total % 16:
            await refuse(ERR_BAD_REQUEST,
                         "total must be a nonzero multiple of 16 bytes")
            return
        if total > tm.max_payload_bytes:
            # The declared total is CLIENT data: bound it before the
            # sparse buffer (np.zeros(total)) or the needed set exist —
            # a begin frame alone must not be able to size an
            # allocation (the same validate-before-allocate contract
            # wire.read_frame enforces for frame payloads).
            await refuse(ERR_TOO_LARGE, (
                f"total {total} bytes exceeds this server's transfer "
                f"cap ({tm.max_payload_bytes} bytes)"))
            return
        step = tm.chunk_blocks * 16
        chunks = (total + step - 1) // step
        tid = str(header.get("tid") or "") or os.urandom(16).hex()
        fp = transfer.fingerprint(mode, key, nonce, iv, total,
                                  tm.chunk_blocks)
        acked = tm.ledger.begin(tid, fp, chunks)
        writer.write(wire.encode_frame(
            {"tx": "begin-ack", "tid": tid, "chunks": chunks,
             "chunk_blocks": tm.chunk_blocks, "acked": sorted(acked)}))
        await writer.drain()

        # Exactly the unacked chunks land in a sparse buffer; acked
        # regions stay zero and are never read (the engine SKIPS them —
        # cbc IVs for their successors come from the ledger's tails).
        buf = np.zeros(total, dtype=np.uint8)
        needed = set(range(chunks)) - set(acked)
        # The upload loop runs under the SAME wall deadline the compute
        # side will: a client that sends begin and then stalls must not
        # pin this connection, the sparse buffer, and a live ledger
        # entry forever (the acks survive the refusal — a later resume
        # picks up where the stall left off).
        upload = Budget(deadline if deadline is not None
                        else tm.deadline_s)
        while needed:
            try:
                left = upload.remaining()
                frame = await asyncio.wait_for(
                    wire.read_frame(reader, self._max_len),
                    timeout=(None if left == float("inf")
                             else max(left, 0.001)))
            except asyncio.TimeoutError:
                await refuse(ERR_DEADLINE, (
                    f"upload stalled: {len(needed)} chunks still "
                    f"unsent after {upload.spent():.3f}s"))
                return
            except wire.WireError as e:
                self.protocol_errors += 1
                await refuse(ERR_BAD_REQUEST, f"wire: {e}")
                return
            if frame is None:
                return  # client gone mid-upload; the acks persist
            h, body = frame
            self.frames += 1
            if h.get("tx") != "chunk":
                await refuse(ERR_BAD_REQUEST, (
                    f"expected a chunk frame, got {h.get('tx')!r}"))
                return
            try:
                i = int(h.get("i"))
            except (TypeError, ValueError):
                await refuse(ERR_BAD_REQUEST, "chunk index malformed")
                return
            want = min(step, total - i * step) if 0 <= i < chunks else -1
            if want != len(body):
                await refuse(ERR_BAD_REQUEST, (
                    f"chunk {i}: {len(body)} bytes, expected {want}"))
                return
            buf[i * step:i * step + want] = np.frombuffer(body, np.uint8)
            needed.discard(i)

        sampled = header.get("sm")
        sampled = bool(sampled) if sampled is not None else None
        parent = header.get("ps")
        parent = str(parent) if parent else None

        async def on_chunk(spec, resp) -> None:
            body = np.asarray(resp.payload, dtype=np.uint8).tobytes()
            writer.write(wire.encode_frame(
                {"tx": "out", "i": spec.index}, body))
            await writer.drain()

        resp = await self._server.submit_transfer(
            str(header.get("t", "")), key, nonce, buf,
            deadline_s=deadline, sampled=sampled, parent=parent,
            mode=mode, iv=iv, resume_token=tid,
            tails=tm.ledger.tails(tid), on_chunk=on_chunk)
        out = {"tx": "done", "ok": resp.ok, "tid": tid,
               "transfer": resp.transfer,
               "ts": trace.now_us(), "pid": os.getpid()}
        if not resp.ok:
            out["error"] = resp.error
            out["detail"] = resp.detail
        writer.write(wire.encode_frame(out))
        await writer.drain()


async def _amain(args) -> int:
    cfg = ServerConfig(
        engine=args.engine,
        min_bucket_blocks=args.bucket_min,
        max_bucket_blocks=args.bucket_max,
        key_slots=args.key_slots,
        native_threads=args.native_threads,
        max_depth=args.queue_depth,
        tenant_depth_frac=args.tenant_depth_frac,
        low_priority_tenants=tuple(args.low_priority_tenant or ()),
        priority_depth_frac=args.priority_depth_frac,
        request_deadline_s=args.deadline,
        dispatch_deadline_s=args.dispatch_deadline,
        retries=args.retries,
        lanes=args.lanes,
        probe_every=args.probe_every,
        journal=args.journal,
        max_inflight=args.max_inflight,
        status_port=args.status_port,
        modes=tuple((args.modes or "ctr").split(",")),
        ceiling_gbps=args.ceiling_gbps,
        transfer_chunk_blocks=args.transfer_chunk_blocks,
        max_transfers=args.max_transfers,
        transfer_window=args.transfer_window,
        transfer_budget_bytes=args.transfer_budget_bytes,
        transfer_max_bytes=args.transfer_max_bytes,
        transfer_deadline_s=args.transfer_deadline,
        transfer_ledger=args.transfer_ledger,
        session_per_tenant=args.session_per_tenant,
        session_window_bytes=args.session_window_bytes,
        session_quantum_bytes=args.session_quantum_bytes,
        session_prefetch_slots=args.session_prefetch_slots,
        session_budget_bytes=args.session_budget_bytes)
    server = Server(cfg)
    await server.start()
    frontend = RequestFrontend(server, args.port, host=args.host)
    await frontend.start()
    ready = {"kind": "ot-serve-worker", "port": frontend.port,
             "status_port": (server.status.port
                             if server.status is not None else None),
             "engine": server.engine, "lanes": len(server.pool.lanes),
             "pid": os.getpid()}
    print(json.dumps(ready), flush=True)
    trace.point("worker-ready", port=frontend.port, engine=server.engine)

    stop_ev = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop_ev.set)
    await stop_ev.wait()

    # Drain order: listener first (no new connections), then admission +
    # dispatch (server.stop closes the queue BEFORE clearing the run
    # flag, so /healthz says "draining" for the whole window and any
    # still-open connection's submit answers `shutdown` immediately).
    server.queue.close()
    # Grace below the spawner's 60 s SIGTERM->SIGKILL window: in-flight
    # exchanges get ample time to answer, an idle held-open connection
    # cannot convert the drain into a group SIGKILL.
    await frontend.stop(grace_s=30.0)
    await server.stop()
    stats = server.stats()
    lost = stats["queue"]["lost"]
    line = {"kind": "ot-serve-worker-exit", "lost": lost,
            "answered": stats["queue"]["answered"],
            "accepted": stats["queue"]["accepted"],
            "batches": stats["batches"],
            "quarantines": stats["lanes"]["quarantine_events"],
            "recompiles": stats["compiles"]["steady"],
            "keycache": stats["keycache"],
            "frames": frontend.frames,
            "protocol_errors": frontend.protocol_errors,
            "transfers": stats["transfers"],
            "sessions": stats["sessions"]}
    print(json.dumps(line), flush=True)
    trace.point("worker-drained", lost=lost, frames=frontend.frames)
    return 1 if lost else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m our_tree_tpu.serve.worker",
        description="one ot-serve backend process behind the router "
                    "(docs/SERVING.md)")
    ap.add_argument("--port", type=int, default=0,
                    help="request port (0 = ephemeral; the bound port "
                         "rides the READY line)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (default loopback: the router and "
                         "its backends share a host or a private net)")
    ap.add_argument("--status-port", type=int, default=0, metavar="PORT",
                    help="/metrics + /healthz port (0 = ephemeral — the "
                         "router's gossip reads it from the READY line)")
    ap.add_argument("--engine", default="auto")
    ap.add_argument("--modes", default="ctr", metavar="M1,M2",
                    help="served modes to enable and warm (serve/queue.py "
                         "MODES: ctr,gcm,gcm-open,cbc,rc4; default ctr — "
                         "AEAD and stateful-session serving are explicit "
                         "opt-ins, docs/SERVING.md)")
    ap.add_argument("--lanes", type=int, default=None, metavar="N")
    ap.add_argument("--bucket-min", type=int, default=32, metavar="BLOCKS")
    ap.add_argument("--bucket-max", type=int, default=4096, metavar="BLOCKS")
    ap.add_argument("--key-slots", type=int, default=None, metavar="K")
    ap.add_argument("--native-threads", type=int, default=0)
    ap.add_argument("--queue-depth", type=int, default=1024)
    ap.add_argument("--tenant-depth-frac", type=float, default=1.0,
                    metavar="FRAC")
    ap.add_argument("--low-priority-tenant", action="append", default=None,
                    metavar="TENANT",
                    help="mark TENANT low priority (repeatable): its "
                         "submits shed first under depth pressure "
                         "(serve_shed{reason=priority}, serve/queue.py)")
    ap.add_argument("--priority-depth-frac", type=float, default=0.5,
                    metavar="FRAC",
                    help="queue-depth fraction past which low-priority "
                         "requests shed (1.0 disables the tier split)")
    ap.add_argument("--deadline", type=float, default=30.0)
    ap.add_argument("--dispatch-deadline", type=float,
                    default=watchdog.default_deadline_s() or 10.0)
    ap.add_argument("--retries", type=int, default=2)
    ap.add_argument("--probe-every", type=int, default=8, metavar="BATCHES")
    ap.add_argument("--max-inflight", type=int, default=None, metavar="N")
    ap.add_argument("--journal", default=None, metavar="PATH")
    ap.add_argument("--transfer-chunk-blocks", type=int, default=None,
                    metavar="BLOCKS",
                    help="chunk rung for oversized payloads "
                         "(serve/transfer.py; default: the top ladder "
                         "rung; 0 refuses oversized payloads outright)")
    ap.add_argument("--max-transfers", type=int, default=8, metavar="N",
                    help="concurrent chunked transfers before new ones "
                         "shed")
    ap.add_argument("--transfer-window", type=int, default=8, metavar="N",
                    help="in-flight chunks per transfer")
    ap.add_argument("--transfer-budget-bytes", type=int, default=64 << 20,
                    metavar="BYTES",
                    help="reassembly-buffer byte budget: held "
                         "out-of-order bytes past this shed NEW "
                         "transfers (backpressure, never a wedge)")
    ap.add_argument("--transfer-max-bytes", type=int, default=1 << 30,
                    metavar="BYTES",
                    help="per-transfer payload ceiling: a begin "
                         "frame's declared total above this refuses "
                         "too-large before any buffer is sized from it")
    ap.add_argument("--transfer-deadline", type=float, default=300.0,
                    metavar="S", help="default per-transfer budget")
    ap.add_argument("--transfer-ledger", default=None, metavar="PATH",
                    help="durable acked-chunk ledger (JSONL, fsync'd): "
                         "the resume contract survives this worker's "
                         "own SIGKILL")
    ap.add_argument("--session-per-tenant", type=int, default=16,
                    metavar="N",
                    help="open rc4 sessions per tenant before the "
                         "session store's LRU evicts that tenant's IDLE "
                         "rows (serve/session.py)")
    ap.add_argument("--session-window-bytes", type=int, default=65536,
                    metavar="BYTES",
                    help="pregenerated keystream kept ahead of each "
                         "session's consumed offset")
    ap.add_argument("--session-quantum-bytes", type=int, default=4096,
                    metavar="BYTES",
                    help="PRGA scan length per refill dispatch (the "
                         "fixed compiled quantum)")
    ap.add_argument("--session-prefetch-slots", type=int, default=8,
                    metavar="S",
                    help="sessions coalesced per prefetch dispatch (the "
                         "stacked scan's fixed S axis)")
    ap.add_argument("--session-budget-bytes", type=int, default=8 << 20,
                    metavar="BYTES",
                    help="global keystream-held budget: at the cap, "
                         "non-urgent refills pause and new opens shed")
    ap.add_argument("--ceiling-gbps", type=float, default=None,
                    metavar="GBPS",
                    help="the measured device roofline the cost model "
                         "records utilization against (obs/costmodel.py;"
                         " rides this worker's cost-*.json run-dir "
                         "stamp, so the fleet report's roofline table "
                         "has its denominator)")
    args = ap.parse_args(argv)
    if args.key_slots is None:
        args.key_slots = batcher.DEFAULT_KEY_SLOTS
    trace.ensure_run()
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
