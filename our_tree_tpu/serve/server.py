"""The serve dispatch loop: queue -> shape buckets -> guarded engine calls.

One asyncio loop on the main thread owns the whole path. Request
coroutines ``submit`` into the bounded queue; the batcher loop drains,
coalesces per (tenant, key) into ladder rungs (``batcher``), and
dispatches each batch synchronously through the scattered-CTR seam
(``models.aes.ctr_crypt_words_scattered`` under the engine
``resolve_engine`` picked at start). Synchronous on purpose: one device
serializes dispatches anyway, and keeping the engine call on the MAIN
thread is what lets the watchdog's SIGALRM interrupt a wedged dispatch
(resilience/watchdog.py's GIL-releasing contract).

Failure containment, per batch:

* transient dispatch failures retry through the shared ``RetryPolicy``
  (``serve-dispatch``; every failed attempt is a ``retry_failures``
  trace counter like every other policy in the repo);
* a batch that still fails resolves EVERY rider with a per-request
  ``dispatch-failed`` error — the server keeps serving;
* a batch killed by the watchdog (``DispatchTimeout``) resolves its
  riders with ``deadline`` errors and deliberately ABANDONS its
  ``batch-dispatched`` span: the dispatch never ended, so the orphaned
  begin is the honest evidence — the same closed-by-kill shape a
  SIGKILLed sweep child leaves, and what the CI gate pins with
  ``obs.report --check --expected-orphans batch-dispatched``.

The fault seam (``serve_dispatch``, plus the generic ``dispatch_fail`` /
``dispatch_hang``) sits inside the guard; the SERVE-LEVEL seams are
exempt during warmup — warmup is not traffic, and a counted CI shot
should land on a served batch, not on the ladder priming. Deeper engine
seams keep their own semantics: on a Pallas engine the launch seam
(``ops/pallas_aes.py:_dispatch_seam``) fires for priming dispatches
like any other first device contact, so there an armed generic fault
can fail ``start()`` loudly — a server that cannot prime its ladder
cannot serve, and masking that would be worse. The CPU CI rehearsals
run the jnp engine, where the serve seams are the only ones.

Obs spans: ``request-queued`` (queue.py, admission->drain),
``batch-formed`` (array packing), ``batch-dispatched`` (the engine
call, ``engine`` attr for the report's per-engine table).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

import jax
import numpy as np

from ..models import aes
from ..obs import trace
from ..resilience import faults, watchdog
from ..resilience.policy import RetryPolicy
from . import batcher
from .keycache import KeyCache, key_digest
from .queue import ERR_DEADLINE, ERR_DISPATCH, RequestQueue

#: The jax monitoring event that fires once per REAL backend compile and
#: never on an executable-cache hit — the zero-recompile assertion's
#: ground truth (``serve.bench --requests N --mixed-sizes`` must hold it
#: flat after warmup).
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_COMPILES = 0
_MONITOR_ON = False


def _on_event(name: str, *args, **kw) -> None:
    global _COMPILES
    if name == _COMPILE_EVENT:
        _COMPILES += 1


def compile_count() -> int:
    """Backend compiles observed in this process since the first call
    (callers difference two snapshots; the absolute value is unanchored)."""
    global _MONITOR_ON
    if not _MONITOR_ON:
        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _MONITOR_ON = True
    return _COMPILES


@dataclass
class ServerConfig:
    engine: str = "auto"
    min_bucket_blocks: int = batcher.DEFAULT_MIN_BLOCKS
    max_bucket_blocks: int = batcher.DEFAULT_MAX_BLOCKS
    max_depth: int = 1024
    #: per-request residency deadline (queue admission -> response)
    request_deadline_s: float = 30.0
    #: watchdog deadline around each engine call; None = the global
    #: OT_DISPATCH_DEADLINE default (0/unset disarms, like every seam)
    dispatch_deadline_s: float | None = None
    #: RetryPolicy attempts per batch (1 = no retry)
    retries: int = 2
    keycache_per_tenant: int = 8
    #: key lengths (bits) warmed per rung — a key size outside this set
    #: still works, it just pays its first-contact compile online
    warmup_key_bits: tuple = (128,)


class Server:
    """The online crypto service over the offline engines."""

    def __init__(self, config: ServerConfig | None = None):
        self.config = config or ServerConfig()
        c = self.config
        self.rungs = batcher.bucket_ladder(c.min_bucket_blocks,
                                           c.max_bucket_blocks)
        self.queue = RequestQueue(max_depth=c.max_depth,
                                  max_request_blocks=self.rungs[-1],
                                  default_deadline_s=c.request_deadline_s)
        self.keycache = KeyCache(per_tenant=c.keycache_per_tenant)
        self.engine: str | None = None  # resolved at start
        self._deadline_s = (watchdog.default_deadline_s()
                            if c.dispatch_deadline_s is None
                            else max(float(c.dispatch_deadline_s), 0.0))
        self._policy = RetryPolicy(
            attempts=max(int(c.retries), 1), base_delay_s=0.0,
            retry_on=(RuntimeError,), name="serve-dispatch")
        self._task: asyncio.Task | None = None
        self._running = False
        self.batches = 0
        self.batches_failed = 0
        self.batches_timed_out = 0
        #: bucket -> {"batches", "blocks"} running totals (O(#rungs)
        #: memory — a week-long soak must not grow per-batch state)
        self._occupancy: dict[int, dict] = {}
        self.warmup_compiles = 0
        self._compiles_at_ready = 0

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        """Resolve the engine, warm the ladder, start the batcher loop."""
        before = compile_count()
        self.engine = aes.resolve_engine(self.config.engine)
        with trace.span("serve-warmup", engine=self.engine,
                        rungs=len(self.rungs)):
            for bits in self.config.warmup_key_bits:
                _, nr, rk = self.keycache.get("_warmup",
                                              b"\x00" * (bits // 8))
                for rung in self.rungs:
                    words = np.zeros(4 * rung, dtype=np.uint32)
                    self._engine_call(words, words, rk, nr,
                                      f"warmup:{rung}", warmup=True)
        self._compiles_at_ready = compile_count()
        self.warmup_compiles = self._compiles_at_ready - before
        trace.gauge("serve_warmup_compiles", self.warmup_compiles,
                    engine=self.engine)
        self._running = True
        self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        self._running = False
        self.queue.kick()
        if self._task is not None:
            await self._task
            self._task = None
        self.queue.flush()

    def steady_compiles(self) -> int:
        """Backend compiles since warmup finished — the number the bucket
        ladder exists to hold at zero."""
        return compile_count() - self._compiles_at_ready

    # -- request side ------------------------------------------------------
    async def submit(self, tenant: str, key: bytes, nonce: bytes, payload,
                     deadline_s: float | None = None):
        """Admit one CTR crypt request and await its Response."""
        return await self.queue.submit(tenant, key, nonce, payload,
                                       deadline_s)

    # -- the batcher loop --------------------------------------------------
    async def _loop(self) -> None:
        while self._running:
            await self.queue.wait()
            while True:
                requests = self.queue.drain()
                if not requests:
                    break
                for b in batcher.form_batches(requests, self.rungs,
                                              key_digest):
                    self._run_batch(b)
                    # Yield between batches: resolved clients get to
                    # resubmit, so the next drain coalesces their
                    # follow-ups (the "continuous" in continuous
                    # batching under a closed loop).
                    await asyncio.sleep(0)

    def _run_batch(self, b: batcher.Batch) -> None:
        """One batch, contained: NO exception may escape — an escape
        would kill the batcher task and wedge every future request, so
        anything unexpected resolves the riders with errors and the
        loop lives on."""
        try:
            with trace.span("batch-formed", batch=b.label, bucket=b.bucket,
                            blocks=b.blocks, requests=len(b.requests)):
                _, nr, rk = self.keycache.get(b.tenant, b.key)
                b.materialise()
        except Exception as e:  # noqa: BLE001 - containment (docstring)
            self.batches_failed += 1
            trace.counter("serve_batch_failed", batch=b.label)
            for req in b.requests:
                req.fail(ERR_DISPATCH, f"{type(e).__name__}: {e}",
                         batch=b.label)
            return
        self.batches += 1
        occ = self._occupancy.setdefault(b.bucket,
                                         {"batches": 0, "blocks": 0})
        occ["batches"] += 1
        occ["blocks"] += b.blocks
        cm = trace.detached_span(
            "batch-dispatched", batch=b.label, bucket=b.bucket,
            blocks=b.blocks, requests=len(b.requests), engine=self.engine)
        cm.__enter__()
        try:
            out = self._policy.run(lambda att: self._engine_call(
                b.words, b.ctr_words, rk, nr, b.label))
        except watchdog.DispatchTimeout as e:
            # The dispatch never completed: the span is ABANDONED, not
            # closed — its orphaned begin is the kill evidence
            # (module docstring; the CI gate's --expected-orphans).
            self.batches_timed_out += 1
            trace.counter("serve_batch_deadline", batch=b.label)
            for req in b.requests:
                req.fail(ERR_DEADLINE, str(e), batch=b.label)
            return
        except Exception as e:  # noqa: BLE001 - containment (docstring)
            cm.__exit__(type(e), e, None)
            self.batches_failed += 1
            trace.counter("serve_batch_failed", batch=b.label)
            for req in b.requests:
                req.fail(ERR_DISPATCH, f"{type(e).__name__}: {e}",
                         batch=b.label)
            return
        cm.__exit__(None, None, None)
        from .queue import Response  # cycle-free: queue never imports us

        try:
            for req, data in zip(b.requests, b.split_output(out)):
                req.resolve(Response(ok=True, payload=data, batch=b.label))
        except Exception as e:  # noqa: BLE001 - containment (docstring)
            # E.g. a wrongly-shaped engine result breaking split_output:
            # riders not yet resolved get errors (fail() no-ops on the
            # already-resolved ones) and the loop lives on.
            self.batches_failed += 1
            trace.counter("serve_batch_failed", batch=b.label)
            for req in b.requests:
                req.fail(ERR_DISPATCH, f"{type(e).__name__}: {e}",
                         batch=b.label)

    # -- the guarded engine call ------------------------------------------
    def _engine_call(self, words, ctr_words, rk, nr, label,
                     warmup: bool = False):
        """One scattered-CTR dispatch under the watchdog. The
        serve-level fault seams fire only for traffic (warmup primes
        compiles, it is not a servable batch — a counted CI shot should
        land on requests); engine-internal seams, where an engine has
        them, see warmup like any first dispatch (module docstring).
        Warmup also swaps the SERVING deadline for the global opt-in one
        (OT_DISPATCH_DEADLINE): a first-contact compile legitimately
        dwarfs a steady-state dispatch, and killing the ladder priming
        at the per-batch latency budget would wedge every cold start."""
        deadline_s = (watchdog.default_deadline_s() if warmup
                      else self._deadline_s)
        with watchdog.deadline(deadline_s,
                               what=f"serve dispatch {label}"):
            if not warmup:
                faults.check("serve_dispatch", label)
                faults.check("dispatch_fail", label)
                watchdog.injected_hang("dispatch_hang", label)
            out = aes.ctr_crypt_words_scattered(
                words, ctr_words, rk, nr, self.engine)
            jax.block_until_ready(out)
        return np.asarray(out)

    # -- introspection -----------------------------------------------------
    def occupancy_histogram(self) -> dict:
        """bucket rung -> {batches, mean occupancy} (the padding price)."""
        return {str(bucket): {
            "batches": h["batches"],
            "mean_occupancy": round(h["blocks"] / (h["batches"] * bucket), 4)}
            for bucket, h in sorted(self._occupancy.items())}

    def stats(self) -> dict:
        return {
            "engine": self.engine,
            "rungs": list(self.rungs),
            "batches": self.batches,
            "batches_failed": self.batches_failed,
            "batches_timed_out": self.batches_timed_out,
            "occupancy": self.occupancy_histogram(),
            "queue": self.queue.stats(),
            "keycache": self.keycache.stats(),
            "compiles": {"warmup": self.warmup_compiles,
                         "steady": self.steady_compiles()},
        }
