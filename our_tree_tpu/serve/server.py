"""The serve dispatch loop: queue -> shape buckets -> in-flight lanes.

One asyncio loop on the main thread owns admission and batch formation;
dispatch is OVERLAPPED. Request coroutines ``submit`` into the bounded
queue; the batcher loop drains, rung-packs up to K key groups per batch
(``batcher`` — the multi-key coalescer: one dispatch carries many
tenants' keys via the stacked schedules + per-block slot vector), and
SUBMITS each batch as its own dispatch task: the loop keeps forming and
placing batches while up to ``max_inflight`` dispatches (default: one
per lane) are in flight across the lane pool, and per-lane completions
feed replies back into the loop as each batch's task resolves its
riders. That is the paper's ``length/num_threads`` decomposition at the
lane level — host batch formation, placement, and reply assembly
overlap device work, so aggregate goodput finally scales with lanes
instead of serializing behind one dispatch at a time. The engine comes
from ``aes.resolve_serve_engine``: the ranked jax-engine ladder
(pallas-dense-bp on a measured TPU) plus the native AESNI host tier,
which "auto" prefers on CPU — the fast-path tiering docs/SERVING.md
tabulates. Each dispatch runs on its lane's worker executor
(``serve/dispatch.py``) with the watchdog deadline armed on the worker
— expiry delivers through the thread-kill hook (fail the future,
abandon the wedged worker) instead of the old main-thread SIGALRM
raise, so a hang still surfaces AT the deadline while healthy lanes
keep streaming.

Failure containment, per batch (docs/SERVING.md has the sequence
diagram):

* transient dispatch failures retry through the lane's ``RetryPolicy``
  (``lane<i>-dispatch``) ON the same lane;
* a lane that still fails (or hangs past its watchdog deadline) is
  degraded through the health state machine — suspect, then
  quarantined; a TIMEOUT quarantines immediately — and the batch is
  **re-dispatched bit-exactly on a healthy lane** (CTR with explicit
  per-block counters is side-effect-free replay) BEFORE any rider sees
  an error;
* only when every lane has been tried (``LanesExhausted``) does the
  batch answer per-request errors (``deadline`` if the last cause was a
  hang, else ``dispatch-failed``) — and the server keeps serving;
* a hung dispatch deliberately ABANDONS its ``lane-dispatch`` span: the
  orphaned begin is the kill evidence (``obs.report --check
  --expected-orphans lane-dispatch``), same convention as a SIGKILLed
  sweep child;
* quarantined lanes are periodically canary-probed between batches and
  released into probation on a bit-exact response; quarantine is
  persisted to the serve journal with the SAME failure rows the sweep
  journal uses, so ``serve.bench --unquarantine lane:<i>`` is the same
  release edit as ``harness.bench --unquarantine``.

Shutdown DRAINS instead of dropping — including under overlap:
``stop()`` first closes admission (new submits answer ``shutdown``
immediately), then lets the batcher loop dispatch everything already
accepted AND await every in-flight batch task, then flushes (normally
nothing) — a clean stop answers every accepted request and leaves no
orphaned span. ``queue.stats()["lost"]`` (accepted minus answered) is
the invariant ``serve.bench`` gates on: it must be 0 even across a
faulted run.

The fault seams (``serve_dispatch``, generic ``dispatch_fail`` /
``dispatch_hang``, per-lane ``lane_fail``/``lane_hang`` with
``@lane=<i>`` scoping) all sit inside the lane's guarded engine call;
serve-level seams are exempt during warmup — warmup is not traffic, and
a counted CI shot should land on a served batch, not on the ladder
priming. Deeper engine seams keep their own semantics (on a Pallas
engine the launch seam fires for priming dispatches like any other
first device contact).

Obs spans: ``request-queued`` (queue.py, admission->drain),
``batch-formed`` (array packing), ``lane-dispatch`` (the engine call,
``lane`` + ``engine`` attrs for the report's per-lane and per-engine
tables), ``lane-probe`` (canary), ``serve-warmup`` / ``lane-warmup``.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass

import jax
import numpy as np

from ..aead import gcm as aead_gcm
from ..aead import ghash as aead_ghash
from ..models import aes
from ..obs import costmodel, incident, metrics, pulse, trace
from ..ops import gf
from ..resilience import faults
from ..resilience import journal as journal_mod
from ..resilience import watchdog
from ..utils import packing
from . import batcher, lanes, session as session_mod, transfer
from .keycache import KeyCache, key_digest
from .queue import (ERR_AUTH, ERR_BAD_REQUEST, ERR_DEADLINE, ERR_DISPATCH,
                    ERR_TOO_LARGE, GCM_MODES, MODES, RequestQueue, Response)
from .status import StatusServer

#: The jax monitoring event that fires once per REAL backend compile and
#: never on an executable-cache hit — the zero-recompile assertion's
#: ground truth (``serve.bench --requests N --mixed-sizes`` must hold it
#: flat after warmup). With multiple lanes the same program compiles
#: once per DEVICE, which is why warmup walks every lane x rung.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_COMPILES = 0
_MONITOR_ON = False

#: What the process is compiling FOR right now: the warmup walk stamps
#: (engine, rung) here before each ladder call, so the jax.monitoring
#: compile-duration events route into the registry as
#: ``serve_compile_us{engine, rung}`` histograms — the compile-cost
#: table that makes warmup startup time visible per rung (on TPU,
#: warmup dominates startup; until now its cost was one opaque wall).
#: rung=0 means "outside the ladder walk" (cost-model lowerings, a
#: steady-state recompile — the latter is already a gated contract
#: violation; here it additionally becomes a measured one).
_COMPILE_CTX = {"engine": "?", "rung": 0}


def compile_context(engine: str, rung: int) -> None:
    """Label subsequent backend-compile events (warmup walk only; the
    listener reads this when an XLA compile actually fires)."""
    _COMPILE_CTX["engine"] = str(engine)
    _COMPILE_CTX["rung"] = int(rung)


def _on_event(name: str, *args, **kw) -> None:
    global _COMPILES
    if name == _COMPILE_EVENT:
        _COMPILES += 1
        dur = args[0] if args and isinstance(args[0], (int, float)) else 0.0
        metrics.observe("serve_compile_us", float(dur) * 1e6,
                        engine=_COMPILE_CTX["engine"],
                        rung=_COMPILE_CTX["rung"])


def compile_count() -> int:
    """Backend compiles observed in this process since the first call
    (callers difference two snapshots; the absolute value is unanchored)."""
    global _MONITOR_ON
    if not _MONITOR_ON:
        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _MONITOR_ON = True
    return _COMPILES


@dataclass
class ServerConfig:
    #: resolved through ``aes.resolve_serve_engine``: the ranked-engine
    #: ladder (pallas-dense-bp on a measured TPU) plus the host tier —
    #: "auto" on CPU serves on the native AESNI runtime, "native" pins
    #: it (and refuses to start if it cannot build), any CORES name
    #: pins that jax engine (docs/SERVING.md has the tier table)
    engine: str = "auto"
    min_bucket_blocks: int = batcher.DEFAULT_MIN_BLOCKS
    max_bucket_blocks: int = batcher.DEFAULT_MAX_BLOCKS
    #: the fixed K dimension: key slots per dispatch (unused slots carry
    #: the all-zero schedule so shapes stay closed — zero-recompile)
    key_slots: int = batcher.DEFAULT_KEY_SLOTS
    #: native-tier ECB threads per slot run (0 = size-based default)
    native_threads: int = 0
    max_depth: int = 1024
    #: one tenant's max share of the queue depth (serve/queue.py): past
    #: ``frac * max_depth`` queued requests, that tenant sheds ITSELF
    #: (``serve_shed{reason=tenant}``) while others keep being admitted;
    #: 1.0 = no per-tenant cap (global shed only)
    tenant_depth_frac: float = 1.0
    #: the LOW-priority tenant set (serve/queue.py priority tiers):
    #: their submits shed first once queue depth crosses
    #: ``priority_depth_frac * max_depth`` (serve_shed{reason=priority})
    low_priority_tenants: tuple = ()
    #: the depth-pressure line low-priority shedding starts at
    priority_depth_frac: float = 0.5
    #: per-request residency deadline (queue admission -> response)
    request_deadline_s: float = 30.0
    #: watchdog deadline around each lane's engine call; None = the
    #: global OT_DISPATCH_DEADLINE default (0/unset disarms, like every
    #: seam)
    dispatch_deadline_s: float | None = None
    #: RetryPolicy attempts per batch PER LANE (1 = no on-lane retry;
    #: failover across lanes happens regardless)
    retries: int = 2
    keycache_per_tenant: int = 8
    #: key lengths (bits) warmed per rung — a key size outside this set
    #: still works, it just pays its first-contact compile online
    warmup_key_bits: tuple = (128,)
    #: the ENABLED served-mode set (queue.MODES). Warmup walks every
    #: enabled mode's ladder per lane — each mode is its own compiled
    #: program (GHASH direction / CBC core are static args) — and
    #: admission refuses modes outside it (an unwarmed mode's first
    #: dispatch would pay a steady-state compile, breaking the
    #: zero-recompile contract mid-traffic). Default ctr-only: AEAD
    #: serving is an explicit opt-in (docs/SERVING.md, AEAD section).
    modes: tuple = ("ctr",)
    #: dispatch lanes: None = one per visible device; an explicit count
    #: may exceed the device count (lanes share devices round-robin —
    #: the single-device rehearsal mode)
    lanes: int | None = None
    #: canary-probe quarantined lanes every N batches
    probe_every: int = 8
    #: clean batches a released lane serves before leaving probation
    probation_batches: int = 2
    #: serve journal path (lane quarantine persistence + the
    #: --unquarantine release edit); None = in-memory health only
    journal: str | None = None
    #: dispatches allowed in flight at once across the lane pool.
    #: None = one per lane (full overlap — the default); 1 restores the
    #: pre-overlap serialize-behind-one-dispatch behaviour (the bench
    #: control run); values above the lane count are clamped by
    #: placement itself (a lane holds one batch at a time)
    max_inflight: int | None = None
    #: operator status endpoint (serve/status.py): /metrics (Prometheus
    #: text from the obs.metrics registry) + /healthz (lane health,
    #: queue depth, in-flight, keycache — live JSON). None = off;
    #: 0 = an ephemeral port (tests read server.status.port)
    status_port: int | None = None
    #: the measured device roofline (GB/s, scripts/vpu_ceiling.py /
    #: BENCH_r* on a real TPU) the cost model reports utilization
    #: against; None = record traffic without a utilization ratio
    ceiling_gbps: float | None = None
    #: chunked transfers (serve/transfer.py): payloads above the ladder
    #: cap decompose into rung-sized chunks instead of refusing
    #: ``too-large``. None = chunks of exactly the top rung; 0 disables
    #: (the pre-stream refusal behaviour)
    transfer_chunk_blocks: int | None = None
    #: concurrent transfers admitted before new ones shed
    max_transfers: int = 8
    #: in-flight chunks per transfer (the pipelining window)
    transfer_window: int = 8
    #: reassembly-buffer byte budget: completed-but-unconsumed chunk
    #: bytes past this shed NEW transfers (backpressure, never a wedge)
    transfer_budget_bytes: int = 64 << 20
    #: per-transfer payload ceiling: a begin frame's client-declared
    #: total above this refuses ``too-large`` BEFORE any buffer is
    #: sized from it (serve/worker.py's validate-before-allocate)
    transfer_max_bytes: int = 1 << 30
    #: per-transfer wall deadline (the whole exchange's Budget)
    transfer_deadline_s: float = 300.0
    #: transfer ledger journal path (resume tokens survive the process);
    #: None = in-memory ledger (transparent decomposition only)
    transfer_ledger: str | None = None
    #: served RC4 sessions (serve/session.py; active iff "rc4" is in
    #: ``modes``): open sessions admitted per tenant before the store's
    #: LRU considers evicting that tenant's IDLE rows
    session_per_tenant: int = 16
    #: pregenerated keystream kept ahead of each session's consumed
    #: offset (bytes); the watermark refill tops sessions back up to it
    session_window_bytes: int = 65536
    #: PRGA scan length per refill dispatch (bytes, multiple of 4) —
    #: the FIXED compiled quantum every prefetch dispatch shares
    session_quantum_bytes: int = 4096
    #: sessions coalesced per prefetch dispatch (the stacked S axis of
    #: the vmapped scan — also a fixed compile shape)
    session_prefetch_slots: int = 8
    #: global keystream-bytes-held budget: at the cap, non-urgent
    #: refills pause and new opens shed (backpressure, never a wedge)
    session_budget_bytes: int = 8 << 20


class Server:
    """The online crypto service over the offline engines."""

    def __init__(self, config: ServerConfig | None = None):
        self.config = config or ServerConfig()
        c = self.config
        self.rungs = batcher.bucket_ladder(c.min_bucket_blocks,
                                           c.max_bucket_blocks)
        bad = [m for m in c.modes if m not in MODES]
        if bad or not c.modes:
            raise ValueError(f"unknown serve mode(s) {bad} "
                             f"(known: {MODES})")
        self.queue = RequestQueue(max_depth=c.max_depth,
                                  max_request_blocks=self.rungs[-1],
                                  default_deadline_s=c.request_deadline_s,
                                  tenant_depth_frac=c.tenant_depth_frac,
                                  low_priority_tenants=c.low_priority_tenants,
                                  priority_depth_frac=c.priority_depth_frac,
                                  modes=c.modes)
        self.keycache = KeyCache(per_tenant=c.keycache_per_tenant)
        self.engine: str | None = None   # resolved at start
        self.pool: lanes.LanePool | None = None  # built at start
        self._deadline_s = (watchdog.default_deadline_s()
                            if c.dispatch_deadline_s is None
                            else max(float(c.dispatch_deadline_s), 0.0))
        self._journal = None
        self._task: asyncio.Task | None = None
        self._running = False
        self.status: StatusServer | None = None
        #: the live pulse analytics thread (obs/pulse.py), started at
        #: start() after warmup; None when OT_PULSE=0
        self.pulse: pulse.PulseThread | None = None
        #: overlap state: the in-flight cap (resolved at start) and the
        #: live task set (dispatch + probe tasks; drain awaits it). The
        #: MEASURED concurrency lives in the pool (`max_inflight_seen`:
        #: lane-occupancy windows, not task counts — queued-behind-a-
        #: busy-lane work must not satisfy the `--min-inflight` gate).
        self.inflight_limit = 0
        self._sem: asyncio.Semaphore | None = None
        self._tasks: set = set()
        self.batches = 0
        self.batches_failed = 0
        self.batches_timed_out = 0
        #: bucket -> {"batches", "blocks"} running totals (O(#rungs)
        #: memory — a week-long soak must not grow per-batch state)
        self._occupancy: dict[int, dict] = {}
        #: rung-packer accounting: payload vs dispatched (rung) blocks
        #: and key-slot fill — the ``coalesce_efficiency`` stat
        self._payload_blocks = 0
        self._dispatched_blocks = 0
        self._slots_used = 0
        self._slot_capacity = 0
        self.warmup_compiles = 0
        self._compiles_at_ready = 0
        #: the warmed ladder's cost-model records (obs/costmodel.py),
        #: filled at start(); the bench's ``cost`` section reads them
        self.cost_records: list = []
        #: the chunked-transfer engine (serve/transfer.py): oversized
        #: payloads decompose into ladder riders through the SAME queue
        #: admission every ordinary request takes. None when disabled
        #: (transfer_chunk_blocks=0).
        self.transfers: transfer.TransferManager | None = None
        if c.transfer_chunk_blocks != 0:
            chunk_blocks = min(c.transfer_chunk_blocks or self.rungs[-1],
                               self.rungs[-1])
            self.transfers = transfer.TransferManager(
                self._transfer_chunk, chunk_blocks=chunk_blocks,
                max_transfers=c.max_transfers, window=c.transfer_window,
                reassembly_budget_bytes=c.transfer_budget_bytes,
                max_payload_bytes=c.transfer_max_bytes,
                deadline_s=c.transfer_deadline_s,
                ledger=transfer.TransferLedger(c.transfer_ledger))
        #: the RC4 session engine (serve/session.py): per-session PRGA
        #: carry state + the batched keystream prefetcher, dispatching
        #: through the SAME lane pool (and its failover) as traffic.
        #: Built only when the rc4 mode is enabled.
        self.sessions: session_mod.SessionManager | None = None
        if "rc4" in c.modes:
            self.sessions = session_mod.SessionManager(
                self._session_prep, per_tenant=c.session_per_tenant,
                window_bytes=c.session_window_bytes,
                quantum_bytes=c.session_quantum_bytes,
                prefetch_slots=c.session_prefetch_slots,
                budget_bytes=c.session_budget_bytes)

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        """Resolve the engine, build the lane pool, adopt journal
        quarantines, warm every lane x rung, start the batcher loop."""
        c = self.config
        before = compile_count()
        self.engine = aes.resolve_serve_engine(c.engine)
        if c.journal:
            self._journal = journal_mod.SweepJournal(
                c.journal, {"kind": "serve-lanes",
                            "lanes": c.lanes, "engine": c.engine})
        self.pool = lanes.LanePool(
            engine=self.engine, deadline_s=self._deadline_s,
            retries=c.retries, lanes=c.lanes, probe_every=c.probe_every,
            probation_batches=c.probation_batches, journal=self._journal,
            native_threads=c.native_threads)
        self.pool.adopt_journal_quarantines()
        self._warmup()
        if not any(l.warmed for l in self.pool.lanes):
            # Per-lane containment must not mask a TOTAL boot failure:
            # one dead lane among several degrades that lane, but a
            # server that could not prime a single lane cannot serve —
            # fail start() loudly (the pre-lane contract) instead of
            # answering dispatch-failed forever.
            raise RuntimeError(
                f"serve warmup failed on all {len(self.pool.lanes)} "
                f"lane(s) — no lane can dispatch (engine {self.engine})")
        # The cost/attribution plane (obs/costmodel.py): modeled
        # per-(engine, mode, rung) dispatch traffic for the warmed
        # ladder — analytic always, XLA-backed per OT_COST_XLA (the
        # lowerings below may compile; they run BEFORE the ready marker
        # so they count as warmup, never as a steady-state recompile).
        # Stamped into the run dir so obs.report can roofline post-hoc,
        # and onto the incident recorder so bundles are self-contained.
        # rc4 is excluded from the cost model: ladder_costs prices AES
        # rounds per key size (ROUNDS is AES-only) and the rc4 XOR is
        # key-oblivious — no (bits, nr) row exists for it.
        cost_modes = tuple(m for m in c.modes if m != "rc4") or ("ctr",)
        self.cost_records = costmodel.ladder_costs(
            self.engine, cost_modes, self.rungs,
            key_bits=c.warmup_key_bits, key_slots=c.key_slots)
        costmodel.write_run_records(self.cost_records, engine=self.engine,
                                    ceiling_gbps=c.ceiling_gbps)
        incident.set_cost_records(self.cost_records)
        self._compiles_at_ready = compile_count()
        self.warmup_compiles = self._compiles_at_ready - before
        trace.gauge("serve_warmup_compiles", self.warmup_compiles,
                    engine=self.engine, lanes=len(self.pool.lanes))
        self.inflight_limit = (len(self.pool.lanes)
                               if c.max_inflight is None
                               else max(int(c.max_inflight), 1))
        self._sem = asyncio.Semaphore(self.inflight_limit)
        # The metrics flusher: periodic registry snapshots into the
        # trace run dir (no-op while OT_TRACE_DIR is unset — the
        # registry still counts in memory for /metrics and the bench
        # artifact either way).
        metrics.ensure_flusher()
        # The live analytics plane (obs/pulse.py): windowed rates, the
        # per-worker capacity model (/healthz "capacity"), and the
        # typed alert rules — started AFTER warmup so the compile ramp
        # is behind every frame the engine ever sees. None when
        # OT_PULSE=0.
        self.pulse = pulse.start_live("serve",
                                      cost_records=self.cost_records)
        if c.status_port is not None:
            self.status = StatusServer(self, c.status_port)
            await self.status.start()
        self._running = True
        self._task = asyncio.ensure_future(self._loop())

    def _warmup(self) -> None:
        """Prime every lane's compile cache over the full ladder. The
        smallest rung doubles as the CANARY batch: its input is pinned
        (zero key, zero payload, zero-nonce counters), the first lane's
        output becomes the canary expectation, and every other lane's
        warmup output is compared against it — cross-lane bit-exactness
        is checked at startup, not assumed. A lane whose warmup fails,
        hangs, or mismatches starts quarantined and UNWARMED (it cannot
        be canary-released; ``--unquarantine`` + restart is its path
        back)."""
        c = self.config
        canary_rung = self.rungs[0]
        canary_words = np.zeros(4 * canary_rung, dtype=np.uint32)
        canary_ctr = packing.np_ctr_le_blocks(
            b"\x00" * 16,
            np.arange(canary_rung, dtype=np.uint32)).reshape(-1)
        canary_expected = None
        # One all-zero slot vector per rung: warmup compiles the EXACT
        # traffic signature — (words, counters, (K, 4*(nr+1)) stack,
        # (rung,) slot vector) — so a steady-state batch is always a
        # cache hit regardless of how many slots it actually fills.
        slot_vecs = {rung: np.zeros(rung, dtype=np.uint32)
                     for rung in self.rungs}
        # Trusted lanes warm FIRST: the first lane to warm pins the
        # canary expectation every other lane is compared against, and
        # a lane that starts quarantined (journal-adopted — possibly for
        # producing wrong bytes) must never be the oracle. With healthy
        # lanes ahead of it, a corrupt quarantined lane fails its own
        # warmup comparison instead, stays UNWARMED, and can never be
        # canary-released against its own output.
        order = sorted(self.pool.lanes,
                       key=lambda l: (l.state == lanes.QUARANTINED, l.idx))
        compile_context(self.engine, 0)
        with trace.span("serve-warmup", engine=self.engine,
                        rungs=len(self.rungs), lanes=len(self.pool.lanes)):
            for lane in order:
                with trace.span("lane-warmup", lane=lane.idx,
                                engine=self.engine):
                    try:
                        mismatch = False
                        for bits in c.warmup_key_bits:
                            sched = self.keycache.stacked(
                                [("_warmup", b"\x00" * (bits // 8))],
                                c.key_slots)
                            for rung in self.rungs:
                                compile_context(self.engine, rung)
                                if (rung == canary_rung
                                        and bits == c.warmup_key_bits[0]):
                                    out = lane.engine_call(
                                        canary_words, canary_ctr, sched,
                                        slot_vecs[canary_rung],
                                        f"warmup:{rung}", warmup=True)
                                    if canary_expected is None:
                                        canary_expected = out
                                        self.pool.set_canary(
                                            canary_words, canary_ctr,
                                            sched, slot_vecs[canary_rung],
                                            out, canary_rung)
                                    elif not np.array_equal(
                                            out, canary_expected):
                                        mismatch = True
                                        break
                                else:
                                    words = np.zeros(4 * rung,
                                                     dtype=np.uint32)
                                    lane.engine_call(words, words, sched,
                                                     slot_vecs[rung],
                                                     f"warmup:{rung}",
                                                     warmup=True)
                            if mismatch:
                                break
                            # Every enabled AEAD/CBC mode primes its OWN
                            # ladder: the GHASH direction and the CBC
                            # decrypt core are static compile arguments,
                            # so each mode is a distinct program per
                            # (lane, rung) — an unwarmed mode's first
                            # batch would recompile mid-traffic.
                            for m in c.modes:
                                # rc4 is schedule-free: keycache.stacked
                                # cannot expand it and the XOR/PRGA
                                # programs are keyless — it primes its
                                # OWN block below, outside the per-bits
                                # loop.
                                if m in ("ctr", "rc4"):
                                    continue
                                sched_m = self.keycache.stacked(
                                    [("_warmup", b"\x00" * (bits // 8))],
                                    c.key_slots, mode=m)
                                for rung in self.rungs:
                                    compile_context(self.engine, rung)
                                    words = np.zeros(4 * rung,
                                                     dtype=np.uint32)
                                    lane.engine_call(
                                        words, words, sched_m,
                                        slot_vecs[rung],
                                        f"warmup:{rung}:{m}", warmup=True,
                                        mode=m, inject_words=words,
                                        seg_keep=np.ones(
                                            rung, dtype=np.uint32))
                        if "rc4" in c.modes and not mismatch:
                            # RC4 primes exactly two program families
                            # per lane: the key-oblivious XOR at every
                            # rung (the crypt-phase shape session
                            # chunks batch into) and ONE batched PRGA
                            # scan at the prefetcher's fixed
                            # (slots x quantum) carry shape — with
                            # both warm, session traffic never
                            # compiles (the zero-recompile contract
                            # extends to the session axis). ``sched``
                            # is None: the rc4 lane branch ignores it.
                            for rung in self.rungs:
                                compile_context(self.engine, rung)
                                words = np.zeros(4 * rung,
                                                 dtype=np.uint32)
                                lane.engine_call(
                                    words, words, None, slot_vecs[rung],
                                    f"warmup:{rung}:rc4", warmup=True,
                                    mode="rc4")
                            s = c.session_prefetch_slots
                            q = c.session_quantum_bytes
                            compile_context(self.engine, q // 16)
                            lane.engine_call(
                                np.zeros(s * 256, dtype=np.uint32),
                                np.zeros(2 * s, dtype=np.uint32),
                                None, slot_vecs[self.rungs[0]],
                                "warmup:rc4-prep", warmup=True,
                                mode="rc4-prep", prep_len=q)
                        if mismatch:
                            lane._quarantine("warmup-mismatch",
                                             self._journal)
                        else:
                            lane.warmed = True
                    except Exception as e:  # noqa: BLE001 - contain per lane
                        # Includes DispatchTimeout: a lane dead at boot
                        # degrades THAT lane, not start().
                        lane._quarantine(
                            f"warmup-failed:{type(e).__name__}",
                            self._journal)
        # Compiles past this point (cost-model lowerings, any
        # steady-state recompile) land unattributed at rung 0.
        compile_context(self.engine, 0)

    async def stop(self) -> None:
        """Graceful drain: stop placement (admission closes), let the
        batcher loop finish everything already accepted, then close.
        A clean stop answers every accepted request — zero lost, zero
        orphaned spans."""
        self.queue.close()
        self._running = False
        self.queue.kick()
        if self._task is not None:
            await self._task
            self._task = None
        dropped = self.queue.flush()
        if dropped:
            trace.counter("serve_drain_dropped", n=dropped)
        trace.point("serve-drained",
                    answered=self.queue.answered,
                    lost=self.queue.accepted - self.queue.answered,
                    max_inflight=self.max_inflight_seen)
        if self.status is not None:
            await self.status.stop()
            self.status = None
        if self.pulse is not None:
            self.pulse.stop()
        if self.pool is not None:
            self.pool.close()  # idle workers dismissed; wedged ones are
            #                    already abandoned (stale generation)
        if self._journal is not None:
            self._journal.close()
        if self.sessions is not None:
            # Force-close whatever is still open (counted: a drain with
            # open sessions is visible, not silent) and stop the
            # background refill — after the batcher drain above, no
            # chunk can still be riding their keystream.
            await self.sessions.drain()
        if self.transfers is not None:
            self.transfers.ledger.close()
        # Final exact totals on disk even if the process never reaches
        # atexit (e.g. an embedding test harness).
        metrics.flush_now()

    @property
    def max_inflight_seen(self) -> int:
        """The run's measured dispatch concurrency: the pool's
        lane-occupancy high-water mark (serve/lanes.py:_inflight) — NOT
        a count of spawned batch tasks, which queuing alone can inflate."""
        return self.pool.max_inflight_seen if self.pool is not None else 0

    def steady_compiles(self) -> int:
        """Backend compiles since warmup finished — the number the bucket
        ladder (walked per lane) exists to hold at zero."""
        return compile_count() - self._compiles_at_ready

    # -- request side ------------------------------------------------------
    async def submit(self, tenant: str, key: bytes, nonce: bytes, payload,
                     deadline_s: float | None = None,
                     sampled: bool | None = None,
                     parent: str | None = None,
                     priority: int | None = None, mode: str = "ctr",
                     iv: bytes = b"", aad: bytes = b"", tag: bytes = b"",
                     sid: int = -1):
        """Admit one crypt request and await its Response.
        ``sampled``/``parent``/``priority`` propagate a wire-fronted
        request's router-side admission decisions; ``mode`` selects the
        served workload with its ``iv``/``aad``/``tag`` fields
        (serve/queue.py has the per-mode contract).

        Payloads above the ladder cap no longer refuse ``too-large``:
        they decompose into rung-sized chunks (serve/transfer.py) that
        ride the same queue/batcher/lane machinery as everyone else,
        and the spliced Response is byte-identical to what one giant
        rung would have produced (chunk-boundary KATs pin it)."""
        data = np.asarray(payload, dtype=np.uint8).reshape(-1)
        if mode == "rc4" and self.sessions is not None:
            # Session data chunk: reserve the chunk's keystream slice
            # from the session's prefetched window (hit = no device
            # wait; miss = an awaited urgent refill), ride the queue as
            # an ordinary coalescable request carrying that slice, and
            # ACK on ANY final answer — a failed chunk's error is final
            # too, and its bytes must not pin the window forever.
            resv = await self.sessions.reserve(tenant, sid, data.size)
            if isinstance(resv, Response):
                return resv
            ks, off = resv
            try:
                return await self.queue.submit(
                    tenant, key, nonce, data, deadline_s, sampled=sampled,
                    parent=parent, priority=priority, mode=mode,
                    sid=sid, ks=ks, ks_offset=off)
            finally:
                self.sessions.ack(tenant, sid, off, data.size)
        span = data.size // 16 + (1 if mode in GCM_MODES else 0)
        if self.transfers is not None and span > self.rungs[-1] \
                and data.size and data.size % 16 == 0:
            return await self.submit_transfer(
                tenant, key, nonce, data, deadline_s=deadline_s,
                sampled=sampled, parent=parent, mode=mode, iv=iv)
        return await self.queue.submit(tenant, key, nonce, payload,
                                       deadline_s, sampled=sampled,
                                       parent=parent, priority=priority,
                                       mode=mode, iv=iv, aad=aad, tag=tag,
                                       sid=sid)

    async def submit_transfer(self, tenant: str, key: bytes, nonce: bytes,
                              payload, deadline_s: float | None = None,
                              sampled: bool | None = None,
                              parent: str | None = None, mode: str = "ctr",
                              iv: bytes = b"",
                              resume_token: str | None = None,
                              tails: dict | None = None,
                              on_chunk=None):
        """The explicit chunked-transfer entry (what ``submit`` takes
        automatically for oversized payloads): ``resume_token`` /
        ``tails`` / ``on_chunk`` are the wire frontend's resumable
        streaming hooks (serve/worker.py's ``tx`` sub-protocol)."""
        if self.transfers is None:
            return Response(ok=False, error=ERR_TOO_LARGE,
                            detail="transfers disabled on this server")
        return await self.transfers.run(
            tenant, key, nonce, payload, mode=mode, iv=iv,
            deadline_s=deadline_s, sampled=sampled, parent=parent,
            resume_token=resume_token, tails=tails, on_chunk=on_chunk)

    async def _transfer_chunk(self, tenant: str, key: bytes,
                              spec: transfer.ChunkSpec, piece, *,
                              mode: str, deadline_s: float | None,
                              sampled: bool, parent: str | None):
        """The transfer engine's submit seam: one chunk = one ORDINARY
        queue admission — it batches, coalesces, fails over, and is
        deadline-policed exactly like a client-sized request."""
        return await self.queue.submit(
            tenant, key, spec.nonce or b"", piece, deadline_s,
            sampled=sampled, parent=parent, mode=mode, iv=spec.iv)

    # -- session side ------------------------------------------------------
    async def open_session(self, tenant: str, sid: int, key: bytes):
        """Open (KSA + full-window keystream prefill) one RC4 session."""
        if self.sessions is None:
            return Response(ok=False, error=ERR_BAD_REQUEST,
                            detail="rc4 mode not enabled on this server")
        return await self.sessions.open(tenant, sid, key)

    async def close_session(self, tenant: str, sid: int):
        """Close one RC4 session, releasing its window and state."""
        if self.sessions is None:
            return Response(ok=False, error=ERR_BAD_REQUEST,
                            detail="rc4 mode not enabled on this server")
        return await self.sessions.close(tenant, sid)

    async def _session_prep(self, m_words, xy_words, sampled: bool):
        """The session prefetcher's lane seam: ONE batched PRGA scan
        (mode ``rc4-prep``) through the same failover pool as traffic.
        A lane that dies or hangs mid-scan redispatches the identical
        carry arrays on a healthy lane — the scan is a pure function of
        its carries, so the replayed keystream is bit-exact — and the
        attempt count comes back as the session layer's
        keystream-replay evidence (``serve_session_replays``)."""
        q = self.config.session_quantum_bytes
        s = int(xy_words.shape[0]) // 2
        out, _lane, replays = await self.pool.dispatch(
            np.ascontiguousarray(m_words, dtype=np.uint32),
            np.ascontiguousarray(xy_words, dtype=np.uint32),
            None, np.zeros(1, dtype=np.uint32), f"rc4-prep:{s}",
            bucket=q // 16, blocks=s * (q // 16), requests=1,
            sampled=sampled, mode="rc4-prep", prep_len=q)
        return np.asarray(out), replays

    # -- the batcher loop --------------------------------------------------
    async def _loop(self) -> None:
        while True:
            await self.queue.wait()
            while True:
                requests = self.queue.drain()
                if not requests:
                    break
                for b in batcher.form_batches(requests, self.rungs,
                                              key_digest,
                                              self.config.key_slots):
                    # Submit: take an in-flight slot (backpressure — the
                    # queue's bounded depth holds while every slot is
                    # busy), spawn the batch's dispatch task, and keep
                    # forming. Completion resolves the riders inside the
                    # task; the loop never waits for device work.
                    await self._sem.acquire()
                    self._spawn(self._run_batch(b))
                    # The periodic canary pass runs as its OWN task: a
                    # probe of a genuinely dead lane costs its watchdog
                    # deadline, and awaiting that inline would stall
                    # every new batch behind it (re-probe concurrency is
                    # safe: _probe_open skips busy/non-quarantined
                    # lanes). The due-check stays inline — cheap and
                    # synchronous — so the common no-op case costs no
                    # task. Drain awaits probe tasks like dispatches,
                    # so a probe in flight at shutdown still closes its
                    # span.
                    if self.pool.probe_due():
                        self._spawn(self.pool.probe_pass())
                    # Yield between batches: resolved clients get to
                    # resubmit, so the next drain coalesces their
                    # follow-ups (the "continuous" in continuous
                    # batching under a closed loop).
                    await asyncio.sleep(0)
            if not self._running:
                # stop() closed admission BEFORE clearing _running, so
                # the drain that just emptied was the complete final
                # set. Everything accepted has been SUBMITTED; await the
                # in-flight tasks so everything is also ANSWERED — the
                # drain-under-overlap contract (`lost` stays 0 with N
                # batches in flight at shutdown). return_exceptions:
                # a probe task must never take the drain down with it.
                if self._tasks:
                    await asyncio.gather(*list(self._tasks),
                                         return_exceptions=True)
                return

    def _spawn(self, coro) -> None:
        """Run ``coro`` as a tracked background task: the drain gathers
        every tracked task before the loop exits."""
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run_batch(self, b: batcher.Batch) -> None:
        """One batch's dispatch task: form arrays, dispatch (awaiting
        the lane executor), resolve riders. Contained: NO exception may
        escape — an escape would kill this task silently and lose its
        riders, so anything unexpected resolves them with errors; the
        in-flight slot is returned in every outcome."""
        try:
            formed = self._form_batch(b)
            if formed is not None:
                await self._dispatch_batch(b, formed[0])
        finally:
            self._sem.release()

    def _form_batch(self, b: batcher.Batch):
        """Array materialisation + schedule stacking; returns a
        1-tuple ``(sched,)`` (sched is None for the schedule-free rc4
        mode — the tuple keeps "formed, no schedule" distinct from
        failure), or None after answering the riders when formation
        itself failed."""
        try:
            # Emitted iff the batch carries a sampled rider; a formation
            # FAILURE still materialises the span (error end) whatever
            # the sample said — incident evidence is never sampled out.
            with trace.maybe_span(b.sampled, "batch-formed", batch=b.label,
                                  bucket=b.bucket, blocks=b.blocks,
                                  slots=len(b.slots),
                                  requests=len(b.requests)):
                # rc4 batches are schedule-free (the XOR is
                # key-oblivious; the per-session key was consumed by
                # the host KSA at session open) — the keycache never
                # sees them, so its tenant-isolation LRU is untouched
                # by session traffic.
                sched = (None if b.mode == "rc4"
                         else self.keycache.stacked(b.keys, b.key_slots,
                                                    mode=b.mode))
                # The native tier generates counters inside C per
                # request (the batch's ``runs`` layout) — materialising
                # the (N, 4) counter array it would never read is pure
                # memory-bandwidth tax at the big rungs. CTR only: the
                # AEAD/CBC modes dispatch through the jax path and read
                # their arrays regardless of tier.
                b.materialise(counters=(b.mode != "ctr"
                                        or self.engine != aes.NATIVE_ENGINE),
                              sched=sched)
                return (sched,)
        except Exception as e:  # noqa: BLE001 - containment (docstring)
            self.batches_failed += 1
            metrics.counter("serve_batches", outcome="form-failed")
            trace.counter("serve_batch_failed", batch=b.label)
            for req in b.requests:
                req.fail(ERR_DISPATCH, f"{type(e).__name__}: {e}",
                         batch=b.label)
            return None

    async def _dispatch_batch(self, b: batcher.Batch, sched) -> None:
        from .queue import Response  # cycle-free: queue never imports us

        t_d0 = time.monotonic()
        timing: dict = {}
        try:
            out, _lane, _redispatched = await self.pool.dispatch(
                b.words, b.ctr_words, sched, b.slot_index, b.label,
                bucket=b.bucket, blocks=b.blocks,
                requests=len(b.requests), runs=b.runs,
                sampled=b.sampled, timing=timing, mode=b.mode,
                inject_words=b.inject_words, seg_keep=b.seg_keep)
        except lanes.LanesExhausted as e:
            # Failover already ran: every lane was tried (and each
            # miss degraded its lane's health). Only now do the riders
            # see errors — coded by what finally stopped the batch.
            if e.timed_out:
                self.batches_timed_out += 1
                metrics.counter("serve_batches", outcome="deadline")
                trace.counter("serve_batch_deadline", batch=b.label)
                code = ERR_DEADLINE
            else:
                self.batches_failed += 1
                metrics.counter("serve_batches", outcome="failed")
                trace.counter("serve_batch_failed", batch=b.label)
                code = ERR_DISPATCH
            for req in b.requests:
                req.fail(code, str(e), batch=b.label)
            return
        except Exception as e:  # noqa: BLE001 - containment (docstring)
            self.batches_failed += 1
            metrics.counter("serve_batches", outcome="failed")
            trace.counter("serve_batch_failed", batch=b.label)
            for req in b.requests:
                req.fail(ERR_DISPATCH, f"{type(e).__name__}: {e}",
                         batch=b.label)
            return
        # Dispatch succeeded: only now does the batch enter the
        # coalesce/occupancy accounting — a batch that exhausted every
        # lane served nothing, and counting it would let a failure-heavy
        # run pass the CI-gated coalesce_efficiency on phantom traffic.
        self.batches += 1
        metrics.counter("serve_batches", outcome="ok")
        metrics.counter("serve_served_bytes", b.blocks * 16)
        occ = self._occupancy.setdefault(b.bucket,
                                         {"batches": 0, "blocks": 0})
        occ["batches"] += 1
        occ["blocks"] += b.blocks
        self._payload_blocks += b.blocks
        self._dispatched_blocks += b.bucket
        self._slots_used += len(b.slots)
        self._slot_capacity += b.key_slots
        # The batch's dispatch window, split for the ledger: executor
        # wait + device compute from the lane seam, host overhead as the
        # remainder — with pack (drain -> dispatch submit) before it and
        # reply (dispatch end -> resolve) after, every rider's stages
        # are contiguous by clock and sum to its measured residency.
        t_d1 = time.monotonic()
        dispatch_total = int((t_d1 - t_d0) * 1e6)
        wait_us = int(timing.get("worker_wait_us", 0))
        device_us = int(timing.get("device_us", 0))
        host_us = max(dispatch_total - wait_us - device_us, 0)
        if b.requests:
            pack_b = max(int((t_d0 - b.requests[0].t_drain) * 1e6), 0)
            metrics.observe("serve_stage_us", pack_b, stage="pack")
            b.stages.update(pack_us=pack_b, worker_wait_us=wait_us,
                            dispatch_us=host_us, device_us=device_us)
        try:
            if b.mode in GCM_MODES:
                res = np.asarray(out)
                outs = b.split_output(res[0])
                tags, auth_ok = self._gcm_finish(b, sched, res[0], res[1])
            else:
                outs = b.split_output(out)
                tags = auth_ok = None
            for i, (req, data) in enumerate(zip(b.requests, outs)):
                if auth_ok is not None and not auth_ok[i]:
                    # Tag mismatch: a PER-REQUEST refusal — the batch
                    # and its other riders are untouched, and no
                    # plaintext leaves the server for this request.
                    metrics.counter("serve_auth_failed", mode=b.mode)
                    trace.counter("serve_auth_failed", batch=b.label)
                    # One mismatch is a data event; a SPIKE within the
                    # incident window dumps a flight-recorder bundle.
                    incident.note_auth_failure()
                    req.fail(ERR_AUTH,
                             "GCM tag mismatch (authentication failed)",
                             batch=b.label)
                    continue
                ledger = None
                t_now = time.monotonic()
                reply_us = max(int((t_now - t_d1) * 1e6), 0)
                if req.sampled:
                    ledger = {
                        "stages": {
                            "backend_queue": req.queued_us,
                            "pack": max(int((t_d0 - req.t_drain) * 1e6),
                                        0),
                            "worker_wait": wait_us,
                            "dispatch": host_us,
                            "device": device_us,
                            "reply": reply_us,
                        },
                        "total_us": int((t_now - req.t_submit) * 1e6),
                    }
                req.resolve(Response(ok=True, payload=data, batch=b.label,
                                     ledger=ledger,
                                     tag=(tags[i] if tags is not None
                                          and b.mode == "gcm" else None)))
                metrics.observe("serve_stage_us", reply_us, stage="reply")
        except Exception as e:  # noqa: BLE001 - containment (docstring)
            # E.g. a wrongly-shaped engine result breaking split_output:
            # riders not yet resolved get errors (fail() no-ops on the
            # already-resolved ones) and the loop lives on.
            self.batches_failed += 1
            metrics.counter("serve_batches", outcome="split-failed")
            trace.counter("serve_batch_failed", batch=b.label)
            for req in b.requests:
                req.fail(ERR_DISPATCH, f"{type(e).__name__}: {e}",
                         batch=b.label)

    def _gcm_finish(self, b: batcher.Batch, sched, crypt_flat,
                    y_flat) -> tuple[list, list]:
        """The host per-request GHASH tail for a served GCM batch:
        each request's running Y comes off its LAST data row of the
        fused kernel's state stream, the length block is folded in with
        its slot's H (one ``gf128_mul`` per request — the variable-
        length work the fixed-shape kernel leaves to the host), and the
        E_K(J0) pad comes off the request's J0 row of the CTR output.
        Returns (tags, auth_ok): ``tags`` the 16-byte computed tag per
        request (in ``b.requests`` order); ``auth_ok`` per-request
        verification for ``gcm-open`` (always True for seal). The
        compare is the constant-time host twin (``ghash.np_tag_eq``);
        the ``tag_mismatch`` fault point forces a mismatch here — the
        deterministic way CI drives the auth-failure path."""
        slot_of = [si for si, slot in enumerate(b.slots)
                   for _ in slot.requests]
        tags, auth_ok = [], []
        for (off, n), si, req in zip(b.req_spans, slot_of, b.requests):
            h = sched.h_ints[si]
            ek_j0 = packing.np_words_to_bytes(
                np.ascontiguousarray(crypt_flat[4 * (off - 1):4 * off]))
            y_last = packing.np_words_to_bytes(
                np.ascontiguousarray(
                    y_flat[4 * (off + n - 1):4 * (off + n)]))
            tag = aead_gcm._finish_tag(
                gf.block_to_int(y_last.tobytes()), h, b"",
                len(req.aad), 16 * n, ek_j0)
            tags.append(tag)
            if b.mode == "gcm-open":
                ok = aead_ghash.np_tag_eq(tag, req.tag)
                if faults.fire("tag_mismatch"):
                    ok = False
                auth_ok.append(ok)
            else:
                auth_ok.append(True)
        return tags, auth_ok

    # -- introspection -----------------------------------------------------
    def occupancy_histogram(self) -> dict:
        """bucket rung -> {batches, mean occupancy} (the padding price)."""
        return {str(bucket): {
            "batches": h["batches"],
            "mean_occupancy": round(h["blocks"] / (h["batches"] * bucket), 4)}
            for bucket, h in sorted(self._occupancy.items())}

    def coalesce_stats(self) -> dict:
        """The rung-packer's efficiency: payload blocks over DISPATCHED
        blocks (rung padding priced in; empty key slots priced by
        ``slot_fill``). Fragmentation regressions — many tenants forced
        into many mostly-padding batches — show up here first
        (``serve.bench`` prints and gates it)."""
        return {
            "payload_blocks": self._payload_blocks,
            "dispatched_blocks": self._dispatched_blocks,
            "efficiency": (round(self._payload_blocks
                                 / self._dispatched_blocks, 4)
                           if self._dispatched_blocks else 0.0),
            "key_slots": self.config.key_slots,
            "slots_used": self._slots_used,
            "slot_fill": (round(self._slots_used / self._slot_capacity, 4)
                          if self._slot_capacity else 0.0),
        }

    def stats(self) -> dict:
        return {
            "engine": self.engine,
            "rungs": list(self.rungs),
            "coalesce": self.coalesce_stats(),
            "overlap": {
                "inflight_limit": self.inflight_limit,
                "max_inflight": self.max_inflight_seen,
            },
            "batches": self.batches,
            "batches_failed": self.batches_failed,
            "batches_timed_out": self.batches_timed_out,
            "occupancy": self.occupancy_histogram(),
            "queue": self.queue.stats(),
            "keycache": self.keycache.stats(),
            "lanes": (self.pool.stats() if self.pool is not None
                      else {"count": 0}),
            "compiles": {"warmup": self.warmup_compiles,
                         "steady": self.steady_compiles()},
            "transfers": (self.transfers.stats()
                          if self.transfers is not None else None),
            "sessions": (self.sessions.stats()
                         if self.sessions is not None else None),
        }
