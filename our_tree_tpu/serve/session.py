"""RC4 streaming sessions: per-session cipher state, batched keystream
pregeneration, bit-exact failover.

The paper's one original idea is the ``arc4_prep``/``arc4_crypt`` phase
split (reference arc4.c:72-112): a sequential keystream recurrence
decoupled from a data-parallel XOR. ``models/arc4.py`` reproduces it
offline; this module is the SERVED shape of the same split — the first
stateful mode the stack carries:

* **open** runs the 256-swap KSA on the host (tiny, inherently serial —
  exactly where the reference runs it) and registers a per-(tenant,
  session-id) ``{x, y, m[256], offset}`` state in a bounded LRU session
  store that rides the keycache's tenant-isolation discipline
  (serve/keycache.py): per-tenant maps, per-tenant capacity, one
  tenant's churn can never evict another's sessions.

* **prep rides ahead of demand.** A keystream prefetcher batches MANY
  sessions' sequential PRGA scans into one vmapped dispatch
  (``arc4.prep_batch_words`` via the lane seam, ``mode="rc4-prep"``):
  the batch axis is the parallel axis, the producer/consumer overlap of
  the pipelined-AES architecture (PAPERS.md 1501.01427). The dispatch
  shape is FIXED — ``prefetch_slots`` stacked states x ``quantum_bytes``
  each, idle slots padded — so the zero-recompile contract holds. Each
  session keeps a bounded keystream window ahead of its consumed offset
  (watermark refill), and a GLOBAL byte budget sheds typed
  (``serve_session_shed``) when windows would outgrow it — the
  reassembly-budget discipline of serve/transfer.py, applied to
  pregenerated keystream instead of reassembled ciphertext.

* **crypt coalesces across sessions.** Data chunks XOR against cached
  keystream via the ordinary queue -> rung-packer -> lane path
  (``mode="rc4"``): the XOR phase is key-oblivious, so chunks of
  different sessions pack into one batch exactly like multikey CTR —
  per-session slots, fixed-K stack, values change per batch, shapes
  never do.

* **failover is bit-exact by construction.** The PRGA carry is
  deterministic, and the engine checkpoints it at quantum boundaries as
  chunks are acked: a lane hang mid-prefetch replays the SAME carry
  arrays on a healthy lane (LanePool.dispatch's redispatch — counted as
  ``serve_session_replays``), an injected ``keystream_miss`` discards
  the cached window and regenerates from the last acked-checkpoint
  carry, and either way every byte a rider sees is byte-identical. The
  router pins session affinity one level up (route/proxy.py): all of a
  session's frames walk the same replica sequence the transfer
  chunk-spray uses, un-rotated, so steady-state chunks hit the warm
  state.

Sessions are a new axis the whole stack carries: admission
(serve/queue.py ``mode="rc4"``), batching (serve/batcher.py per-session
slots), caching (this store), failover (carry replay), drain
(``drain()`` force-closes open sessions at server stop and refuses new
opens — sessions drain like quarantine rows persist), metrics
(``serve_session_*``), and the router tier (session-pinned placement).

Fault seams (resilience/faults.py, all ``@session=<id>``-scopable):
``session_stall`` stalls the refill dispatch (backpressure, not a
wedge), ``keystream_miss`` discards a session's cached window (the
replay-from-carry rehearsal), ``session_evict`` force-evicts the LRU
idle row (the eviction rehearsal; busy rows are never evicted — a full
store of busy sessions refuses new opens typed instead).
"""

from __future__ import annotations

import asyncio
import collections
import os
import time

import numpy as np

from ..models import arc4
from ..obs import metrics, trace
from ..resilience import faults
from .queue import ERR_BAD_REQUEST, ERR_SHED, ERR_SHUTDOWN, Response

#: RC4 takes 1..256 key bytes (reference arc4.c:43-67) — NOT the AES
#: 16/24/32 set; queue admission skips its AES key check for mode rc4
#: and the store enforces this instead.
KEY_BYTES_MIN, KEY_BYTES_MAX = 1, 256


def _slow_s() -> float:
    """The injected stall cost (``OT_SLOW_S``, the one knob every
    simulated-latency fault shares — see faults.injected_slow)."""
    try:
        return max(float(os.environ.get("OT_SLOW_S", 0.05)), 0.0)
    except ValueError:
        return 0.05


class _Session:
    """One stream's state: the PRGA carry chain and the keystream window.

    Offsets are absolute byte positions in the session's keystream:
    ``win_start <= acked <= consumed <= gen``, where ``window`` holds
    bytes ``[win_start, gen)``, ``carries`` holds the PRGA state at
    every quantum boundary in ``[win_start, gen]`` (``carries[
    win_start]`` IS the acked checkpoint — the replay base), reserved
    chunks occupy ``[acked, consumed)`` and ``gen`` is the prefetch
    head (always a quantum multiple)."""

    __slots__ = ("tenant", "sid", "key_len", "consumed", "acked",
                 "win_start", "window", "gen", "carries", "pending",
                 "done", "chunks", "refills", "closed")

    def __init__(self, tenant: str, sid: int, key: bytes):
        self.tenant = tenant
        self.sid = int(sid)
        self.key_len = len(key)
        self.consumed = 0
        self.acked = 0
        self.win_start = 0
        self.window = bytearray()
        self.gen = 0
        self.carries: dict[int, tuple[int, int, np.ndarray]] = {
            0: (0, 0, arc4.key_schedule(key))}
        #: offset -> nbytes of reserved-not-yet-acked chunks. reserve()
        #: is strictly sequential per session, so insertion order IS
        #: offset order and the acked prefix advances with a peek.
        self.pending: collections.OrderedDict[int, int] = \
            collections.OrderedDict()
        self.done: set[int] = set()
        self.chunks = 0
        self.refills = 0
        self.closed = False

    @property
    def busy(self) -> bool:
        """Chunks in flight — a busy session is never evicted."""
        return bool(self.pending)

    def ahead(self) -> int:
        """Keystream bytes generated past the consumed offset."""
        return self.gen - self.consumed


class SessionManager:
    """The session store + keystream prefetcher (one per server).

    ``dispatch_prep`` is the server's lane seam: an async callable
    ``(m_words, xy_words, sampled) -> (out, replays)`` wrapping
    ``LanePool.dispatch(mode="rc4-prep")`` — ``out`` is the
    ``arc4.prep_batch_words`` result array, ``replays`` the count of
    failed-over lane attempts (each one a keystream replay from carry).
    Runs entirely on the server's event loop; the only await points are
    the prefetch dispatch and the injected stall.
    """

    def __init__(self, dispatch_prep, *, per_tenant: int = 16,
                 window_bytes: int = 65536, quantum_bytes: int = 4096,
                 prefetch_slots: int = 8, budget_bytes: int = 8 << 20,
                 clock=time.monotonic):
        if quantum_bytes % 4 or quantum_bytes <= 0:
            raise ValueError(f"quantum_bytes must be a positive multiple "
                             f"of 4, got {quantum_bytes}")
        self._dispatch = dispatch_prep
        self.per_tenant = int(per_tenant)
        self.window_bytes = max(int(window_bytes), quantum_bytes)
        self.quantum_bytes = int(quantum_bytes)
        self.prefetch_slots = int(prefetch_slots)
        self.budget_bytes = int(budget_bytes)
        #: refill below this lookahead (half a window: refill overlaps
        #: consumption without thrashing the dispatch seam)
        self.watermark = max(self.window_bytes // 2, self.quantum_bytes)
        self._clock = clock
        #: tenant -> OrderedDict[sid, _Session] (LRU order per tenant —
        #: the keycache isolation discipline: capacity and churn are
        #: per-tenant, cross-tenant eviction is impossible by shape)
        self._stores: dict[str, collections.OrderedDict] = {}
        self._lock = asyncio.Lock()
        self._bg: asyncio.Task | None = None
        self._draining = False
        self.held_bytes = 0
        self.opened = 0
        self.closed = 0
        self.evicted = 0
        self.refused = 0
        self.shed = 0
        self.chunks = 0
        self.hits = 0
        self.misses = 0
        self.replays = 0
        self.prefetches = 0
        self.stalls = 0
        self.injected_misses = 0
        self.drained_open = 0
        # Published once (the transfer-budget idiom): any registry
        # consumer can judge held_bytes against the budget without
        # reaching into this object.
        metrics.gauge("serve_session_budget_bytes", self.budget_bytes)

    # -- admission ----------------------------------------------------------
    def _refuse(self, code: str, why: str) -> Response:
        self.refused += 1
        metrics.counter("serve_session_refused", code=code)
        return Response(ok=False, error=code, detail=why)

    def _shed(self, reason: str, why: str) -> Response:
        self.shed += 1
        metrics.counter("serve_session_shed", reason=reason)
        return Response(ok=False, error=ERR_SHED, detail=why)

    def _get(self, tenant: str, sid) -> _Session | None:
        store = self._stores.get(tenant)
        if store is None:
            return None
        sess = store.get(int(sid))
        if sess is not None:
            store.move_to_end(int(sid))
        return sess

    def _release(self, sess: _Session) -> None:
        self.held_bytes -= len(sess.window)
        sess.window = bytearray()
        sess.closed = True

    def _evict_idle(self, tenant: str,
                    store: collections.OrderedDict) -> bool:
        """Evict the tenant's least-recently-used IDLE session; False
        when every row is busy (the mid-session refusal: a session with
        chunks in flight is never yanked from under its riders)."""
        for osid, osess in store.items():
            if not osess.busy:
                del store[osid]
                self._release(osess)
                self.evicted += 1
                metrics.counter("serve_session_evictions")
                trace.point("session-evict", tenant=tenant, session=osid)
                return True
        return False

    async def open(self, tenant: str, sid, key: bytes) -> Response:
        """Register a session: host KSA, store row, window prefill.

        The prefill (one full window of keystream, in fixed quanta)
        makes the steady state hit-dominated: by the time the first
        data chunk arrives its bytes are cached, and the watermark keeps
        the window ahead of consumption from then on."""
        if self._draining:
            return self._refuse(ERR_SHUTDOWN, "server is draining; "
                                              "no new sessions")
        try:
            sid = int(sid)
        except (TypeError, ValueError):
            return self._refuse(ERR_BAD_REQUEST, f"bad session id {sid!r}")
        if sid < 0:
            return self._refuse(ERR_BAD_REQUEST,
                                f"session id must be >= 0, got {sid}")
        key = bytes(key)
        if not (KEY_BYTES_MIN <= len(key) <= KEY_BYTES_MAX):
            return self._refuse(ERR_BAD_REQUEST, (
                f"rc4 key must be {KEY_BYTES_MIN}..{KEY_BYTES_MAX} bytes, "
                f"got {len(key)}"))
        store = self._stores.setdefault(tenant, collections.OrderedDict())
        if sid in store:
            return self._refuse(ERR_BAD_REQUEST,
                                f"session {sid} already open")
        if faults.fire_session("session_evict", sid):
            # The eviction rehearsal: force the LRU-idle path even
            # below capacity (no-op when every row is busy — busy rows
            # keep their never-evicted guarantee under injection too).
            self._evict_idle(tenant, store)
        if len(store) >= self.per_tenant and not self._evict_idle(
                tenant, store):
            return self._shed("sessions", (
                f"tenant {tenant!r} at capacity ({self.per_tenant} "
                f"sessions, all with chunks in flight); eviction "
                f"mid-session is refused — retry or close a session"))
        sess = _Session(tenant, sid, key)
        store[sid] = sess
        self.opened += 1
        metrics.counter("serve_session_open")
        sampled = trace.sample()
        with trace.maybe_span(sampled, "session-open", tenant=tenant,
                              session=sid):
            r = await self._ensure(sess, self.window_bytes, sampled)
        if isinstance(r, Response):
            # Prefill shed (global keystream budget): the open itself
            # is refused — a session the prefetcher can't feed would
            # miss on every chunk.
            if store.get(sid) is sess:
                del store[sid]
            self._release(sess)
            return r
        return Response(ok=True, detail=f"session {sid} open")

    # -- the keystream window -----------------------------------------------
    async def reserve(self, tenant: str, sid, nbytes: int):
        """Hand a data chunk its keystream slice ``[consumed,
        consumed+nbytes)`` and advance the reserved offset. Returns
        ``(keystream uint8[nbytes], offset)`` or a typed error
        Response. A slice served entirely from the cached window is a
        prefetch HIT; anything that must await a dispatch is a miss —
        the hit rate is the artifact gate (SESSION_rNN.json)."""
        sess = self._get(tenant, sid)
        if sess is None:
            return self._refuse(ERR_BAD_REQUEST,
                                f"unknown session {sid} (never opened, "
                                f"closed, or evicted)")
        nbytes = int(nbytes)
        if nbytes <= 0:
            return self._refuse(ERR_BAD_REQUEST,
                                f"bad chunk size {nbytes}")
        if faults.fire_session("keystream_miss", sess.sid):
            self._discard_window(sess)
        need = sess.consumed + nbytes
        if sess.gen >= need:
            self.hits += 1
            metrics.counter("serve_session_prefetch", outcome="hit")
        else:
            self.misses += 1
            metrics.counter("serve_session_prefetch", outcome="miss")
            r = await self._ensure(sess, need, trace.sample())
            if isinstance(r, Response):
                return r
        off = sess.consumed
        lo = off - sess.win_start
        ks = np.frombuffer(bytes(sess.window[lo:lo + nbytes]), np.uint8)
        sess.pending[off] = nbytes
        sess.consumed = off + nbytes
        sess.chunks += 1
        self.chunks += 1
        metrics.counter("serve_session_chunks")
        if sess.ahead() < self.watermark and not self._draining:
            self._kick()
        return ks, off

    def ack(self, tenant: str, sid, offset: int, nbytes: int) -> None:
        """Chunk answered: advance the contiguous acked prefix and slide
        the checkpoint forward to the last quantum boundary at or below
        it — bytes and carries behind the checkpoint are released (the
        per-acked-chunk checkpoint the bit-exact failover replays
        from). Failed chunks ack too: their error is final (the wire
        answer is typed, never retried), so their bytes must not pin
        the window forever."""
        sess = self._get(tenant, sid)
        if sess is None or sess.closed:
            return
        sess.done.add(int(offset))
        while sess.pending:
            off0, n0 = next(iter(sess.pending.items()))
            if off0 not in sess.done:
                break
            sess.pending.popitem(last=False)
            sess.done.discard(off0)
            sess.acked = off0 + n0
        base = min((sess.acked // self.quantum_bytes) * self.quantum_bytes,
                   sess.gen)
        if base > sess.win_start:
            cut = base - sess.win_start
            del sess.window[:cut]
            self.held_bytes -= cut
            for b in [b for b in sess.carries if b < base]:
                del sess.carries[b]
            sess.win_start = base

    def _discard_window(self, sess: _Session) -> None:
        """The ``keystream_miss`` injection: the cached window is gone
        (cold cache stand-in); keep only the acked-checkpoint carry.
        The next reserve regenerates forward from it in fixed quanta —
        deterministic PRGA, so the regenerated bytes are byte-identical
        to the discarded ones: one counted replay from carry."""
        self.held_bytes -= len(sess.window)
        sess.window = bytearray()
        sess.carries = {sess.win_start: sess.carries[sess.win_start]}
        sess.gen = sess.win_start
        self.injected_misses += 1
        self.replays += 1
        metrics.counter("serve_session_replays", kind="injected-miss")
        trace.point("keystream-miss", tenant=sess.tenant, session=sess.sid)

    async def _ensure(self, sess: _Session, min_gen: int, sampled: bool):
        """Refill until ``sess.gen >= min_gen`` (absolute offset), in
        fixed quanta. Returns None on success or the typed shed
        Response when the global budget can't cover this session."""
        rounds = 0
        limit = (min_gen - sess.gen) // self.quantum_bytes + 2
        while sess.gen < min_gen:
            if sess.closed:
                return self._refuse(ERR_BAD_REQUEST,
                                    f"session {sess.sid} closed mid-refill")
            rounds += 1
            if rounds > limit:  # pragma: no cover - arithmetic backstop
                return self._shed("keystream", "refill made no progress")
            r = await self._refill_round(sess, sampled)
            if isinstance(r, Response):
                return r
        return None

    def _kick(self) -> None:
        """Arm the background watermark refill (one task at a time —
        the refill lock serializes dispatches anyway, a task herd would
        only churn the loop)."""
        if self._bg is None or self._bg.done():
            self._bg = asyncio.ensure_future(self._bg_refill())

    async def _bg_refill(self) -> None:
        while not self._draining:
            low = any(
                not s.closed and s.ahead() < self.watermark
                for store in self._stores.values() for s in store.values())
            if not low:
                return
            r = await self._refill_round(None, trace.sample())
            if isinstance(r, Response) or r == 0:
                return  # budget-pinned or nothing refillable: stop, the
                #         next reserve re-kicks (no spin at the budget)

    async def _refill_round(self, urgent: _Session | None, sampled: bool):
        """ONE batched prefetch: stack up to ``prefetch_slots`` sessions
        below watermark (``urgent`` first — the session a reserve is
        awaiting), one fixed-shape dispatch, distribute carries and
        windows. Returns the refilled count, or the typed shed Response
        when ``urgent`` itself can't fit the global budget."""
        async with self._lock:
            cands: list[_Session] = []
            if urgent is not None and not urgent.closed:
                cands.append(urgent)
            for store in self._stores.values():
                for s in store.values():
                    if len(cands) >= self.prefetch_slots:
                        break
                    if s is urgent or s.closed:
                        continue
                    if s.ahead() < self.watermark:
                        cands.append(s)
            fit: list[_Session] = []
            projected = self.held_bytes
            for s in cands:
                if projected + self.quantum_bytes > self.budget_bytes:
                    if s is urgent:
                        return self._shed("keystream", (
                            f"keystream budget pinned ({projected} of "
                            f"{self.budget_bytes} bytes held across "
                            f"sessions); chunk sheds until acks release "
                            f"window bytes"))
                    continue
                fit.append(s)
                projected += self.quantum_bytes
            if not fit:
                return 0
            for s in fit:
                if faults.fire_session("session_stall", s.sid):
                    # An awaitable stall, never a wedge: the lock holds
                    # (refills queue behind it) but the server loop and
                    # the XOR dispatch path keep draining under it.
                    self.stalls += 1
                    await asyncio.sleep(_slow_s())
                    break
            S, L = self.prefetch_slots, self.quantum_bytes
            m_words = np.zeros(S * 256, np.uint32)
            xy_words = np.zeros(2 * S, np.uint32)
            for i, s in enumerate(fit):
                x, y, m = s.carries[s.gen]
                m_words[i * 256:(i + 1) * 256] = m.astype(np.uint32)
                xy_words[i] = x
                xy_words[S + i] = y
            with trace.maybe_span(sampled, "keystream-prefetch",
                                  sessions=len(fit), quantum=L):
                out, replays = await self._dispatch(m_words, xy_words,
                                                    sampled)
            self.prefetches += 1
            if replays:
                self.replays += int(replays)
                metrics.counter("serve_session_replays", n=int(replays),
                                kind="redispatch")
            for i, s in enumerate(fit):
                row = out[i]
                s.carries[s.gen + L] = (int(row[0]) & 0xFF,
                                        int(row[1]) & 0xFF,
                                        row[2:258].astype(np.uint8))
                s.window += row[258:].astype("<u4").tobytes()
                s.gen += L
                s.refills += 1
                self.held_bytes += L
            return len(fit)

    # -- close / drain ------------------------------------------------------
    async def close(self, tenant: str, sid) -> Response:
        store = self._stores.get(tenant)
        sess = store.get(int(sid)) if store else None
        if sess is None:
            return self._refuse(ERR_BAD_REQUEST, f"unknown session {sid}")
        if sess.busy:
            return self._refuse(ERR_BAD_REQUEST, (
                f"session {sid} has {len(sess.pending)} chunk(s) in "
                f"flight; close after their answers"))
        del store[int(sid)]
        final = sess.consumed
        self._release(sess)
        self.closed += 1
        metrics.counter("serve_session_close")
        return Response(ok=True, detail=f"session {sid} closed at "
                                        f"offset {final}")

    async def drain(self) -> None:
        """Server stop: refuse new opens, stop the refill task, and
        force-close whatever is still open (counted — the drain story
        for state that would otherwise be orphaned; the quarantine-row
        analogue for sessions)."""
        self._draining = True
        t, self._bg = self._bg, None
        if t is not None:
            t.cancel()
            await asyncio.gather(t, return_exceptions=True)
        for store in self._stores.values():
            for sess in list(store.values()):
                self.drained_open += 1
                self._release(sess)
            store.clear()
        if self.drained_open:
            metrics.counter("serve_session_drained", n=self.drained_open)

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        served = self.hits + self.misses
        return {
            "open": sum(len(s) for s in self._stores.values()),
            "opened": self.opened,
            "closed": self.closed,
            "evicted": self.evicted,
            "refused": self.refused,
            "shed": self.shed,
            "chunks": self.chunks,
            "held_bytes": self.held_bytes,
            "budget_bytes": self.budget_bytes,
            "window_bytes": self.window_bytes,
            "quantum_bytes": self.quantum_bytes,
            "prefetch_slots": self.prefetch_slots,
            "drained_open": self.drained_open,
            "prefetch": {
                "dispatches": self.prefetches,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / served) if served else None,
                "replays": self.replays,
                "stalls": self.stalls,
                "injected_misses": self.injected_misses,
            },
        }
