"""The serve wire protocol: framed CTR requests over a byte stream.

One frame = one JSON header line (UTF-8, ``\\n``-terminated) followed by
``header["len"]`` raw payload bytes. The header carries the small typed
fields (tenant, hex key/nonce, error codes); the payload rides raw so a
64 KiB request costs no base64 inflation and no JSON string scanning.
Both directions use the same shape:

request::

    {"t": "<tenant>", "k": "<key hex>", "n": "<nonce hex>",
     "len": <payload bytes>, "deadline_s": <float|null>,
     "sm": <bool|absent>, "ps": "<parent span id|absent>",
     "pr": <0|absent>, "lg": <true|absent>,
     "m": "<mode|absent>", "iv": "<iv hex|absent>",
     "a": "<aad hex|absent>", "tg": "<tag hex|absent>"}\\n
    <len raw bytes>

response::

    {"ok": true, "len": <n>, "batch": "<label|null>", "tr": <epoch µs>,
     "ts": <epoch µs>, "pid": <int>, "lg": {<ledger>|absent},
     "tg": "<tag hex|absent>"}\\n<raw>
    {"ok": false, "len": 0, "error": "<code>", "detail": "..."}\\n

The codes are ``serve.queue``'s closed ERR_* set — the router
dispatches on them (a ``shed`` retries the replica ring with backoff, a
``shutdown`` marks the backend draining, everything else answers the
rider as-is), so the wire adds NO new failure vocabulary; ``auth-failed``
(a GCM tag mismatch) rides it as a plain per-request error.

The AEAD fields are the served-mode seam (docs/SERVING.md, AEAD
section): ``m`` selects the mode (``ctr`` when absent — every
pre-AEAD frame is still a valid frame), ``iv`` carries the GCM 96-bit
/ CBC 128-bit IV, ``a`` the GCM additional authenticated data, and
``tg`` the tag — request-side the tag to VERIFY (``gcm-open``),
response-side the tag ``gcm`` sealing produced. Hex for all three:
they are small (12-16 bytes, AAD typically header-sized) next to the
raw-riding payload.

The observability fields are the CROSS-PROCESS propagation seam
(docs/OBSERVABILITY.md, fleet tracing): ``sm`` carries the router's
admission-time head-sampling decision (one coin flip governs the whole
chain), ``ps`` the router's per-request span id (the backend's
``request-queued`` span chains under it, joining the fleet trace),
``pr`` a low-priority marker, and ``lg`` requests the per-request
time-attribution ledger, which rides back in the response's ``lg``.
Every response also stamps ``tr``/``ts`` (the backend's epoch-µs clock
at frame receipt and at reply — the NTP-style pair the router's
clock-skew estimate cancels processing time with) and ``pid`` — the
wire handshake the Perfetto timeline alignment is built from. All
optional: a bare header is a plain local request, exactly as before.

Frames carrying a ``tx`` field belong to the resumable chunked-transfer
sub-protocol (oversized payloads, serve/transfer.py): ``begin`` /
``begin-ack`` / ``chunk`` / ``out`` / ``done`` exchanged on one
connection, each an ordinary header+payload frame — the framing layer
below is unchanged, and every per-frame bound (MAX_HEADER, ``max_len``)
still applies because a transfer's chunks are at most one ladder rung
each. ``serve/worker.py`` documents the exchange.

Frames carrying an ``ss`` field belong to the stateful-session
sub-protocol (mode ``rc4``, serve/session.py): ``open`` / ``data`` /
``close``, each its OWN one-frame request/response exchange — no
multi-frame state rides the connection, so one connection interleaves
many sessions' chunks with ordinary requests (the server coalesces
concurrent sessions' chunks into shared dispatches). The session state
itself lives server-side, keyed ``(tenant, sid)``; the router pins each
session's frames to the backend that opened it (route/proxy.py).
``serve/worker.py`` documents the frames.

Used by ``serve/worker.py`` (the backend process's TCP frontend — reads
requests, feeds ``Server.submit``, writes responses) and by
``route/proxy.py`` (the router's backend client — the one
backend-contact seam, otlint's ``route-backend-seam`` rule). Bounded on
both sides: a header line over ``MAX_HEADER`` bytes or a payload over
the caller's ``max_len`` is a protocol error, refused before any
allocation trusts the peer. stdlib + asyncio only — no numpy, no jax:
the frame layer must be importable by the device-free router.
"""

from __future__ import annotations

import json

#: Header line ceiling: tenant + hex key/nonce + codes fit in well under
#: 1 KiB; anything bigger is a corrupt or hostile peer.
MAX_HEADER = 4096

#: Default payload ceiling (bytes): the largest default bucket rung
#: (4096 blocks) is 64 KiB; one frame never needs more than a small
#: multiple of it. Callers with bigger ladders pass their own.
MAX_PAYLOAD = 1 << 22


class WireError(RuntimeError):
    """A malformed or oversized frame (protocol violation, not a
    request-level error: the connection is not trustworthy past it)."""


class FrameTooLarge(WireError):
    """A frame whose PARSEABLE header declares a payload over the
    configured max — refused before any allocation trusts the peer.

    Split out from ``WireError`` because this shape is recoverable: the
    header parsed, so the stream is still framed — the frontend can
    answer a TYPED error frame (``"too-large"`` with the declared size
    in the detail) instead of resetting the connection, and — when the
    declared length is modest enough to drain (``skip_payload``) — even
    keep serving later frames on the same connection. A torn or
    unparseable header stays a plain ``WireError``: there is no frame
    boundary left to trust."""

    def __init__(self, header: dict, declared: int, max_len: int):
        self.header = header
        self.declared = int(declared)
        self.max_len = int(max_len)
        super().__init__(
            f"frame payload {declared} bytes outside [0, {max_len}]")


async def skip_payload(reader, n: int, chunk: int = 1 << 16) -> bool:
    """Drain ``n`` declared payload bytes in bounded slices (never one
    ``n``-sized allocation — ``n`` is the untrusted quantity). True when
    the stream resynced at the next frame boundary; False on EOF."""
    left = int(n)
    while left > 0:
        piece = await reader.read(min(left, chunk))
        if not piece:
            return False
        left -= len(piece)
    return True


def encode_frame(header: dict, payload: bytes = b"") -> bytes:
    """One frame as bytes; stamps ``len`` from the payload."""
    h = dict(header)
    h["len"] = len(payload)
    return (json.dumps(h, separators=(",", ":")).encode("utf-8")
            + b"\n" + payload)


async def read_frame(reader, max_len: int = MAX_PAYLOAD):
    """(header dict, payload bytes) from an asyncio StreamReader, or
    None on clean EOF at a frame boundary. Raises WireError on a torn,
    oversized, or unparseable frame."""
    try:
        line = await reader.readuntil(b"\n")
    except EOFError:
        return None
    except Exception as e:  # IncompleteReadError (mid-line EOF), overflow
        # asyncio raises IncompleteReadError with .partial on EOF; empty
        # partial is a clean close between frames.
        partial = getattr(e, "partial", None)
        if partial == b"":
            return None
        raise WireError(f"torn frame header: {type(e).__name__}") from e
    if len(line) > MAX_HEADER:
        raise WireError(f"header line {len(line)} bytes > {MAX_HEADER}")
    try:
        header = json.loads(line)
    except ValueError as e:
        raise WireError(f"unparseable frame header: {e}") from e
    if not isinstance(header, dict):
        raise WireError("frame header is not a JSON object")
    try:
        n = int(header.get("len", 0))
    except (TypeError, ValueError) as e:
        raise WireError("frame len is not an integer") from e
    if n < 0 or n > max_len:
        # Validated against the configured max BEFORE any allocation:
        # the declared length is attacker-controlled input, and the
        # typed subclass carries what a frontend needs to refuse it
        # politely (serve/worker.py, route/fleet.py).
        raise FrameTooLarge(header, n, max_len)
    payload = b""
    if n:
        try:
            payload = await reader.readexactly(n)
        except Exception as e:
            raise WireError("torn frame payload") from e
    return header, payload
