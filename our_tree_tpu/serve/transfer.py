"""Resumable chunked transfers: oversized payloads as ladder riders.

The serve ladder tops out at ``max_bucket_blocks`` (the 4096-block rung
by default) and admission refuses anything larger (``"too-large"``) —
a hard availability gap for the large-file/streaming scenario the
ROADMAP names. The paper's own ``length/num_threads`` contiguous-chunk
decomposition makes CTR embarrassingly parallel AND bit-exactly
recomposable: block ``offset + j`` of the whole payload and block ``j``
of a chunk whose counter starts at ``nonce + offset`` produce the same
keystream byte-for-byte. This module turns that identity into an
admission path:

* **Decomposition** (``plan``): an oversized payload becomes
  ladder-rung chunks. CTR chunks carry per-chunk counter offsets (the
  full 128-bit big-endian add, matching
  ``utils.packing.np_ctr_le_blocks`` — a counter wrap landing exactly
  on a chunk boundary is a pinned KAT, tests/test_transfer.py). CBC
  *decrypt* chunks chain IVs from the previous chunk's last ciphertext
  block — known up front from the input, so chunks stay independently
  dispatchable. GCM is refused with a typed reason
  (``"transfer-unsupported"``): its tag is a GHASH over the WHOLE
  message and this engine does not implement host-side GHASH
  continuation across chunk tags — refusing loudly beats a tag that
  only verifies by luck.
* **Streaming**: chunks ride the existing queue/batcher/lane (or
  router) machinery as ordinary riders — each inherits the bit-exact
  lane/backend redispatch story, so a lane hang, worker SIGKILL, or
  router failover mid-transfer costs exactly the in-flight chunks.
* **Reassembly**: strictly in order under a bounded buffer.
  Out-of-order completions are HELD (``held_bytes``); when the byte
  budget is crossed, NEW transfers shed with a typed error
  (``serve_transfer_shed{reason=reassembly}``) while admitted chunks
  keep draining — a slow consumer backpressures admission, never the
  dispatch loop.
* **Resumability**: a journal-backed ledger (JSONL, fsync'd appends,
  torn-tail tolerant — the ``resilience/journal.py`` durability idiom)
  records each transfer's id, parameter fingerprint, and acked-chunk
  bitmap. A reconnecting client presents its resume token: acked
  chunks are never recomputed or re-emitted, only unacked chunks are
  re-sent, and the spliced output is byte-identical to an
  uninterrupted run (CTR/CBC chunk outputs depend only on key + chunk
  params, never on which attempt computed them).

Fault points (``resilience/faults.py``, ``@chunk=<i>`` scoped):
``chunk_lost`` discards one completed chunk before reassembly (forcing
a redispatch), ``reassembly_stall`` stalls the in-order emit seam (the
slow consumer), ``transfer_abort`` kills the exchange mid-flight with
the resume token in the typed error.

Observability: a root ``transfer`` span chains every ``transfer-chunk``
span (and, through ``parent=``, every chunk's queue/dispatch spans)
under one id; ``serve_transfer_*`` counters and the
``serve_stage_us{stage="reassembly"}`` histogram carry the exact
counts; ``serve_reassembly_held_bytes`` gauges the buffer.

asyncio + numpy + resilience/obs only — no jax and no engine imports:
the module is testable without a backend, and the router (a
device-free process) imports it as freely as the server.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import time
import uuid
from dataclasses import dataclass

import numpy as np

from ..obs import metrics, trace
from ..resilience import faults
from ..resilience.policy import Budget
from .queue import (ERR_BAD_REQUEST, ERR_DEADLINE, ERR_SHED,
                    ERR_TOO_LARGE, ERR_TRANSFER_ABORT, ERR_TRANSFER_MODE,
                    Response)

#: Modes the chunk decomposition serves bit-exactly. GCM (both
#: directions) is NOT here: see the module docstring — oversized GCM is
#: a typed refusal, never a silent downgrade.
TRANSFER_MODES = ("ctr", "cbc")

LEDGER_KIND = "ot-transfer-ledger"
LEDGER_VERSION = 1


def _slow_s() -> float:
    """The injected stall cost (``OT_SLOW_S``, faults.injected_slow's
    knob — one knob for every simulated-latency fault)."""
    try:
        return max(float(os.environ.get("OT_SLOW_S", 0.05)), 0.0)
    except ValueError:
        return 0.05


def chunk_nonce(nonce: bytes, start_block: int) -> bytes:
    """The CTR counter start of the chunk whose first block is
    ``start_block`` of the whole payload: the full 128-bit big-endian
    add (mod 2^128), the same ripple-carry semantics as
    ``utils.packing.np_ctr_le_blocks`` — so chunked and whole-payload
    keystreams agree even when the counter wraps mid-transfer."""
    if len(nonce) != 16:
        raise ValueError(f"nonce must be 16 bytes, got {len(nonce)}")
    n = (int.from_bytes(nonce, "big") + int(start_block)) % (1 << 128)
    return n.to_bytes(16, "big")


@dataclass(frozen=True)
class ChunkSpec:
    """One planned chunk: where it lives in the transfer and the
    derived per-chunk cipher parameters."""

    index: int
    offset: int    #: byte offset into the transfer payload
    nbytes: int
    nonce: bytes = b""   #: ctr: derived 16-byte counter start
    iv: bytes = b""      #: cbc: derived 16-byte IV (previous ct block)


def plan(mode: str, chunk_blocks: int, total_bytes: int,
         nonce: bytes = b"", iv: bytes = b"", payload=None,
         tails: dict | None = None) -> list[ChunkSpec]:
    """Decompose a transfer into ladder-rung chunks.

    ``payload`` (ctr: unused; cbc: the ciphertext, for IV chaining) may
    be sparse on a RESUME — ``tails`` maps chunk index -> that chunk's
    last 16 input bytes (the ledger remembers them at ack time), so a
    chunk whose predecessor was acked in a previous connection still
    plans its IV without the predecessor's bytes.
    """
    if total_bytes <= 0 or total_bytes % 16:
        raise ValueError("payload must be a nonzero multiple of 16 bytes")
    if chunk_blocks <= 0:
        raise ValueError(f"chunk_blocks must be positive, got {chunk_blocks}")
    step = int(chunk_blocks) * 16
    specs = []
    tails = tails or {}
    for i, off in enumerate(range(0, total_bytes, step)):
        n = min(step, total_bytes - off)
        if mode == "ctr":
            specs.append(ChunkSpec(i, off, n,
                                   nonce=chunk_nonce(nonce, off // 16)))
        elif mode == "cbc":
            if off == 0:
                civ = bytes(iv)
            elif i - 1 in tails:
                civ = bytes(tails[i - 1])
            elif payload is not None:
                civ = bytes(bytearray(
                    np.asarray(payload, dtype=np.uint8)[off - 16:off]))
            else:
                raise ValueError(
                    f"cbc chunk {i} needs the previous chunk's tail "
                    "(payload slice or ledger tail)")
            if len(civ) != 16:
                raise ValueError(f"cbc chunk {i} derived a {len(civ)}-byte IV")
            specs.append(ChunkSpec(i, off, n, iv=civ))
        else:
            raise ValueError(f"mode {mode!r} is not chunkable "
                             f"(transfer modes: {TRANSFER_MODES})")
    return specs


def fingerprint(mode: str, key: bytes, nonce: bytes, iv: bytes,
                total_bytes: int, chunk_blocks: int) -> str:
    """The transfer-parameter fingerprint the ledger pins a resume token
    to: same token + different params means the splice would NOT be
    byte-identical, so the resume is refused (a fresh transfer starts).
    The key rides as a digest — the ledger file never holds key bytes.
    The payload itself is NOT fingerprinted: a resuming client presents
    only the unacked chunks, and re-presenting its own data faithfully
    is its job (the server cannot check bytes it never re-reads)."""
    h = hashlib.sha256()
    h.update(mode.encode())
    h.update(hashlib.sha256(bytes(key)).digest())
    h.update(bytes(nonce))
    h.update(bytes(iv))
    h.update(int(total_bytes).to_bytes(8, "big"))
    h.update(int(chunk_blocks).to_bytes(8, "big"))
    return h.hexdigest()[:32]


class TransferLedger:
    """The journal-backed acked-chunk ledger (transfer id -> fingerprint
    + acked bitmap + CBC tails). Same durability idiom as
    ``resilience/journal.py``: JSONL header + rows, every append flushed
    AND fsync'd (an ack must survive the process's own SIGKILL — it is
    the resume contract), torn tail truncated on load. ``path=None`` is
    the in-memory variant (same API, no durability) for embedders that
    only want transparent decomposition."""

    def __init__(self, path: str | None = None, max_live: int = 4096,
                 compact_min_rows: int = 1024):
        self.path = path
        self.max_live = int(max_live)
        self.compact_min_rows = int(compact_min_rows)
        self._fh = None
        #: journal op rows on disk (begin/ack/done) — the compaction
        #: trigger compares this against the rows the live set needs
        self._rows = 0
        self.compactions = 0
        #: tid -> {"fp", "chunks", "acked": set[int], "tails": {i: bytes}}
        self._live: dict[str, dict] = {}
        if path is not None:
            self._load()
            fresh = not os.path.exists(path) or os.path.getsize(path) == 0
            self._fh = open(path, "a", encoding="utf-8")
            if fresh:
                self._append({"kind": LEDGER_KIND, "v": LEDGER_VERSION,
                              "created_us": trace.now_us()})

    # -- persistence -------------------------------------------------------
    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        good = []
        torn = False
        with open(self.path, encoding="utf-8") as fh:
            for line in fh:
                try:
                    row = json.loads(line)
                except ValueError:
                    torn = True  # torn tail (or garbage): drop from here
                    break
                good.append(line)
                if "op" in row:
                    self._rows += 1
                self._replay(row)
        if torn:
            # Truncate the torn tail (the journal.py idiom): appending
            # after a partial line would weld two rows into garbage.
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.writelines(good)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)

    def _replay(self, row: dict) -> None:
        op = row.get("op")
        tid = row.get("tid")
        if op == "begin":
            st = self._live.get(tid)
            if st is None or st["fp"] != row.get("fp"):
                self._live[tid] = {"fp": row.get("fp"),
                                   "chunks": int(row.get("chunks", 0)),
                                   "acked": set(), "tails": {}}
            # max_live holds across restarts too: a journal written
            # under a larger bound (or missing eviction rows from an
            # older version) must not replay past the configured cap.
            while len(self._live) > self.max_live:
                self._live.pop(next(iter(self._live)))
        elif op == "ack" and tid in self._live:
            st = self._live[tid]
            st["acked"].add(int(row["i"]))
            tail = row.get("tail")
            if tail:
                st["tails"][int(row["i"])] = bytes.fromhex(tail)
        elif op == "done":
            self._live.pop(tid, None)

    # The resumability contract: a begin/ack row must be on disk before
    # the chunk is acknowledged to the client, so the fsync is
    # deliberately inline on the transfer path (PR 17's chunk-level
    # failover depends on never acking an undurable chunk).
    # ot-san: absorb=journal-fsync-durability
    def _append(self, row: dict) -> None:
        if self._fh is None:
            return
        self._fh.write(json.dumps(row, separators=(",", ":")) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())
        if "op" in row:
            self._rows += 1
            self._maybe_compact()

    def _state_rows(self) -> int:
        """Rows a compacted journal would hold (one begin + one ack per
        acked chunk, per live transfer)."""
        return sum(1 + len(st["acked"]) for st in self._live.values())

    def _maybe_compact(self) -> None:
        """Rewrite the journal from the live set once dead rows (done'd
        and evicted transfers, superseded begins) dominate: without
        this, a long-lived ledger grows one row per ack FOREVER. The
        floor keeps small journals append-only (compaction is an fsync'd
        whole-file rewrite — not worth it under ~1k rows)."""
        if self._fh is None:
            return
        if self._rows <= max(self.compact_min_rows,
                             4 * (self._state_rows() + 1)):
            return
        rows = [{"kind": LEDGER_KIND, "v": LEDGER_VERSION,
                 "created_us": trace.now_us()}]
        for tid, st in self._live.items():
            rows.append({"op": "begin", "tid": tid, "fp": st["fp"],
                         "chunks": int(st["chunks"])})
            for i in sorted(st["acked"]):
                r = {"op": "ack", "tid": tid, "i": int(i)}
                tail = st["tails"].get(i)
                if tail:
                    r["tail"] = bytes(tail).hex()
                rows.append(r)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            for r in rows:
                fh.write(json.dumps(r, separators=(",", ":")) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._rows = len(rows) - 1  # header row doesn't count
        self.compactions += 1

    # -- the transfer engine's API -----------------------------------------
    def begin(self, tid: str, fp: str, chunks: int) -> set[int]:
        """Open (or re-open) a transfer; returns the already-acked chunk
        set — empty for a fresh transfer OR when the presented token's
        fingerprint does not match (mismatched params restart from
        scratch rather than splicing incompatible outputs)."""
        st = self._live.get(tid)
        if st is not None and st["fp"] == fp:
            return set(st["acked"])
        if len(self._live) >= self.max_live:
            # Bounded: evict the oldest live transfer (dict order =
            # insertion order) — an abandoned token from last week must
            # not pin ledger memory forever. The eviction is JOURNALED
            # as a done row: a restart must not replay the evicted
            # transfer back into the live set.
            old = next(iter(self._live))
            self._live.pop(old)
            self._append({"op": "done", "tid": old, "ok": False,
                          "evicted": True})
        self._live[tid] = {"fp": fp, "chunks": int(chunks),
                           "acked": set(), "tails": {}}
        self._append({"op": "begin", "tid": tid, "fp": fp,
                      "chunks": int(chunks)})
        return set()

    def ack(self, tid: str, i: int, tail: bytes = b"") -> None:
        st = self._live.get(tid)
        if st is None:
            return
        st["acked"].add(int(i))
        if tail:
            st["tails"][int(i)] = bytes(tail)
        row = {"op": "ack", "tid": tid, "i": int(i)}
        if tail:
            row["tail"] = bytes(tail).hex()
        self._append(row)

    def acked(self, tid: str) -> set[int]:
        st = self._live.get(tid)
        return set(st["acked"]) if st is not None else set()

    def tails(self, tid: str) -> dict:
        st = self._live.get(tid)
        return dict(st["tails"]) if st is not None else {}

    def done(self, tid: str, ok: bool = True) -> None:
        if tid in self._live:
            self._live.pop(tid, None)
            self._append({"op": "done", "tid": tid, "ok": bool(ok)})

    def live(self) -> int:
        return len(self._live)

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            finally:
                self._fh = None


class TransferManager:
    """The transfer engine: plans, streams, reassembles, and resumes.

    Parameterized by ``submit_chunk`` — ``async (tenant, key, spec,
    payload_slice, *, mode, deadline_s, sampled, parent) -> Response`` —
    so the SAME engine drives the server's queue admission
    (serve/server.py wraps ``RequestQueue.submit``) and the router's
    ring placement (route/proxy.py wraps ``_route``, spraying chunks
    across backends). Everything chunk-agnostic about robustness lives
    here once: the in-flight window, the per-transfer ``Budget``, the
    bounded reassembly buffer, the fault seams, the ledger, the spans.
    """

    def __init__(self, submit_chunk, *, chunk_blocks: int,
                 max_transfers: int = 8, window: int = 8,
                 reassembly_budget_bytes: int = 64 << 20,
                 max_payload_bytes: int = 1 << 30,
                 deadline_s: float = 300.0, retry_backoff_s: float = 0.05,
                 ledger: TransferLedger | None = None,
                 clock=time.monotonic):
        self._submit = submit_chunk
        self.chunk_blocks = int(chunk_blocks)
        self.max_transfers = int(max_transfers)
        self.window = int(window)
        self.reassembly_budget_bytes = int(reassembly_budget_bytes)
        #: the per-transfer size ceiling — the frontends check it
        #: against a client-DECLARED total before allocating anything
        self.max_payload_bytes = int(max_payload_bytes)
        self.deadline_s = float(deadline_s)
        self.retry_backoff_s = float(retry_backoff_s)
        self.ledger = ledger if ledger is not None else TransferLedger()
        self._clock = clock
        self.active = 0
        self.held_bytes = 0
        self.held_peak = 0
        # -- counters (mirrored as serve_transfer_* metrics) --
        self.started = 0
        self.completed = 0
        self.resumed = 0
        self.aborted = 0
        self.shed = 0
        self.refused = 0
        self.chunks_sent = 0
        self.chunks_skipped = 0
        self.chunk_redispatches = 0
        self.bytes_out = 0
        # Published once as a gauge so any registry consumer — the
        # pulse reassembly-pressure rule, live or replaying snapshots
        # offline — can judge held_bytes against the budget without
        # reaching into this object.
        metrics.gauge("serve_transfer_budget_bytes",
                      self.reassembly_budget_bytes)

    # -- admission ----------------------------------------------------------
    def _refuse(self, code: str, why: str, mode: str) -> Response:
        self.refused += 1
        metrics.counter("serve_transfer_refused", code=code)
        return Response(ok=False, error=code, detail=why)

    def _shed(self, reason: str, why: str) -> Response:
        self.shed += 1
        metrics.counter("serve_transfer_shed", reason=reason)
        return Response(ok=False, error=ERR_SHED, detail=why)

    async def run(self, tenant: str, key: bytes, nonce: bytes, payload,
                  *, mode: str = "ctr", iv: bytes = b"",
                  deadline_s: float | None = None,
                  sampled: bool | None = None, parent: str | None = None,
                  resume_token: str | None = None, tails: dict | None = None,
                  on_chunk=None) -> Response:
        """Serve one oversized payload as a chunked transfer.

        ``on_chunk`` (optional, sync or async ``(spec, response)``) is
        the streaming consumer: called strictly in chunk order as the
        contiguous prefix completes — the wire frontend streams
        out-frames from it. Without it the chunks splice into one
        payload and the returned ``Response`` carries the whole output
        (the transparent-admission path). With it, acked-on-resume
        chunks are SKIPPED (never recomputed, never re-emitted) and
        ``Response.payload`` is None — the consumer assembled the
        bytes. Every response carries ``Response.transfer`` (token +
        chunk tallies)."""
        data = np.asarray(payload, dtype=np.uint8).reshape(-1)
        mode = str(mode or "ctr")
        if mode not in TRANSFER_MODES:
            return self._refuse(ERR_TRANSFER_MODE, (
                f"mode {mode!r} cannot be served as a chunked transfer "
                f"(chunkable: {TRANSFER_MODES}); GCM's tag is a GHASH "
                "over the whole message and host-side GHASH continuation "
                "across chunk tags is not implemented — submit at or "
                "below the ladder cap, or use ctr/cbc"), mode)
        if data.size == 0 or data.size % 16:
            return self._refuse(ERR_BAD_REQUEST, (
                "payload must be a nonzero multiple of 16 bytes"), mode)
        if data.size > self.max_payload_bytes:
            return self._refuse(ERR_TOO_LARGE, (
                f"payload {data.size} bytes exceeds the transfer cap "
                f"({self.max_payload_bytes} bytes)"), mode)
        try:
            specs = plan(mode, self.chunk_blocks, data.size,
                         nonce=nonce, iv=iv, payload=data, tails=tails)
        except ValueError as e:
            return self._refuse(ERR_BAD_REQUEST, f"transfer plan: {e}", mode)
        # Backpressure BEFORE any work: a slow consumer (held bytes over
        # budget) or a full transfer table sheds NEW transfers with a
        # typed error — admitted transfers' chunks keep flowing, the
        # dispatch loop never wedges behind reassembly.
        if self.active >= self.max_transfers:
            return self._shed("transfers", (
                f"{self.active} transfers in flight (max "
                f"{self.max_transfers}); retry with backoff"))
        if self.held_bytes > self.reassembly_budget_bytes:
            return self._shed("reassembly", (
                f"reassembly buffer over budget ({self.held_bytes} > "
                f"{self.reassembly_budget_bytes} bytes held); the "
                "consumer is slow — retry with backoff"))

        tid = resume_token or uuid.uuid4().hex
        fp = fingerprint(mode, key, nonce, iv, data.size, self.chunk_blocks)
        acked = self.ledger.begin(tid, fp, len(specs))
        # Resuming only makes sense on the streaming path: without a
        # consumer the response must carry EVERY byte, so acked chunks
        # would have to be recomputed anyway.
        skip = acked if on_chunk is not None else set()
        resumed = bool(resume_token) and bool(skip)
        if sampled is None:
            sampled = trace.sample()
        if deadline_s is None:
            deadline_s = self.deadline_s
        budget = Budget(deadline_s, clock=self._clock)
        self.started += 1
        if resumed:
            self.resumed += 1
            metrics.counter("serve_transfer_resumed", mode=mode)
        metrics.counter("serve_transfer_requests", mode=mode)
        self.chunks_skipped += len(skip)
        if skip:
            metrics.counter("serve_transfer_chunks", len(skip),
                            outcome="skipped", mode=mode)

        cm = trace.maybe_span(sampled, "transfer", parent=parent,
                              tenant=tenant, mode=mode, chunks=len(specs),
                              blocks=data.size // 16, resumed=resumed)
        cm.__enter__()
        root = cm.span_id
        self.active += 1
        t0 = self._clock()
        out = np.empty(data.size, dtype=np.uint8) if on_chunk is None else None
        results: dict[int, Response] = {}
        landed = asyncio.Event()
        abort: list = []  # [code, detail] — first failure wins
        sem = asyncio.Semaphore(max(self.window, 1))
        sent = 0
        redispatched = 0

        def _fail(code: str, detail: str) -> None:
            if not abort:
                abort.extend((code, detail))
            landed.set()

        async def run_chunk(spec: ChunkSpec) -> None:
            nonlocal sent, redispatched
            async with sem:
                while True:
                    if abort:
                        return
                    if budget.exhausted():
                        _fail(ERR_DEADLINE, (
                            f"transfer budget spent "
                            f"({budget.spent():.3f}s of {deadline_s}s) "
                            f"before chunk {spec.index} dispatched"))
                        return
                    # The per-chunk admission seam: transfer_abort kills
                    # the WHOLE exchange here (@<skip> places it so some
                    # chunks are already acked — the resume drill).
                    if faults.fire_chunk("transfer_abort", spec.index):
                        _fail(ERR_TRANSFER_ABORT, (
                            f"injected transfer_abort at chunk "
                            f"{spec.index}; present the resume token to "
                            "finish"))
                        return
                    piece = data[spec.offset:spec.offset + spec.nbytes]
                    ccm = trace.maybe_span(sampled, "transfer-chunk",
                                           parent=root, chunk=spec.index,
                                           blocks=spec.nbytes // 16)
                    ccm.__enter__()
                    try:
                        sent += 1
                        remaining = budget.remaining()
                        resp = await self._submit(
                            tenant, key, spec, piece, mode=mode,
                            deadline_s=(None if remaining == float("inf")
                                        else max(remaining, 0.001)),
                            sampled=sampled, parent=root)
                    except Exception as e:  # noqa: BLE001 - typed answer
                        ccm.__exit__(type(e), e, None)
                        _fail(ERR_TRANSFER_ABORT,
                              f"chunk {spec.index} dispatch raised: {e}")
                        return
                    if resp.ok and faults.fire_chunk("chunk_lost",
                                                     spec.index):
                        # The injected in-flight loss: the ladder served
                        # the chunk, the result frame never arrived —
                        # discard and redispatch exactly this chunk.
                        ccm.__exit__(RuntimeError, None, None)
                        redispatched += 1
                        self.chunk_redispatches += 1
                        metrics.counter("serve_transfer_chunks",
                                        outcome="redispatch", mode=mode)
                        continue
                    if not resp.ok and resp.error == ERR_SHED \
                            and not budget.exhausted():
                        # A shed chunk is backpressure, not failure:
                        # back off within the transfer budget and
                        # redispatch (the router does the same dance on
                        # the ring, one fault domain up).
                        ccm.__exit__(RuntimeError, None, None)
                        redispatched += 1
                        self.chunk_redispatches += 1
                        metrics.counter("serve_transfer_chunks",
                                        outcome="redispatch", mode=mode)
                        await asyncio.sleep(self.retry_backoff_s)
                        continue
                    if not resp.ok:
                        ccm.__exit__(RuntimeError, None, None)
                        _fail(resp.error or ERR_TRANSFER_ABORT,
                              f"chunk {spec.index}: {resp.detail}")
                        return
                    ccm.__exit__(None, None, None)
                    metrics.counter("serve_transfer_chunks",
                                    outcome="ok", mode=mode)
                    results[spec.index] = resp
                    self.held_bytes += spec.nbytes
                    if self.held_bytes > self.held_peak:
                        self.held_peak = self.held_bytes
                    metrics.gauge("serve_reassembly_held_bytes",
                                  self.held_bytes)
                    landed.set()
                    return

        tasks = [asyncio.ensure_future(run_chunk(s))
                 for s in specs if s.index not in skip]
        try:
            try:
                # The in-order emit loop: the ONE consumer-facing seam.
                for spec in specs:
                    if spec.index in skip:
                        continue  # resume: acked in a previous connection
                    t_wait = self._clock()
                    while spec.index not in results and not abort:
                        landed.clear()
                        if spec.index in results or abort:
                            break
                        try:
                            await asyncio.wait_for(landed.wait(),
                                                   timeout=0.25)
                        except asyncio.TimeoutError:
                            if budget.exhausted():
                                _fail(ERR_DEADLINE, (
                                    f"transfer budget spent waiting to "
                                    f"reassemble chunk {spec.index}"))
                    if abort:
                        break
                    resp = results.pop(spec.index)
                    hold_us = (self._clock() - t_wait) * 1e6
                    metrics.observe("serve_stage_us", hold_us,
                                    stage="reassembly")
                    try:
                        if faults.fire_chunk("reassembly_stall",
                                             spec.index):
                            # The slow consumer, injected: an AWAITABLE
                            # stall (the manager shares the dispatch
                            # loop's thread — a blocking sleep would
                            # wedge what this fault exists to prove
                            # never wedges).
                            await asyncio.sleep(_slow_s())
                        if on_chunk is not None:
                            r = on_chunk(spec, resp)
                            if asyncio.iscoroutine(r):
                                await r
                        else:
                            out[spec.offset:spec.offset + spec.nbytes] = \
                                resp.payload
                    except Exception as e:  # noqa: BLE001 - typed abort
                        # A raising consumer (the wire writer draining
                        # into a dead socket — the very disconnect
                        # resume exists for) aborts through the same
                        # typed path as any chunk failure, so the
                        # cancel/cleanup below runs and the resume
                        # token stays presentable.
                        _fail(ERR_TRANSFER_ABORT, (
                            f"consumer failed emitting chunk "
                            f"{spec.index}: {e}"))
                        break
                    finally:
                        # The popped chunk's hold releases on EVERY
                        # path: held_bytes is manager-wide admission
                        # state — leaking it on a consumer failure
                        # would ratchet every future transfer toward
                        # shed.
                        self.held_bytes -= spec.nbytes
                        metrics.gauge("serve_reassembly_held_bytes",
                                      self.held_bytes)
                    tail = b""
                    if mode == "cbc":
                        # The ledger remembers each chunk's input tail:
                        # a RESUMED cbc transfer plans chunk i+1's IV
                        # from it without re-reading chunk i's bytes.
                        end = spec.offset + spec.nbytes
                        tail = bytes(bytearray(data[end - 16:end]))
                    self.ledger.ack(tid, spec.index, tail=tail)
                    self.bytes_out += spec.nbytes
            finally:
                # Cancel unconditionally: on a clean pass every task
                # already returned (cancel is a no-op), on ANY abnormal
                # exit — abort, consumer failure, an unexpected raise —
                # in-flight chunks must not outlive the exchange.
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                # Landed-but-never-emitted chunks (an aborted exchange,
                # or stragglers that completed between the abort and
                # the cancel) release their reassembly hold: an
                # abandoned transfer must not pin the buffer budget it
                # no longer uses.
                for spec in specs:
                    if results.pop(spec.index, None) is not None:
                        self.held_bytes -= spec.nbytes
                metrics.gauge("serve_reassembly_held_bytes",
                              self.held_bytes)
        except BaseException as e:
            # An escape the typed paths didn't catch still closes the
            # transfer span — obs must not leak an open root (and the
            # caller sees the raise unchanged).
            cm.__exit__(type(e), e, e.__traceback__)
            raise
        finally:
            self.active -= 1

        self.chunks_sent += sent
        tx = {"token": tid, "chunks": len(specs), "sent": sent,
              "skipped": len(skip), "redispatched": redispatched,
              "acked": len(self.ledger.acked(tid)), "resumed": resumed}
        if abort:
            self.aborted += 1
            metrics.counter("serve_transfer_aborts", code=abort[0])
            cm.__exit__(RuntimeError, None, None)  # force-sample failures
            return Response(ok=False, error=abort[0], detail=abort[1],
                            transfer=tx)
        self.ledger.done(tid, ok=True)
        self.completed += 1
        metrics.counter("serve_transfer_completed", mode=mode)
        metrics.counter("serve_transfer_bytes", data.size, mode=mode)
        metrics.observe("serve_transfer_us", (self._clock() - t0) * 1e6)
        cm.__exit__(None, None, None)
        return Response(
            ok=True,
            payload=out if on_chunk is None else None,
            queued_s=0.0, transfer=tx)

    def stats(self) -> dict:
        """The artifact/status ``transfers`` section."""
        return {"chunk_blocks": self.chunk_blocks,
                "started": self.started, "completed": self.completed,
                "resumed": self.resumed, "aborted": self.aborted,
                "shed": self.shed, "refused": self.refused,
                "active": self.active,
                "chunks_sent": self.chunks_sent,
                "chunks_skipped": self.chunks_skipped,
                "chunk_redispatches": self.chunk_redispatches,
                "bytes_out": self.bytes_out,
                "held_bytes": self.held_bytes,
                "held_peak_bytes": self.held_peak,
                "budget_bytes": self.reassembly_budget_bytes,
                "ledger_live": self.ledger.live()}
