"""Multi-tenant LRU cache of expanded AES key schedules.

Key expansion is host-side, sequential, and per-key
(``ops.keyschedule.expand_key_enc`` — the reference expands on host even
for its GPU backend). Per-request that cost dwarfs a small request's
crypt time; a service where every request names its key must make
rekeying a LOOKUP. Entries hold the HOST (numpy) schedule: device
staging belongs to the dispatch lane (``serve/lanes.py`` commits the
44-60 round-key words onto its own device per call — the words are tiny
and committed inputs are what route a dispatch to the lane's device).

Entries are keyed by (tenant, key digest). Tenant isolation is
structural, twice over:

* **capacity** — each tenant gets its own LRU of ``per_tenant`` entries,
  so one tenant churning through keys can never evict another tenant's
  hot schedules (the noisy-neighbour failure of a shared LRU);
* **identity** — the same key bytes under two tenants are two entries;
  cache state never flows across tenants, so the cache cannot become a
  cross-tenant oracle for "has someone else used this key".

The digest (truncated SHA-256) is the cache identity and the only
key-derived value that escapes into labels/traces — raw key bytes stay
inside the entry.

Single-event-loop discipline like the rest of serve/ (no lock); hits,
misses and evictions are counted both locally (``stats()``) and into
the obs counters.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

from ..obs import trace
from ..ops.keyschedule import expand_key_enc


def key_digest(key: bytes) -> str:
    """The cache/trace identity of a key: truncated SHA-256 hex."""
    return hashlib.sha256(bytes(key)).hexdigest()[:16]


class KeyCache:
    """tenant -> (digest -> (nr, host round keys)) with per-tenant LRU."""

    def __init__(self, per_tenant: int = 8):
        if per_tenant < 1:
            raise ValueError("per_tenant must be >= 1")
        self.per_tenant = int(per_tenant)
        self._tenants: dict[str, OrderedDict] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, tenant: str, key: bytes):
        """(digest, nr, host round-key words) for ``key`` under
        ``tenant``, expanding on miss, evicting the tenant's least
        recently used entry past capacity."""
        digest = key_digest(key)
        lru = self._tenants.setdefault(tenant, OrderedDict())
        entry = lru.get(digest)
        if entry is not None:
            lru.move_to_end(digest)
            self.hits += 1
            trace.counter("keycache_hit", tenant=tenant)
            return (digest, *entry)
        self.misses += 1
        trace.counter("keycache_miss", tenant=tenant)
        nr, rk = expand_key_enc(bytes(key))
        entry = (nr, np.asarray(rk, dtype=np.uint32))
        lru[digest] = entry
        if len(lru) > self.per_tenant:
            lru.popitem(last=False)
            self.evictions += 1
            trace.counter("keycache_evict", tenant=tenant)
        return (digest, *entry)

    def holds(self, tenant: str, key: bytes) -> bool:
        """Whether the entry is cached (no LRU touch — test/introspection
        only; production reads go through ``get``)."""
        return key_digest(key) in self._tenants.get(tenant, {})

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "tenants": len(self._tenants),
                "entries": sum(len(v) for v in self._tenants.values())}
