"""Multi-tenant LRU cache of expanded AES key schedules.

Key expansion is host-side, sequential, and per-key
(``ops.keyschedule.expand_key_enc`` — the reference expands on host even
for its GPU backend). Per-request that cost dwarfs a small request's
crypt time; a service where every request names its key must make
rekeying a LOOKUP. Entries hold the HOST (numpy) schedule: device
staging belongs to the dispatch lane (``serve/lanes.py`` commits the
44-60 round-key words onto its own device per call — the words are tiny
and committed inputs are what route a dispatch to the lane's device).

Entries are keyed by (tenant, key digest). Tenant isolation is
structural, twice over:

* **capacity** — each tenant gets its own LRU of ``per_tenant`` entries,
  so one tenant churning through keys can never evict another tenant's
  hot schedules (the noisy-neighbour failure of a shared LRU);
* **identity** — the same key bytes under two tenants are two entries;
  cache state never flows across tenants, so the cache cannot become a
  cross-tenant oracle for "has someone else used this key".

The digest (truncated SHA-256) is the cache identity and the only
key-derived value that escapes into labels/traces — raw key bytes stay
inside the entry.

The multi-key dispatch seam consumes schedules as a STACKED view
(``stacked()``): one (K, 4*(nr+1)) array holding every slot's schedule
(zero rows in unused slots), plus — for the native host tier — the
pre-built C contexts. Stacks are memoized per (slot digest set, K) in
their own small LRU, so a steady-state traffic mix re-forming the same
batches does NO per-batch schedule work at all: no expansion, no row
copies, no native key setup — one OrderedDict hit (the digest identity
makes the memo safe across per-tenant evictions: digest -> schedule is
a pure function).

Accepted tradeoff, stated plainly: the stacked memo RETAINS expanded
schedules (and lazily-built native contexts) past a per-tenant LRU
eviction, until ``stacked_capacity`` churn pushes the stack out.
Per-tenant eviction is CAPACITY management, not key revocation — it
fires on cache pressure while the tenant may still be sending traffic
under that key, and purging stacks on it would re-pay full stack
assembly every few batches for any tenant with more live keys than
``per_tenant`` (the exact steady-state cost the memo exists to
delete; tests pin eviction-survival). There is no revocation API;
key-material lifetime in this process is bounded by BOTH LRUs.

Single-event-loop discipline like the rest of serve/ (no lock); hits,
misses and evictions are counted both locally (``stats()``) and into
the obs counters.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

from ..aead import ghash as aead_ghash
from ..obs import metrics, trace
from ..ops import gf
from ..ops.keyschedule import dec_schedule_from_enc, expand_key_enc


def key_digest(key: bytes) -> str:
    """The cache/trace identity of a key: truncated SHA-256 hex."""
    return hashlib.sha256(bytes(key)).hexdigest()[:16]


class StackedSchedules:
    """An immutable K-slot schedule stack: the multi-key dispatch view.

    ``rks``: (K, 4*(nr+1)) u32, row i = slot i's expanded schedule
    (all-zero rows pad unused slots so the dispatch shape is closed over
    K). ``native_ctxs()`` lazily builds — and then retains — the native
    C contexts for the host engine tier, one memmove per slot
    (``runtime.native.aes_ctx_from_schedule``): lazy because jax-engine
    servers never need them, retained because the stack itself is
    memoized, so steady state pays zero key setup either way.

    The AEAD extensions ride the same stack lazily, per MODE need
    (``KeyCache.stacked``): ``rks_dec`` is the (K, 4*(nr+1)) DECRYPT
    schedule stack (the parallel CBC-decrypt dispatch), ``hmats`` the
    (K, 128, 128) mul-by-H bit matrices and ``h_ints`` the raw H field
    elements (the GCM fused kernel + the host tag finisher). All pure
    functions of the slot keys, attached once to the memoized stack —
    a ctr-only server never pays for them.
    """

    __slots__ = ("nr", "rks", "digests", "_native_ctxs",
                 "rks_dec", "hmats", "h_ints")

    def __init__(self, nr: int, rks: np.ndarray, digests: tuple):
        self.nr = int(nr)
        self.rks = rks
        self.digests = digests
        self._native_ctxs = None
        self.rks_dec = None
        self.hmats = None
        self.h_ints = None

    def native_ctxs(self):
        if self._native_ctxs is None:
            from ..runtime import native

            self._native_ctxs = tuple(
                native.aes_ctx_from_schedule(self.nr, row)
                for row in self.rks)
        return self._native_ctxs


class KeyCache:
    """tenant -> (digest -> (nr, host round keys)) with per-tenant LRU."""

    def __init__(self, per_tenant: int = 8, stacked_capacity: int = 64):
        if per_tenant < 1:
            raise ValueError("per_tenant must be >= 1")
        self.per_tenant = int(per_tenant)
        self._tenants: dict[str, OrderedDict] = {}
        self._stacked: OrderedDict = OrderedDict()
        self.stacked_capacity = max(int(stacked_capacity), 1)
        #: per-digest AEAD derivation memos — pure functions of the key
        #: bytes (digest -> value), shared across every stack the digest
        #: appears in so re-stacking a familiar key never re-derives.
        #: ``_aead``: digest -> (H int, (128, 128) mul-by-H matrix);
        #: ``_dec``: digest -> the decrypt-schedule row. Bounded like
        #: the stack memo (FIFO past 4x stacked_capacity): the H-matrix
        #: is ~64 KiB/key and must not grow with key churn.
        self._aead: OrderedDict = OrderedDict()
        self._dec: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.stacked_hits = 0
        self.stacked_misses = 0
        self.aead_derives = 0

    def get(self, tenant: str, key: bytes):
        """(digest, nr, host round-key words) for ``key`` under
        ``tenant``, expanding on miss, evicting the tenant's least
        recently used entry past capacity."""
        digest = key_digest(key)
        lru = self._tenants.setdefault(tenant, OrderedDict())
        entry = lru.get(digest)
        if entry is not None:
            lru.move_to_end(digest)
            self.hits += 1
            metrics.counter("keycache", outcome="hit")
            trace.counter("keycache_hit", tenant=tenant)
            return (digest, *entry)
        self.misses += 1
        metrics.counter("keycache", outcome="miss")
        trace.counter("keycache_miss", tenant=tenant)
        nr, rk = expand_key_enc(bytes(key))
        entry = (nr, np.asarray(rk, dtype=np.uint32))
        lru[digest] = entry
        if len(lru) > self.per_tenant:
            lru.popitem(last=False)
            self.evictions += 1
            metrics.counter("keycache", outcome="evict")
            trace.counter("keycache_evict", tenant=tenant)
        return (digest, *entry)

    def stacked(self, slots: list, key_slots: int,
                mode: str = "ctr") -> StackedSchedules:
        """The memoized (K, 4*(nr+1)) stack for ``slots`` (slot-ordered
        (tenant, key) pairs — ``Batch.keys``). Every slot still passes
        through ``get`` (LRU touch + hit accounting + expansion on a
        genuinely new key), but the stack ASSEMBLY — row copies, and the
        native contexts behind ``native_ctxs()`` — is memoized per
        (digest tuple, K), so re-forming a familiar batch shape does no
        schedule work. Mixed key lengths are refused: ``nr`` is a static
        compile argument of the dispatch (the batcher never packs them
        together; this is the seam's own guard).

        ``mode`` attaches that served mode's extra per-key material to
        the (shared) stack on first need: ``gcm``/``gcm-open`` the
        GHASH subkeys H = E_K(0^128) and their mul-by-H bit matrices,
        ``cbc`` the decrypt-schedule stack. Derivations memo per DIGEST
        (``_aead``/``_dec``), so one key sealing and opening — or
        appearing in two different stacks — derives once."""
        if not slots or len(slots) > key_slots:
            raise ValueError(
                f"{len(slots)} slot(s) for a {key_slots}-slot stack")
        entries = [self.get(t, k) for t, k in slots]
        nrs = {e[1] for e in entries}
        if len(nrs) > 1:
            raise ValueError(f"mixed key lengths in one stack: nr={nrs}")
        digests = tuple((t, e[0]) for (t, _k), e in zip(slots, entries))
        memo_key = (digests, int(key_slots))
        hit = self._stacked.get(memo_key)
        if hit is not None:
            self._stacked.move_to_end(memo_key)
            self.stacked_hits += 1
            metrics.counter("keycache_stacked", outcome="hit")
            trace.counter("keycache_stacked_hit")
            self._attach_mode(hit, entries, mode)
            return hit
        self.stacked_misses += 1
        metrics.counter("keycache_stacked", outcome="miss")
        trace.counter("keycache_stacked_miss")
        nr = entries[0][1]
        rks = np.zeros((int(key_slots), 4 * (nr + 1)), dtype=np.uint32)
        for i, (_d, _nr, rk) in enumerate(entries):
            rks[i] = rk
        sched = StackedSchedules(nr, rks, digests)
        self._stacked[memo_key] = sched
        if len(self._stacked) > self.stacked_capacity:
            self._stacked.popitem(last=False)
        self._attach_mode(sched, entries, mode)
        return sched

    def _memo_aead(self, digest: str, nr: int, rk) -> tuple:
        """(H int, mul-by-H matrix) for one key, memoized per digest."""
        hit = self._aead.get(digest)
        if hit is None:
            self.aead_derives += 1
            metrics.counter("keycache", outcome="aead-derive")
            h = aead_ghash.derive_h(nr, rk)
            hit = (h, gf.gf128_mul_matrix_words(h))
            self._aead[digest] = hit
            if len(self._aead) > 4 * self.stacked_capacity:
                self._aead.popitem(last=False)
        return hit

    def _attach_mode(self, sched: StackedSchedules, entries: list,
                     mode: str) -> None:
        """Attach ``mode``'s per-key material to the stack, once. Unused
        slots stay zero — a GCM batch's padding rows index slot 0 (a
        real slot) and their GHASH lanes are discarded by the request
        spans, so zero rows are never read as key material."""
        if mode in ("gcm", "gcm-open") and sched.hmats is None:
            k = sched.rks.shape[0]
            hmats = np.zeros((k, 128, 128), dtype=np.uint32)
            h_ints = [0] * k
            for i, (digest, nr, rk) in enumerate(entries):
                h_ints[i], hmats[i] = self._memo_aead(digest, nr, rk)
            sched.hmats = hmats
            sched.h_ints = tuple(h_ints)
        elif mode == "cbc" and sched.rks_dec is None:
            rks_dec = np.zeros_like(sched.rks)
            for i, (digest, nr, rk) in enumerate(entries):
                row = self._dec.get(digest)
                if row is None:
                    # Derived from the already-expanded ENCRYPT schedule
                    # (reverse + InvMixColumns) — no key bytes re-touched.
                    row = dec_schedule_from_enc(nr, rk)
                    self._dec[digest] = row
                    if len(self._dec) > 4 * self.stacked_capacity:
                        self._dec.popitem(last=False)
                rks_dec[i] = row
            sched.rks_dec = rks_dec

    def holds(self, tenant: str, key: bytes) -> bool:
        """Whether the entry is cached (no LRU touch — test/introspection
        only; production reads go through ``get``)."""
        return key_digest(key) in self._tenants.get(tenant, {})

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "stacked_hits": self.stacked_hits,
                "stacked_misses": self.stacked_misses,
                "stacked_entries": len(self._stacked),
                "aead_derives": self.aead_derives,
                "tenants": len(self._tenants),
                "entries": sum(len(v) for v in self._tenants.values())}
