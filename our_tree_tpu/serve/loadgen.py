"""Closed- and open-loop load generators for the serve path.

Closed loop (the default): ``concurrency`` client coroutines each run a
closed loop — draw a (tenant, key, size) from a seeded RNG, submit,
await the response, repeat — so offered load adapts to service rate
(the standard closed-loop model; there is no coordinated-omission
window because a client never has more than one request outstanding).

Open loop (``arrival_rate=R``): requests ARRIVE at a fixed rate of R/s
regardless of how fast the server answers — one submission every 1/R
seconds, outstanding requests unbounded. This is the mode that can
actually expose overlap gains: a closed loop with few clients throttles
itself to the service rate (a single-dispatch server and an overlapped
one both stay "busy"), while a fixed offered load above one lane's
capacity piles work into the queue and only multi-lane in-flight
dispatch can drain it — the saturation run's offered-load knob
(docs/SERVING.md). Latency is measured from each request's SCHEDULED
arrival time, so generator lag counts as queueing delay instead of
being coordinated-omission-masked.

Correctness rides along without polluting the compile counter: a fixed
set of PROBE requests — one per request size, keys/nonces/payloads
pinned by the seed — is precomputed against the byte-exact models API
(``AES.crypt_ctr``, the parity-oracle path) BEFORE the server's warmup
marker, and every ``verify_every``-th request replays a probe and
checks the returned bytes. Random requests exercise breadth; probes pin
bit-exactness; neither adds a post-warmup compile (probes reuse served
shapes, references are precomputed).

Latency percentiles use the nearest-rank method on the full sample (no
binning error at the tail); goodput counts only OK-response payload
bytes.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

import numpy as np

from ..aead import ghash as aead_ghash
from ..models.aes import AES
from ..obs import metrics as obs_metrics
from ..ops.keyschedule import expand_key_enc

#: The mixed-size menu (bytes): 1 block to the default bucket ceiling.
#: Mixed sizes are the point — a single size would never exercise the
#: ladder's coalesce-and-pad behaviour.
MIXED_SIZES = (16, 64, 256, 1024, 4096, 16384, 65536)

#: The multi-tenant-heavy menu: small requests only, so a full rung can
#: only come from PACKING many tenants' key groups into one dispatch —
#: the shape that starved the pre-multikey coalescer (one batch per
#: (tenant, key)) and the one ``serve.bench --tenant-heavy`` gates
#: ``coalesce_efficiency`` on.
TENANT_HEAVY_SIZES = (16, 64, 256, 1024)


def percentile(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile (sorted input; 0 < p <= 100) — delegates
    to the repo's ONE implementation (``obs.metrics.percentile_exact``;
    the report's histogram percentiles interpolate from log2 buckets
    via the sibling ``percentile_from_buckets``)."""
    return obs_metrics.percentile_exact(sorted_vals, p)


@dataclass
class Probe:
    tenant: str
    key: bytes
    nonce: bytes
    payload: np.ndarray
    expected: np.ndarray
    #: served mode + its request fields (serve/queue.py); ctr leaves
    #: them empty. ``expected_tag`` pins the gcm seal tag bit-exactly.
    mode: str = "ctr"
    iv: bytes = b""
    aad: bytes = b""
    tag: bytes = b""
    expected_tag: bytes = b""


@dataclass
class LoadReport:
    requests: int = 0
    ok: int = 0
    errors: dict = field(default_factory=dict)  #: error code -> count
    verified: int = 0
    mismatches: int = 0
    wall_s: float = 0.0
    goodput_gbps: float = 0.0
    p50_ms: float = 0.0
    p95_ms: float = 0.0
    p99_ms: float = 0.0
    latencies_ms: list = field(default_factory=list, repr=False)
    #: per-request time-attribution ledgers collected off sampled
    #: responses (Response.ledger — the router attaches them): the
    #: waterfall population route.bench aggregates and gates on
    ledgers: list = field(default_factory=list, repr=False)
    #: chunked-transfer tallies (requests whose Response carried a
    #: ``transfer`` section — the oversized mix, serve/transfer.py);
    #: empty when the drive sent none
    transfers: dict = field(default_factory=dict)
    #: stateful-session tallies (the rc4 session mix, serve/session.py:
    #: opened/closed/chunks/verified/mismatches/...); empty when the
    #: drive ran no sessions
    sessions: dict = field(default_factory=dict)

    def finish(self, wall_s: float, ok_bytes: int) -> None:
        self.wall_s = wall_s
        self.goodput_gbps = (ok_bytes / 1e9 / wall_s) if wall_s > 0 else 0.0
        lat = sorted(self.latencies_ms)
        self.p50_ms = round(percentile(lat, 50), 3)
        self.p95_ms = round(percentile(lat, 95), 3)
        self.p99_ms = round(percentile(lat, 99), 3)

    def to_json(self) -> dict:
        return {
            "requests": self.requests, "ok": self.ok,
            "errors": dict(sorted(self.errors.items())),
            "verified": self.verified, "mismatches": self.mismatches,
            "wall_s": round(self.wall_s, 3),
            "goodput_gbps": round(self.goodput_gbps, 4),
            "p50_ms": self.p50_ms, "p95_ms": self.p95_ms,
            "p99_ms": self.p99_ms,
            **({"transfers": dict(self.transfers)}
               if self.transfers else {}),
            **({"sessions": dict(self.sessions)}
               if self.sessions else {}),
        }


def _np_cbc_encrypt(key: bytes, iv16: bytes, pt: bytes) -> bytes:
    """Host-reference CBC encrypt (the sequential direction serving
    deliberately lacks): chains ``aead.ghash``'s single-block oracle —
    the probe-generation twin of the served parallel CBC decrypt."""
    nr, rk = expand_key_enc(key)
    prev, ct = iv16, bytearray()
    for i in range(0, len(pt), 16):
        blk = bytes(a ^ b for a, b in zip(pt[i:i + 16], prev))
        prev = aead_ghash.np_aes_encrypt_block(nr, rk, blk).tobytes()
        ct += prev
    return bytes(ct)


def make_probes(sizes, seed: int, modes=("ctr",)) -> list[Probe]:
    """One pinned request per (mode, size) with its reference output.

    ctr references run the byte-exact models path; the AEAD/CBC
    references are the pure-host numpy twins (``aead.ghash`` — no jax,
    no compile). Call BEFORE the server's warmup/compile marker, so
    reference compiles never count against steady-state serving. The
    ``gcm-open`` probe replays the ``gcm`` probe's sealed pair — its
    expected output is the original plaintext, and its (valid) tag is
    what keeps verified open traffic from auth-failing."""
    rng = np.random.default_rng(seed ^ 0x9E3779B9)
    probes = []
    for size in sizes:
        key = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        nonce = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        payload = rng.integers(0, 256, size, dtype=np.uint8)
        if "ctr" in modes:
            ref = AES(key, engine="jnp")
            expected, _, _, _ = ref.crypt_ctr(
                0, np.frombuffer(nonce, np.uint8),
                np.zeros(16, np.uint8), payload)
            probes.append(Probe("probe", key, nonce, payload,
                                np.asarray(expected)))
        gcm_wanted = [m for m in ("gcm", "gcm-open") if m in modes]
        if gcm_wanted:
            iv = rng.integers(0, 256, 12, dtype=np.uint8).tobytes()
            aad = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
            ct, tag = aead_ghash.np_gcm_seal(key, iv, aad,
                                             payload.tobytes())
            if "gcm" in gcm_wanted:
                probes.append(Probe(
                    "probe", key, b"", payload,
                    np.frombuffer(ct, np.uint8), mode="gcm", iv=iv,
                    aad=aad, expected_tag=tag))
            if "gcm-open" in gcm_wanted:
                probes.append(Probe(
                    "probe", key, b"", np.frombuffer(ct, np.uint8),
                    payload, mode="gcm-open", iv=iv, aad=aad, tag=tag))
        if "cbc" in modes:
            iv16 = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
            cbc_ct = _np_cbc_encrypt(key, iv16, payload.tobytes())
            probes.append(Probe(
                "probe", key, b"", np.frombuffer(cbc_ct, np.uint8),
                payload, mode="cbc", iv=iv16))
    return probes


def make_transfer_probes(sizes, seed: int) -> list[Probe]:
    """One pinned OVERSIZED ctr request per size — the chunked-transfer
    mix's probes (serve/transfer.py). Every transfer request in the
    drive is one of these, always verified: the whole point of the
    oversized mix is proving the spliced output byte-identical to the
    single-shot reference, so unverified random transfers would only
    add bytes, not evidence. Same rule as ``make_probes``: call BEFORE
    the warmup marker — the references compile on the models path, not
    the server's."""
    rng = np.random.default_rng(seed ^ 0x7F4A7C15)
    probes = []
    for size in sizes:
        if size % 16:
            raise ValueError(f"transfer size {size} is not a multiple "
                             "of 16 bytes")
        key = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        nonce = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        payload = rng.integers(0, 256, size, dtype=np.uint8)
        ref = AES(key, engine="jnp")
        expected, _, _, _ = ref.crypt_ctr(
            0, np.frombuffer(nonce, np.uint8),
            np.zeros(16, np.uint8), payload)
        probes.append(Probe("transfer", key, nonce, payload,
                            np.asarray(expected)))
    return probes


@dataclass
class SessionScript:
    """One pinned RC4 session drive: key, chunk payloads, and every
    chunk's expected ciphertext — a per-session probe SEQUENCE (the
    stream is stateful, so the unit of verification is the whole
    ordered chunk script, not one request)."""
    tenant: str
    sid: int
    key: bytes
    payloads: list
    expected: list


def make_session_probes(sessions: int, chunks: int, seed: int,
                        chunk_sizes=(256, 1024, 4096),
                        tenants: int = 4) -> list[SessionScript]:
    """Pinned session scripts with HOST-reference ciphertexts.

    References come from ``models/arc4.keystream_np`` — the pure-numpy
    PRGA oracle (no jax, no compile), so a fully-verified session drive
    adds zero post-warmup compiles (the ``make_probes`` rule). Chunk
    sizes cycle the menu per session with a per-session phase, so
    concurrent sessions' chunks land on DIFFERENT rungs and the
    coalescer has mixed shapes to pack. Every chunk is a multiple of 16
    bytes (queue admission's block rule binds rc4 like every mode)."""
    from ..models.arc4 import key_schedule, keystream_np
    rng = np.random.default_rng(seed ^ 0x2545F491)
    scripts = []
    for s in range(int(sessions)):
        key = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        state = (0, 0, key_schedule(key))
        payloads, expected = [], []
        for c in range(int(chunks)):
            size = int(chunk_sizes[(s + c) % len(chunk_sizes)])
            data = rng.integers(0, 256, size, dtype=np.uint8)
            ks, state = keystream_np(state, size)
            payloads.append(data)
            expected.append(np.bitwise_xor(data, ks))
        scripts.append(SessionScript(f"t{s % max(int(tenants), 1)}",
                                     s, key, payloads, expected))
    return scripts


async def run(server, n_requests: int, concurrency: int = 32,
              sizes=MIXED_SIZES, tenants: int = 4, keys_per_tenant: int = 2,
              seed: int = 0, verify_every: int = 8,
              deadline_s: float | None = None,
              probes: list[Probe] | None = None,
              arrival_rate: float | None = None,
              modes=("ctr",),
              transfer_sizes=(), transfer_every: int = 0,
              transfer_probes: list[Probe] | None = None,
              sessions: int = 0, session_chunks: int = 0,
              session_chunk_bytes=(256, 1024, 4096),
              session_scripts: list[SessionScript] | None = None,
              clock=time.monotonic) -> LoadReport:
    """Drive ``server`` with ``n_requests`` total; returns the
    aggregated LoadReport.

    ``arrival_rate=None`` (default): ``concurrency`` closed-loop
    clients. ``arrival_rate=R``: open loop — one request submitted every
    ``1/R`` seconds with no outstanding-request bound (``concurrency``
    is ignored; the offered load, not the service rate, sets the pace).

    ``modes`` is the served-mode MIX (serve/queue.py MODES): each
    request draws its mode uniformly, so CTR, GCM seal/open, and CBC
    decrypt interleave in one queue — the mixed-workload drive. Random
    ``gcm-open`` traffic replays the per-size sealed probe pair (a
    made-up tag would answer ``auth-failed`` by design; auth-failure
    coverage is the tamper tests' job, not the load mix's).

    ``transfer_sizes`` + ``transfer_every=N``: every Nth request is an
    OVERSIZED pinned probe (round-robin over the sizes) that the target
    serves as a chunked transfer (serve/transfer.py) — always verified
    against its single-shot reference, tallied in
    ``LoadReport.transfers``.

    ``sessions=N`` + ``session_chunks=M``: N rc4 session clients run
    ALONGSIDE the ordinary drive — each opens its session, streams M
    interleaved data chunks (every one verified against the pinned
    host-keystream script, serve/session.py), and closes. The stream
    is stateful, so a failed chunk ends ITS session's script (the
    stream position cannot rewind); everything is tallied in
    ``LoadReport.sessions`` and the chunks join the request totals.
    """
    sizes = tuple(sizes)
    modes = tuple(modes) or ("ctr",)
    if probes is None:
        probes = make_probes(sizes, seed, modes)
    tprobes = list(transfer_probes or ())
    if not tprobes and transfer_sizes and transfer_every:
        tprobes = make_transfer_probes(tuple(transfer_sizes), seed)
    scripts = list(session_scripts or ())
    if not scripts and sessions and session_chunks:
        scripts = make_session_probes(sessions, session_chunks, seed,
                                      chunk_sizes=tuple(session_chunk_bytes),
                                      tenants=tenants)
    by_key = {(p.mode, p.payload.size): p for p in probes}
    if "gcm-open" in modes:
        missing = [s for s in sizes if ("gcm-open", s) not in by_key]
        if missing:
            # Fail FAST: without a sealed pair per size every random
            # gcm-open request would either carry a made-up tag (100%
            # auth-failed) or have to silently change mode — both turn
            # the drive into noise. Verification supplies the pairs.
            raise ValueError(
                f"gcm-open in the mode mix needs a sealed probe pair "
                f"per size (missing sizes {missing}): enable "
                f"verify_every / pass probes covering every size")
    keys = {}
    key_rng = np.random.default_rng(seed)
    for t in range(tenants):
        for k in range(keys_per_tenant):
            keys[(t, k)] = key_rng.integers(0, 256, 16,
                                            dtype=np.uint8).tobytes()
    report = LoadReport()
    counter = {"next": 0, "ok_bytes": 0}
    # One pre-generated random payload per size, shared by every client:
    # requests are read-only (the batcher copies into its own arrays),
    # CTR timing is payload-independent, and generating fresh random
    # bytes per request INSIDE the timed window was charging payload
    # manufacture against goodput — at native-tier rates the generator
    # is comparable to the cipher (docs/PERF.md, the serve gap table).
    pool_rng = np.random.default_rng(seed ^ 0x5DEECE66D)
    payloads = {s: pool_rng.integers(0, 256, s, dtype=np.uint8)
                for s in sizes}

    def pick(i: int, rng):
        """Request ``i``'s (tenant, key, nonce, payload, probe, mode,
        iv, aad, tag) — shared by both loop models so a run's request
        mix depends only on the seed and the request index order, not
        on the loop shape."""
        if tprobes and transfer_every and i % transfer_every == 0:
            p = tprobes[(i // transfer_every) % len(tprobes)]
            return (p.tenant, p.key, p.nonce, p.payload, p,
                    p.mode, p.iv, p.aad, p.tag)
        size = int(rng.choice(sizes))
        mode = modes[int(rng.integers(len(modes)))]
        probe = (by_key.get((mode, size))
                 if (verify_every and i % verify_every == 0) else None)
        if probe is not None:
            return (probe.tenant, probe.key, probe.nonce, probe.payload,
                    probe, probe.mode, probe.iv, probe.aad, probe.tag)
        if mode == "gcm-open":
            # Unverified open traffic still needs a VALID tag: replay
            # the sealed pair without counting it as a probe (its
            # presence per size is checked at run() entry).
            p = by_key[(mode, size)]
            return (p.tenant, p.key, p.nonce, p.payload, None,
                    p.mode, p.iv, p.aad, p.tag)
        tenant = f"t{int(rng.integers(tenants))}"
        key = keys[(int(tenant[1:]), int(rng.integers(keys_per_tenant)))]
        nonce = iv = aad = b""
        if mode == "ctr":
            nonce = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        elif mode == "gcm":
            iv = rng.integers(0, 256, 12, dtype=np.uint8).tobytes()
            aad = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        elif mode == "cbc":
            iv = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        return (tenant, key, nonce, payloads[size], None, mode, iv, aad,
                b"")

    def account(resp, payload, probe, dt_ms: float):
        report.requests += 1
        report.latencies_ms.append(dt_ms)
        if getattr(resp, "ledger", None) is not None:
            report.ledgers.append(resp.ledger)
        tx = getattr(resp, "transfer", None)
        if tx is not None:
            t = report.transfers
            t["requests"] = t.get("requests", 0) + 1
            t["ok"] = t.get("ok", 0) + (1 if resp.ok else 0)
            t["chunks_sent"] = (t.get("chunks_sent", 0)
                                + int(tx.get("sent", 0)))
            t["redispatched"] = (t.get("redispatched", 0)
                                 + int(tx.get("redispatched", 0)))
        # Per-request client-side outcome + end-to-end latency into the
        # metrics registry: the error CODES are a closed set
        # (queue.ERR_*), so `outcome` stays low-cardinality — exact
        # totals per outcome at any OT_TRACE_SAMPLE rate.
        obs_metrics.counter("loadgen_requests",
                            outcome=(resp.error or "ok"))
        obs_metrics.observe("loadgen_latency_us", dt_ms * 1e3,
                            outcome=(resp.error or "ok"))
        if resp.ok:
            report.ok += 1
            counter["ok_bytes"] += int(payload.size)
            obs_metrics.counter("loadgen_ok_bytes", int(payload.size))
            if probe is not None:
                report.verified += 1
                if not np.array_equal(np.asarray(resp.payload),
                                      probe.expected):
                    report.mismatches += 1
                elif (probe.expected_tag
                        and getattr(resp, "tag", None)
                        != probe.expected_tag):
                    # The gcm seal probe pins the TAG bit-exactly too:
                    # right ciphertext + wrong tag is still a broken
                    # AEAD path.
                    report.mismatches += 1
        else:
            report.errors[resp.error] = report.errors.get(resp.error, 0) + 1

    async def submit_one(tenant, key, nonce, payload, mode, iv, aad, tag):
        # Mode kwargs only off the ctr default: a ctr-only drive keeps
        # the pre-AEAD submit() shape (and with it every router client
        # that predates modes).
        kw = ({} if mode == "ctr"
              else {"mode": mode, "iv": iv, "aad": aad, "tag": tag})
        return await server.submit(tenant, key, nonce, payload,
                                   deadline_s=deadline_s, **kw)

    async def client(cid: int):
        rng = np.random.default_rng((seed << 8) ^ cid)
        while True:
            i = counter["next"]
            if i >= n_requests:
                return
            counter["next"] = i + 1
            (tenant, key, nonce, payload, probe,
             mode, iv, aad, tag) = pick(i, rng)
            t0 = clock()
            resp = await submit_one(tenant, key, nonce, payload, mode,
                                    iv, aad, tag)
            account(resp, payload, probe, (clock() - t0) * 1e3)

    async def open_request(i: int, scheduled: float, rng):
        (tenant, key, nonce, payload, probe,
         mode, iv, aad, tag) = pick(i, rng)
        resp = await submit_one(tenant, key, nonce, payload, mode, iv,
                                aad, tag)
        # Latency from the SCHEDULED arrival: a generator that fell
        # behind a saturated server charges the lag as queueing delay
        # (the open-loop, coordinated-omission-free accounting).
        account(resp, payload, probe, (clock() - scheduled) * 1e3)

    async def session_client(script: SessionScript):
        """One session's whole lifecycle: open -> M data chunks (each
        verified against the pinned host-keystream script) -> close.
        Runs concurrently with every other session and the ordinary
        clients — the interleaving is the workload."""
        t = report.sessions
        t["sessions"] = t.get("sessions", 0) + 1
        r = await server.open_session(script.tenant, script.sid,
                                      script.key)
        if not getattr(r, "ok", False):
            t["open_failed"] = t.get("open_failed", 0) + 1
            err = getattr(r, "error", None) or "open-failed"
            report.errors[err] = report.errors.get(err, 0) + 1
            obs_metrics.counter("loadgen_sessions", outcome="open-failed")
            return
        t["opened"] = t.get("opened", 0) + 1
        obs_metrics.counter("loadgen_sessions", outcome="opened")
        for data, want in zip(script.payloads, script.expected):
            t0 = clock()
            resp = await server.submit(script.tenant, b"", b"", data,
                                       deadline_s=deadline_s, mode="rc4",
                                       sid=script.sid)
            dt_ms = (clock() - t0) * 1e3
            report.requests += 1
            report.latencies_ms.append(dt_ms)
            t["chunks"] = t.get("chunks", 0) + 1
            obs_metrics.counter("loadgen_requests",
                                outcome=(resp.error or "ok"))
            obs_metrics.observe("loadgen_latency_us", dt_ms * 1e3,
                                outcome=(resp.error or "ok"))
            if not resp.ok:
                # The stream is stateful: a failed chunk's keystream
                # position is gone, so the rest of this session's
                # script would mis-verify by construction — end it.
                report.errors[resp.error] = (
                    report.errors.get(resp.error, 0) + 1)
                t["chunk_failed"] = t.get("chunk_failed", 0) + 1
                break
            report.ok += 1
            counter["ok_bytes"] += int(data.size)
            obs_metrics.counter("loadgen_ok_bytes", int(data.size))
            report.verified += 1
            t["verified"] = t.get("verified", 0) + 1
            if not np.array_equal(
                    np.asarray(resp.payload, np.uint8).reshape(-1),
                    want):
                report.mismatches += 1
                t["mismatches"] = t.get("mismatches", 0) + 1
            await asyncio.sleep(0)  # let the other sessions interleave
        r = await server.close_session(script.tenant, script.sid)
        if getattr(r, "ok", False):
            t["closed"] = t.get("closed", 0) + 1

    async def open_loop(t_start: float):
        interval = 1.0 / arrival_rate
        rng = np.random.default_rng(seed << 8)
        pending = []
        for i in range(n_requests):
            scheduled = t_start + i * interval
            delay = scheduled - clock()
            if delay > 0:
                await asyncio.sleep(delay)
            pending.append(asyncio.ensure_future(
                open_request(i, scheduled, rng)))
        await asyncio.gather(*pending)

    t_start = clock()
    sess_tasks = [session_client(s) for s in scripts]
    if arrival_rate is not None and arrival_rate > 0:
        await asyncio.gather(open_loop(t_start), *sess_tasks)
    else:
        await asyncio.gather(*(client(c) for c in range(concurrency)),
                             *sess_tasks)
    report.finish(clock() - t_start, counter["ok_bytes"])
    return report
