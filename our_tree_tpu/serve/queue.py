"""Admission control and backpressure for the serve path.

The queue is where overload policy lives, and the policy is explicit:

* **Bounded depth.** A queue that grows without bound converts overload
  into unbounded latency for everyone; past ``max_depth`` new requests
  are SHED with an immediate ``"shed"`` error response. The first shed
  of a process is stamped through the shared degradation chokepoint
  (``resilience.degrade``, kind ``accept->shed``) so an overloaded run
  can never masquerade as a healthy one in its artifacts — same
  contract as every other demotion in the repo.
* **Per-tenant depth share.** Global shed alone lets one heavy tenant
  fill the queue and starve everyone (every OTHER tenant's submits shed
  while the heavy one's queued work drains first). With
  ``tenant_depth_frac < 1`` a tenant may occupy at most that fraction
  of ``max_depth``; past it, THAT tenant's submits shed
  (``serve_shed{reason=tenant}``, degrade kind ``tenant->shed``) while
  the rest of the fleet keeps being admitted — the fairness seam the
  router's backpressure propagation leans on (a shed answer travels
  back as retry-with-backoff on the replica ring, so the heavy tenant
  self-throttles instead of taking the host down).
* **Two priority tiers.** Tenants named in ``low_priority_tenants``
  (or requests submitted with ``priority=0``) shed FIRST once queue
  depth crosses ``priority_depth_frac * max_depth``
  (``serve_shed{reason=priority}``, degrade kind ``priority->shed``):
  under pressure the low tier degrades before the normal tier feels
  anything, instead of both tiers racing to the hard cap.
* **Per-request deadline.** Every accepted request carries a
  ``resilience.policy.Budget``; a request whose budget is exhausted by
  the time the batcher drains it gets a ``"deadline"`` error instead of
  occupying device time it can no longer use (and the same error when
  the batch it rode died at the dispatch deadline).
* **Admission checks up front.** CTR over 16-byte blocks: payloads must
  be a nonzero multiple of 16 bytes and fit the largest bucket rung;
  nonces are exactly 16 bytes. Malformed requests are refused at submit
  (``"bad-request"`` / ``"too-large"``), not discovered mid-batch.

Every accepted request opens a DETACHED ``request-queued`` obs span
(begin at admission, end at drain) — queue residency is the latency
component the batcher's spans cannot see. Detached because request
lifetimes overlap arbitrarily on the one event-loop thread
(``obs.trace.detached_span``).

Admission is also where the run's HEAD-SAMPLING decision is made
(``OT_TRACE_SAMPLE``, docs/OBSERVABILITY.md): each accepted request
draws ``trace.sample()`` once and carries the bit (``Request.sampled``)
through batch formation to dispatch, so one request's spans appear or
vanish together. An unsampled request's ``request-queued`` span is
DEFERRED (``trace.maybe_span``): nothing is written on the happy path,
but a deadline expiry at drain still materialises the span with an
error end — abnormal outcomes are force-sampled. The metrics registry
(``obs/metrics.py``) counts every request, shed, refusal, and expiry
EXACTLY regardless of the sample rate, and tracks queue depth plus its
high-water mark as gauges — the /metrics view of admission pressure.

asyncio + stdlib + resilience/obs only — no jax: admission logic is
testable without a backend in sight.
"""

from __future__ import annotations

import asyncio
import itertools
import time
from dataclasses import dataclass, field

import numpy as np

from ..aead import ghash as aead_ghash
from ..obs import metrics, trace
from ..ops.keyschedule import expand_key_enc
from ..resilience import degrade
from ..resilience.policy import Budget

#: Response error codes (the closed set clients dispatch on).
ERR_SHED = "shed"              #: queue full — back off and retry
ERR_TOO_LARGE = "too-large"    #: payload exceeds the largest bucket
ERR_BAD_REQUEST = "bad-request"  #: malformed payload/nonce
ERR_DEADLINE = "deadline"      #: budget exhausted (queued or dispatching)
ERR_DISPATCH = "dispatch-failed"  #: the batch died after retries
ERR_SHUTDOWN = "shutdown"      #: server stopped with the request queued
ERR_AUTH = "auth-failed"       #: GCM open: tag mismatch (per-request
#                                 refusal — the batch and its other
#                                 riders are unaffected)
ERR_TRANSFER_ABORT = "transfer-abort"  #: a chunked transfer died
#                                 mid-flight (fault/budget); the
#                                 response's ``transfer`` dict carries
#                                 the resume token and acked count, so
#                                 the client can reconnect and finish
ERR_TRANSFER_MODE = "transfer-unsupported"  #: oversized payload in a
#                                 mode the chunk decomposition cannot
#                                 serve bit-exactly (GCM needs GHASH
#                                 continuation across chunk tags) —
#                                 refused with the reason, never
#                                 silently downgraded

#: The served mode vocabulary. ``ctr`` is the original scattered-CTR
#: workload; ``gcm``/``gcm-open`` are AES-GCM seal/open (aead/gcm.py —
#: distinct modes because the fused kernel's GHASH direction is a
#: static compile argument, so the two may never share a dispatch);
#: ``cbc`` is parallel CBC DECRYPT (the only CBC direction that
#: parallelises — models/aes.py:cbc_decrypt_words_scattered_multikey);
#: ``rc4`` is the session-stateful stream mode (serve/session.py): data
#: chunks of an OPEN session XOR against pregenerated keystream — the
#: request carries its keystream slice, reserved by the SessionManager
#: before admission. Batches never mix modes (serve/batcher.py).
MODES = ("ctr", "gcm", "gcm-open", "cbc", "rc4")

#: Modes whose batch rows include the extra J0 block (the E_K(J0) tag
#: pad rides the CTR dispatch as each request's row 0).
GCM_MODES = ("gcm", "gcm-open")


class ServeError(RuntimeError):
    """A request-path failure with a machine-readable ``code``."""

    def __init__(self, code: str, message: str = ""):
        self.code = code
        super().__init__(message or code)


@dataclass
class Response:
    """What a request resolves to: payload bytes or a coded error."""

    ok: bool
    payload: np.ndarray | None = None  #: (len,) u8, encrypt/decrypt output
    error: str | None = None           #: one of the ERR_* codes
    detail: str = ""
    #: GCM seal only: the 16-byte authentication tag (None elsewhere)
    tag: bytes | None = None
    queued_s: float = 0.0              #: admission -> drain residency
    batch: str | None = None           #: label of the batch that served it
    #: the per-request time-attribution ledger (docs/OBSERVABILITY.md,
    #: the waterfall): stage name -> µs, disjoint contiguous stages
    #: summing to ``total`` — built by the server for SAMPLED requests
    #: and shipped over the wire so the router can prepend its own
    #: stages. None on unsampled/refused requests.
    ledger: dict | None = None
    #: chunked-transfer bookkeeping (serve/transfer.py): the resume
    #: token, chunk counts, and redispatch/skip tallies of the transfer
    #: this response answers. None on ordinary (single-rung) requests.
    transfer: dict | None = None


@dataclass
class Request:
    """One accepted in-flight request (queue/batcher/server currency)."""

    id: int
    tenant: str
    key: bytes
    nonce: bytes                 #: 16 big-endian counter bytes (ctr mode)
    payload: np.ndarray          #: (16*nblocks,) u8
    future: asyncio.Future
    budget: Budget | None = None
    t_submit: float = 0.0
    #: served mode (MODES); mode-specific fields below are empty for ctr
    mode: str = "ctr"
    iv: bytes = b""              #: GCM IV (any nonzero length) / CBC IV
    aad: bytes = b""             #: GCM additional authenticated data
    tag: bytes = b""             #: GCM open: the tag to verify
    #: GCM: the 16-byte pre-counter block, derived at ADMISSION — the
    #: 96-bit fast path is IV || 0^31 || 1; any other IV length takes
    #: the host GHASH path (J0 = GHASH_H(IV padded || lens), SP
    #: 800-38D §7.1) so non-96-bit IVs ride the same fixed dispatch
    #: shape as everyone else (the batcher consumes this verbatim)
    j0: bytes = b""
    #: rc4 only: the session id this chunk belongs to, and the
    #: keystream slice the SessionManager reserved for it at
    #: ``ks_offset`` of the session's stream (serve/session.py) — the
    #: batcher packs ``ks`` as the dispatch's counter-array twin, and
    #: the server acks ``ks_offset`` back to the session when the chunk
    #: is answered (the failover checkpoint advance).
    sid: int = -1
    ks: np.ndarray | None = None
    ks_offset: int = -1
    #: the admission-time head-sampling decision (OT_TRACE_SAMPLE):
    #: every span this request rides is emitted iff this bit is set
    #: (or the outcome force-samples it). When the request arrived over
    #: the wire the ROUTER's admission decision rides in instead, so one
    #: coin flip governs the whole cross-process chain.
    sampled: bool = True
    #: the upstream (router) span id this request's spans chain under —
    #: cross-process trace parentage, handed over the wire ("ps")
    parent: str | None = None
    #: admission -> drain residency, stamped by drain() (the ledger's
    #: backend_queue stage), plus the drain timestamp itself (the next
    #: stage's start — the ledger's stages are contiguous by clock)
    queued_us: int = 0
    t_drain: float = 0.0
    _span_cm: object | None = field(default=None, repr=False)
    _queue: object | None = field(default=None, repr=False)

    @property
    def nblocks(self) -> int:
        return self.payload.size // 16

    @property
    def span_blocks(self) -> int:
        """Batch rows this request occupies: GCM requests carry one
        extra row — counter J0 under a zero data word, whose CTR
        output is E_K(J0), the tag's final pad (serve/batcher.py)."""
        return self.nblocks + (1 if self.mode in GCM_MODES else 0)

    def resolve(self, resp: Response) -> None:
        if not self.future.done():
            self.future.set_result(resp)
            # The lost-request ledger: every ACCEPTED request must be
            # answered exactly once (payload or coded error) — counted
            # at the one resolution seam, so `accepted - answered` is
            # the number of requests the server silently dropped
            # (serve.bench exits 1 when it is ever nonzero).
            if self._queue is not None:
                self._queue.answered += 1

    def fail(self, code: str, detail: str = "",
             batch: str | None = None) -> None:
        self.resolve(Response(ok=False, error=code, detail=detail,
                              batch=batch))


class RequestQueue:
    """Bounded FIFO of accepted requests with an asyncio wakeup.

    Single-event-loop discipline (the server's): ``submit`` is called
    from request coroutines, ``drain`` from the batcher loop, all on one
    thread — no lock, by design, like the rest of the asyncio path.
    """

    def __init__(self, max_depth: int = 1024,
                 max_request_blocks: int = 4096,
                 default_deadline_s: float = 30.0,
                 tenant_depth_frac: float = 1.0,
                 low_priority_tenants=(),
                 priority_depth_frac: float = 0.5,
                 modes=("ctr",),
                 clock=time.monotonic):
        self.max_depth = int(max_depth)
        self.max_request_blocks = int(max_request_blocks)
        #: the ENABLED mode set: the server warms exactly these ladders,
        #: so a mode outside it must refuse at admission — its first
        #: dispatch would otherwise pay a steady-state compile, breaking
        #: the zero-recompile contract mid-traffic.
        self.modes = tuple(modes)
        self.default_deadline_s = float(default_deadline_s)
        #: Two-level tenant priority (ROADMAP carry-over): tenants named
        #: here are LOW priority — under depth pressure (queue depth at
        #: or past ``priority_depth_frac * max_depth``) their submits
        #: shed FIRST (``serve_shed{reason=priority}``), reserving the
        #: remaining headroom for normal-priority traffic. Everyone is
        #: equal below the pressure line; the hard depth cap still sheds
        #: everyone at the top. A per-request ``priority=0`` submit
        #: argument opts a single request into the low tier regardless
        #: of tenant (the wire's "pr" field).
        self.low_priority_tenants = frozenset(low_priority_tenants)
        self.priority_depth_frac = min(
            max(float(priority_depth_frac), 0.0), 1.0)
        self._priority_line = max(
            int(self.priority_depth_frac * self.max_depth), 1)
        #: Per-tenant admission cap, as a fraction of ``max_depth``: one
        #: tenant may occupy at most ``max(1, int(frac * max_depth))``
        #: queued slots, so a heavy tenant sheds ITSELF (reason=tenant)
        #: while everyone else keeps being admitted — before this, shed
        #: was global only and the heavy tenant starved the rest
        #: (ROADMAP fairness carry-over). 1.0 disables the cap (a single
        #: tenant may fill the queue, the pre-cap behaviour).
        self.tenant_depth_frac = min(max(float(tenant_depth_frac), 0.0), 1.0)
        self._tenant_cap = max(1, int(self.tenant_depth_frac
                                      * self.max_depth))
        self._tenant_pending: dict[str, int] = {}
        self._clock = clock
        self._pending: list[Request] = []
        self._event = asyncio.Event()
        self._ids = itertools.count()
        self.closed = False
        self.accepted = 0
        self.answered = 0
        self.shed = 0
        self.shed_tenant = 0
        self.shed_priority = 0
        self.refused = 0
        self.expired = 0
        self.depth_peak = 0

    def depth(self) -> int:
        return len(self._pending)

    # -- admission ---------------------------------------------------------
    def submit(self, tenant: str, key: bytes, nonce: bytes, payload,
               deadline_s: float | None = None,
               sampled: bool | None = None, parent: str | None = None,
               priority: int | None = None, mode: str = "ctr",
               iv: bytes = b"", aad: bytes = b"",
               tag: bytes = b"", sid: int = -1, ks=None,
               ks_offset: int = -1) -> asyncio.Future:
        """Admit one request; always returns a future (already resolved
        with a coded error Response when admission refuses it — callers
        get one uniform await, not two failure channels).

        ``sampled``/``parent`` are the cross-process propagation hooks:
        a request arriving over the wire carries the ROUTER's admission
        sampling decision and span id, so its spans join the router's
        trace instead of flipping a second coin (None = local admission:
        draw ``trace.sample()`` here, no upstream parent). ``priority``
        (0 = low) opts a single request into the low tier; None defers
        to the ``low_priority_tenants`` set.

        ``mode`` selects the served workload (MODES): ``ctr`` (nonce
        required), ``gcm``/``gcm-open`` (96-bit ``iv``, optional
        ``aad``; open carries the 16-byte ``tag``), ``cbc`` decrypt
        (128-bit ``iv``). The serve path keeps CTR's block-granular
        payload contract for every mode — arbitrary-length GCM lives at
        the models API (``gcm_seal``/``gcm_open``)."""
        fut = asyncio.get_running_loop().create_future()
        data = np.asarray(payload, dtype=np.uint8).reshape(-1)
        mode = str(mode or "ctr")
        iv, aad, tag = bytes(iv), bytes(aad), bytes(tag)
        span = data.size // 16 + (1 if mode in GCM_MODES else 0)
        code = None
        if self.closed:
            # Placement stopped (graceful drain in progress): refuse up
            # front so the drain set is frozen the moment stop() begins.
            code, why = ERR_SHUTDOWN, "server is draining"
        elif mode not in MODES:
            code, why = ERR_BAD_REQUEST, (
                f"unknown mode {mode!r} (served modes: {MODES})")
        elif mode not in self.modes:
            code, why = ERR_BAD_REQUEST, (
                f"mode {mode!r} not enabled on this server "
                f"(enabled: {self.modes}; its ladder was never warmed)")
        elif data.size == 0 or data.size % 16:
            code, why = ERR_BAD_REQUEST, "payload must be a nonzero multiple of 16 bytes"
        elif mode != "rc4" and len(bytes(key)) not in (16, 24, 32):
            # Refused HERE, not discovered at key expansion inside the
            # batcher loop — admission owns malformed requests. rc4 is
            # exempt: its (1..256-byte) key was consumed by the host KSA
            # at session OPEN (serve/session.py); data chunks carry no
            # key at all, only their session id + keystream slice.
            code, why = ERR_BAD_REQUEST, (
                f"key must be 16/24/32 bytes, got {len(bytes(key))}")
        elif mode == "ctr" and len(bytes(nonce)) != 16:
            code, why = ERR_BAD_REQUEST, "nonce must be 16 bytes"
        elif mode == "rc4" and int(sid) < 0:
            code, why = ERR_BAD_REQUEST, (
                "rc4 chunks must name an open session (sid >= 0)")
        elif mode == "rc4" and (ks is None
                                or getattr(ks, "size", 0) != data.size):
            # The server reserves the slice BEFORE admission; a missing
            # or short one is a broken session handoff, refused typed.
            code, why = ERR_BAD_REQUEST, (
                f"rc4 chunk needs a payload-sized keystream slice "
                f"(got {getattr(ks, 'size', None)}, want {data.size})")
        elif mode in GCM_MODES and not iv:
            # Any NONZERO IV length serves (SP 800-38D): 96-bit takes
            # the counter-concat fast path, everything else derives J0
            # through the host GHASH path below. An empty IV is the
            # one shape the spec itself refuses.
            code, why = ERR_BAD_REQUEST, "GCM iv must be non-empty"
        elif mode == "gcm-open" and len(tag) != 16:
            code, why = ERR_BAD_REQUEST, (
                f"gcm-open tag must be 16 bytes, got {len(tag)}")
        elif mode == "cbc" and len(iv) != 16:
            code, why = ERR_BAD_REQUEST, (
                f"cbc iv must be 16 bytes, got {len(iv)}")
        elif span > self.max_request_blocks:
            code, why = ERR_TOO_LARGE, (
                f"{span} blocks > bucket ceiling "
                f"{self.max_request_blocks}")
        elif len(self._pending) >= self.max_depth:
            code, why = ERR_SHED, f"queue depth {self.max_depth} reached"
            self.shed += 1
            metrics.counter("serve_shed", reason="depth")
            trace.counter("serve_shed", tenant=tenant)
            # First shed = the process entered overload shedding: a
            # demotion of the accept path, recorded like every other
            # demotion (duplicate kinds collapse in the ledger).
            degrade.degrade(
                "accept->shed",
                f"serve queue overloaded (depth {self.max_depth}); "
                f"shedding new requests")
        elif ((priority == 0 or (priority is None
                                 and tenant in self.low_priority_tenants))
              and self.priority_depth_frac < 1.0
              and len(self._pending) >= self._priority_line):
            # The priority tier: under depth pressure (at or past the
            # priority line, below the hard cap) LOW-priority traffic
            # sheds first, reserving the remaining headroom for the
            # normal tier — graceful degradation by tier instead of a
            # lottery at the cap.
            code, why = ERR_SHED, (
                f"low-priority shed under depth pressure "
                f"({self._priority_line}/{self.max_depth} slots used)")
            self.shed += 1
            self.shed_priority += 1
            metrics.counter("serve_shed", reason="priority")
            trace.counter("serve_shed_priority")
            degrade.degrade(
                "priority->shed",
                f"queue depth crossed the priority line "
                f"({self._priority_line}/{self.max_depth}, "
                f"priority_depth_frac={self.priority_depth_frac}); "
                "shedding low-priority requests first")
        elif (self.tenant_depth_frac < 1.0
              and self._tenant_pending.get(tenant, 0) >= self._tenant_cap):
            # The per-tenant cap: THIS tenant is over its depth share
            # while the queue as a whole still has room — shed the heavy
            # tenant's request (it can back off and retry) instead of
            # letting it crowd every other tenant out of admission.
            code, why = ERR_SHED, (
                f"tenant over its queue share ({self._tenant_cap} of "
                f"{self.max_depth} slots)")
            self.shed += 1
            self.shed_tenant += 1
            metrics.counter("serve_shed", reason="tenant")
            trace.counter("serve_shed_tenant")
            degrade.degrade(
                "tenant->shed",
                f"a tenant exceeded its queue share "
                f"({self._tenant_cap}/{self.max_depth} slots, "
                f"tenant_depth_frac={self.tenant_depth_frac}); "
                "shedding that tenant's requests only")
        j0 = b""
        if code is None and mode in GCM_MODES:
            if len(iv) == 12:
                j0 = iv + b"\x00\x00\x00\x01"
            else:
                # The non-96-bit path: J0 = GHASH_H(IV) needs H =
                # E_K(0^128) — one host key expansion + one host AES
                # block + a short GHASH, paid once at admission by the
                # rare IV shape that needs it (the 96-bit fast path
                # stays a concat). Host-side on purpose: admission may
                # never touch a device, and the derived J0 rides the
                # request into the SAME fixed dispatch shape (KAT
                # vector 9 pins the math at the models layer; the
                # serve twin is tests/test_serve_aead.py).
                try:
                    nr_j0, rk_j0 = expand_key_enc(bytes(key))
                    j0 = aead_ghash.j0_from_iv(
                        aead_ghash.derive_h(nr_j0, rk_j0), iv)
                except Exception as e:  # noqa: BLE001 - refuse, not crash
                    code, why = ERR_BAD_REQUEST, (
                        f"J0 derivation failed: {e}")
        if code is not None:
            if code != ERR_SHED:
                self.refused += 1
                # The mode label comes off the WIRE: an unknown value is
                # untrusted client input and must not mint metric series
                # (labels live forever; _MAX_SERIES would fill with junk
                # and drop legitimate series) — collapse it.
                metrics.counter("serve_refused", code=code,
                                mode=(mode if mode in MODES
                                      else "invalid"))
            fut.set_result(Response(ok=False, error=code, detail=why))
            return fut
        deadline = (self.default_deadline_s if deadline_s is None
                    else float(deadline_s))
        req = Request(
            id=next(self._ids), tenant=tenant, key=bytes(key),
            nonce=bytes(nonce), payload=data, future=fut,
            budget=Budget(deadline, clock=self._clock) if deadline > 0
            else None,
            t_submit=self._clock(), _queue=self,
            sampled=trace.sample() if sampled is None else bool(sampled),
            parent=parent, mode=mode, iv=iv, aad=aad, tag=tag, j0=j0,
            sid=int(sid), ks=ks, ks_offset=int(ks_offset))
        cm = trace.maybe_span(req.sampled, "request-queued",
                              parent=req.parent, req=req.id,
                              tenant=tenant, blocks=req.nblocks,
                              mode=mode)
        cm.__enter__()
        req._span_cm = cm
        self._pending.append(req)
        self._tenant_pending[tenant] = self._tenant_pending.get(tenant, 0) + 1
        self.accepted += 1
        # Registry, not trace: the per-request counter is the hot path
        # the sampled trace can no longer count exactly — and queue
        # depth (+ its high-water) is the /metrics admission gauge.
        # ``mode`` splits the request/dispatch/error series per served
        # workload (the per-mode row in obs.report).
        metrics.counter("serve_requests", mode=mode)
        metrics.counter("serve_payload_blocks", req.nblocks)
        depth = len(self._pending)
        if depth > self.depth_peak:
            self.depth_peak = depth
            metrics.gauge_max("serve_queue_depth_peak", depth)
        metrics.gauge("serve_queue_depth", depth)
        self._event.set()
        return fut

    # -- the batcher side --------------------------------------------------
    async def wait(self) -> None:
        """Block until at least one request MAY be pending (spurious
        wakeups fine — drain() returning [] is the check)."""
        await self._event.wait()
        self._event.clear()

    def kick(self) -> None:
        """Wake a waiting drain loop (shutdown path)."""
        self._event.set()

    def close(self) -> None:
        """Stop admission (new submits answer ``shutdown`` immediately).
        Already-accepted requests are untouched — the server's drain
        pass dispatches them before the loop exits."""
        self.closed = True

    def _tenant_done(self, req: Request) -> None:
        """Return the request's per-tenant queue slot (it left _pending);
        empty tenants are dropped so the dict stays bounded by the LIVE
        tenant set, not the all-time one."""
        left = self._tenant_pending.get(req.tenant, 0) - 1
        if left > 0:
            self._tenant_pending[req.tenant] = left
        else:
            self._tenant_pending.pop(req.tenant, None)

    def drain(self) -> list[Request]:
        """Take everything pending: closes each request's queued span and
        fails the ones whose deadline budget is already spent — they can
        no longer use the device time a batch would give them."""
        taken, self._pending = self._pending, []
        if taken:
            metrics.gauge("serve_queue_depth", 0)
            metrics.observe("serve_drain_requests", len(taken))
        live = []
        for req in taken:
            self._tenant_done(req)
            req.t_drain = self._clock()
            queued_s = req.t_drain - req.t_submit
            req.queued_us = int(queued_s * 1e6)
            metrics.observe("serve_queued_us", queued_s * 1e6)
            # Tail exemplar: the request-queued span is still open here,
            # so the backend_queue histogram's worst bucket names the
            # one request (and, via its router parent, the whole
            # cross-process chain) that sat there longest.
            sid = (req._span_cm.span_id
                   if req._span_cm is not None else None)
            metrics.observe("serve_stage_us", req.queued_us,
                            stage="backend_queue",
                            exemplar=({"span": sid,
                                       "trace": trace.run_id()}
                                      if sid else None))
            if req.budget is not None and req.budget.exhausted():
                self.expired += 1
                metrics.counter("serve_deadline_expired")
                trace.counter("serve_deadline_expired", tenant=req.tenant)
                if req._span_cm is not None:
                    req._span_cm.__exit__(TimeoutError, None, None)
                req.resolve(Response(
                    ok=False, error=ERR_DEADLINE,
                    detail=f"spent {req.budget.spent():.3f}s queued",
                    queued_s=queued_s))
                continue
            if req._span_cm is not None:
                req._span_cm.__exit__(None, None, None)
            live.append(req)
        return live

    def flush(self, code: str = ERR_SHUTDOWN) -> int:
        """Fail everything still queued (server shutdown): every span
        closes — a clean stop leaves no orphans."""
        taken, self._pending = self._pending, []
        for req in taken:
            self._tenant_done(req)
            if req._span_cm is not None:
                req._span_cm.__exit__(RuntimeError, None, None)
            req.fail(code, "server stopped before dispatch")
        return len(taken)

    def stats(self) -> dict:
        return {"accepted": self.accepted, "answered": self.answered,
                "lost": self.accepted - self.answered,
                "shed": self.shed, "shed_tenant": self.shed_tenant,
                "shed_priority": self.shed_priority,
                "refused": self.refused,
                "expired": self.expired, "depth": self.depth(),
                "depth_peak": self.depth_peak}
