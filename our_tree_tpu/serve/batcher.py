"""Shape-bucketed continuous batching: requests -> fixed-shape dispatches.

The recompile hazard is the serving twin of the shape-unroll hazard the
jaxpr auditor flags offline: jax compiles per shape, so a service that
dispatches each request at its natural size compiles O(distinct sizes)
programs and spends its latency budget in the compiler. The answer is a
FIXED BUCKET LADDER — power-of-two block counts between a floor and a
ceiling — and padding every batch up to its rung: after one warmup pass
over the ladder, steady-state serving replays compiled programs only
(``serve.bench`` asserts exactly that, via the backend-compile counter).

Batches coalesce per (tenant, key digest): the scattered-CTR dispatch
(``models.aes.ctr_crypt_words_scattered``) carries one round-key
schedule per call, while each request keeps its OWN counter stream —
request segments are concatenated with their per-block counters
materialised host-side (``utils.packing.np_ctr_le_blocks``), so the
batch needs no common counter base, only a common key. Padding blocks
reuse the tail counter region with zero payload; their keystream is
computed and discarded (the occupancy column in ``serve.bench`` prices
exactly this waste).

jax-free on purpose: forming a batch is numpy bookkeeping; the device
boundary is the server's.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..utils import packing
from .queue import Request

#: Default ladder bounds, in 16-byte blocks. Floor 32: the bitsliced
#: engines pack 32 blocks per lane group, so smaller rungs only add
#: compile cache entries below the packing grain. Ceiling 4096 (64 KiB):
#: big enough that one request rarely spans batches, small enough that a
#: padded miss wastes at most one rung.
DEFAULT_MIN_BLOCKS = 32
DEFAULT_MAX_BLOCKS = 4096


def bucket_ladder(min_blocks: int = DEFAULT_MIN_BLOCKS,
                  max_blocks: int = DEFAULT_MAX_BLOCKS) -> tuple[int, ...]:
    """The fixed rung set: powers of two from min to max inclusive."""
    if min_blocks < 1 or max_blocks < min_blocks:
        raise ValueError(f"bad ladder bounds [{min_blocks}, {max_blocks}]")
    rungs = []
    r = 1
    while r < min_blocks:
        r *= 2
    while r < max_blocks:
        rungs.append(r)
        r *= 2
    rungs.append(max_blocks)  # ceiling always present, pow2 or not
    return tuple(rungs)


def bucket_for(nblocks: int, rungs: tuple[int, ...]) -> int:
    """Smallest rung >= nblocks (nblocks must fit the ladder)."""
    for r in rungs:
        if nblocks <= r:
            return r
    raise ValueError(f"{nblocks} blocks exceeds ladder ceiling {rungs[-1]}")


@dataclass
class Batch:
    """One formed dispatch: same tenant+key, padded to a ladder rung."""

    tenant: str
    digest: str                  #: key digest (keycache identity)
    key: bytes
    bucket: int                  #: padded block count (the rung)
    requests: list[Request]
    blocks: int                  #: real (unpadded) block count
    words: np.ndarray | None = field(default=None, repr=False)
    ctr_words: np.ndarray | None = field(default=None, repr=False)

    @property
    def label(self) -> str:
        return f"{self.tenant}/{self.digest[:8]}:{self.bucket}"

    @property
    def occupancy(self) -> float:
        return self.blocks / self.bucket

    def materialise(self) -> None:
        """Build the flat u32 dispatch arrays (payload words + per-block
        LE counter words). Flat (4N,) on purpose: the dense jit-boundary
        layout every models entry point shares (models/aes.py:
        _as_block_words)."""
        words = np.zeros(4 * self.bucket, dtype=np.uint32)
        ctr = np.zeros((self.bucket, 4), dtype=np.uint32)
        off = 0
        for req in self.requests:
            n = req.nblocks
            words[4 * off:4 * (off + n)] = packing.np_bytes_to_words(
                req.payload)
            ctr[off:off + n] = packing.np_ctr_le_blocks(
                req.nonce, np.arange(n, dtype=np.uint32))
            off += n
        self.words = words
        self.ctr_words = ctr.reshape(-1)

    def split_output(self, out_words: np.ndarray) -> list[np.ndarray]:
        """Per-request output bytes from the batch's output words."""
        flat = np.asarray(out_words, dtype=np.uint32).reshape(-1)
        outs = []
        off = 0
        for req in self.requests:
            n = req.nblocks
            outs.append(packing.np_words_to_bytes(
                flat[4 * off:4 * (off + n)].reshape(-1, 4)).reshape(-1))
            off += n
        return outs


def form_batches(requests: list[Request],
                 rungs: tuple[int, ...],
                 key_digest) -> list[Batch]:
    """Greedy coalescing: group by (tenant, key digest) in arrival
    order, fill each batch up to the ladder ceiling, pad to the smallest
    rung that holds what was packed. Returns batches in first-arrival
    order of their groups; array materialisation is deferred to the
    caller (the server times it under its ``batch-formed`` span).
    """
    ceiling = rungs[-1]
    groups: dict[tuple[str, str], list[Request]] = {}
    order: list[tuple[str, str]] = []
    for req in requests:
        k = (req.tenant, key_digest(req.key))
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(req)
    batches: list[Batch] = []
    for tenant, digest in order:
        pending = groups[(tenant, digest)]
        cur: list[Request] = []
        cur_blocks = 0
        for req in pending:
            if cur and cur_blocks + req.nblocks > ceiling:
                batches.append(Batch(tenant, digest, cur[0].key,
                                     bucket_for(cur_blocks, rungs),
                                     cur, cur_blocks))
                cur, cur_blocks = [], 0
            cur.append(req)
            cur_blocks += req.nblocks
        if cur:
            batches.append(Batch(tenant, digest, cur[0].key,
                                 bucket_for(cur_blocks, rungs),
                                 cur, cur_blocks))
    return batches
