"""Shape-bucketed continuous batching: requests -> fixed-shape dispatches.

The recompile hazard is the serving twin of the shape-unroll hazard the
jaxpr auditor flags offline: jax compiles per shape, so a service that
dispatches each request at its natural size compiles O(distinct sizes)
programs and spends its latency budget in the compiler. The answer is a
FIXED BUCKET LADDER — power-of-two block counts between a floor and a
ceiling — and padding every batch up to its rung: after one warmup pass
over the ladder, steady-state serving replays compiled programs only
(``serve.bench`` asserts exactly that, via the backend-compile counter).

Coalescing is a RUNG-PACKER over key groups: requests first group by
(tenant, key digest) in arrival order — each group becomes one key SLOT
carrying its own schedule — and up to ``key_slots`` groups pack into one
batch, filled to the ladder ceiling. The dispatch seam
(``models.aes.ctr_crypt_words_scattered_multikey``) carries the K
stacked schedules plus a per-block slot-index vector, so one device call
serves many tenants' keys; each request still keeps its OWN counter
stream, materialised host-side (``utils.packing.np_ctr_le_blocks``).
Before the multi-key seam, every distinct (tenant, key) forced its own
batch — many tenants with small requests meant many mostly-padding
dispatches; the packer turns that fragmentation into full rungs (the
``coalesce_efficiency`` stat in ``serve.bench`` prices exactly this).
The slot dimension K is FIXED per server (unused slots carry the
all-zero schedule), so shapes stay closed and the zero-recompile
contract holds unchanged. Groups of different key LENGTHS never share a
batch: the round count ``nr`` is a static compile argument.

Padding blocks ride slot 0 with zero counters and zero payload; their
keystream is computed and discarded.

jax-free on purpose: forming a batch is numpy bookkeeping; the device
boundary is the lane's (``serve/lanes.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..aead import ghash as aead_ghash
from ..obs import metrics
from ..ops import gf
from ..ops.keyschedule import ROUNDS
from ..utils import packing
from .queue import GCM_MODES, Request

#: Default ladder bounds, in 16-byte blocks. Floor 32: the bitsliced
#: engines pack 32 blocks per lane group, so smaller rungs only add
#: compile cache entries below the packing grain. Ceiling 4096 (64 KiB):
#: big enough that one request rarely spans batches, small enough that a
#: padded miss wastes at most one rung.
DEFAULT_MIN_BLOCKS = 32
DEFAULT_MAX_BLOCKS = 4096

#: Default key slots per dispatch (the fixed K dimension). 8 covers the
#: many-tenants-few-requests drain shape without inflating the stacked
#: schedule (8 x 60 words) or the Pallas kernel's masked-select sweep.
DEFAULT_KEY_SLOTS = 8


#: Shared block-offset vector for counter materialisation, grown on
#: demand: one request's counters are ``nonce + _block_idx(n)`` and the
#: arange itself is the same for every request — allocating it per
#: request showed up on the serve fast path's profile.
_ARANGE = np.arange(DEFAULT_MAX_BLOCKS, dtype=np.uint32)


def _block_idx(n: int) -> np.ndarray:
    global _ARANGE
    if n > _ARANGE.size:
        _ARANGE = np.arange(n, dtype=np.uint32)
    return _ARANGE[:n]


def bucket_ladder(min_blocks: int = DEFAULT_MIN_BLOCKS,
                  max_blocks: int = DEFAULT_MAX_BLOCKS) -> tuple[int, ...]:
    """The fixed rung set: powers of two from min to max inclusive."""
    if min_blocks < 1 or max_blocks < min_blocks:
        raise ValueError(f"bad ladder bounds [{min_blocks}, {max_blocks}]")
    rungs = []
    r = 1
    while r < min_blocks:
        r *= 2
    while r < max_blocks:
        rungs.append(r)
        r *= 2
    rungs.append(max_blocks)  # ceiling always present, pow2 or not
    return tuple(rungs)


def bucket_for(nblocks: int, rungs: tuple[int, ...]) -> int:
    """Smallest rung >= nblocks (nblocks must fit the ladder)."""
    for r in rungs:
        if nblocks <= r:
            return r
    raise ValueError(f"{nblocks} blocks exceeds ladder ceiling {rungs[-1]}")


@dataclass
class Slot:
    """One key group inside a batch: a (tenant, key) and its riders."""

    tenant: str
    digest: str                  #: key digest (keycache identity)
    key: bytes
    requests: list[Request]
    blocks: int                  #: payload blocks in this slot

    @property
    def label(self) -> str:
        return f"{self.tenant}/{self.digest[:8]}"


@dataclass
class Batch:
    """One formed dispatch: up to K key slots, padded to a ladder rung.

    ``mode`` is uniform across the batch (the packer never mixes
    modes: each mode compiles its own dispatch program — GHASH
    direction and the CBC decrypt core are static arguments — and a
    mixed batch would be two programs in one shape). Per-mode array
    semantics (``materialise``):

    * ``ctr`` — words/ctr_words/slot_index exactly as always;
    * ``gcm``/``gcm-open`` — each request's rows are [J0, data...]:
      ``ctr_words`` carries J0 then inc32 counters, ``seg_keep`` zeroes
      the GHASH carry at each J0/first-data row, ``inject_words`` seeds
      each request's first data row with its host-computed AAD prefix
      state (needs ``sched`` — the per-slot H);
    * ``cbc`` — ``ctr_words`` is repurposed as the PREV stream (IV at
      each request's first block, then its shifted ciphertext): the
      XOR side of P_i = D(C_i) ^ C_{i-1};
    * ``rc4`` — ``ctr_words`` is repurposed as the cached KEYSTREAM
      (each chunk's slice, reserved from its session's prefetched
      window, serve/session.py): the dispatch is the key-oblivious
      XOR phase, so chunks of different sessions coalesce exactly
      like multikey CTR — one slot per session, no schedules at all.
    """

    slots: list[Slot]
    bucket: int                  #: padded block count (the rung)
    blocks: int                  #: real (unpadded) payload block count
    nr: int                      #: round count (uniform across slots)
    key_slots: int               #: the fixed K dimension
    mode: str = "ctr"            #: uniform served mode (queue.MODES)
    words: np.ndarray | None = field(default=None, repr=False)
    ctr_words: np.ndarray | None = field(default=None, repr=False)
    slot_index: np.ndarray | None = field(default=None, repr=False)
    #: GCM only: the fused kernel's segment arrays (aead/gcm.py)
    inject_words: np.ndarray | None = field(default=None, repr=False)
    seg_keep: np.ndarray | None = field(default=None, repr=False)
    #: per-request (data_start_block, nblocks) in ``requests`` order —
    #: the split_output offsets (GCM rows skip each request's J0 row)
    req_spans: list | None = field(default=None, repr=False)
    #: request layout [(slot, start_block, nblocks, nonce16)] — the
    #: native tier's per-request C CTR path consumes this instead of
    #: the materialised counter array (models.aes ``native_runs``)
    runs: list | None = field(default=None, repr=False)
    #: batch-level time-attribution windows (µs), filled by the server
    #: as the batch moves through pack -> dispatch -> reply — the
    #: shared stages of every rider's per-request ledger
    stages: dict = field(default_factory=dict, repr=False)

    @property
    def label(self) -> str:
        first = self.slots[0].label if self.slots else "?"
        suffix = "" if self.mode == "ctr" else f":{self.mode}"
        return f"{first}+{len(self.slots) - 1}k:{self.bucket}{suffix}"

    @property
    def requests(self) -> list[Request]:
        return [r for s in self.slots for r in s.requests]

    @property
    def sampled(self) -> bool:
        """Whether this batch carries at least one head-sampled rider:
        the batch's ``batch-formed``/``lane-dispatch`` spans are emitted
        iff it does (abnormal outcomes force-sample regardless)."""
        return any(r.sampled for s in self.slots for r in s.requests)

    @property
    def keys(self) -> list[tuple[str, bytes]]:
        """Slot-ordered (tenant, key) pairs — the keycache.stacked input."""
        return [(s.tenant, s.key) for s in self.slots]

    @property
    def occupancy(self) -> float:
        return self.blocks / self.bucket

    def materialise(self, counters: bool = True, sched=None) -> None:
        """Build the flat u32 dispatch arrays: payload words, per-block
        LE counter words, the per-block slot-index vector, and the
        request-layout ``runs``. Flat (4N,) words on purpose: the dense
        jit-boundary layout every models entry point shares
        (models/aes.py:_as_block_words). Padding blocks stay at slot 0 /
        zero counters / zero payload — their keystream (and, for GCM,
        their GHASH lane) is discarded by ``req_spans``' offsets.

        ``counters=False`` (the native-tier server, ctr mode only)
        skips the counter array and the slot vector entirely: the host
        tier consumes ``runs`` — per-request (slot, start, nblocks,
        nonce) — and generates counters inside C, so materialising an
        (N, 4) array it would never read is a pure memory-bandwidth tax
        at exactly the rungs where bandwidth is the budget.

        ``sched`` (the keycache's StackedSchedules) is required for GCM
        batches: each request's AAD prefix state Y_aad = GHASH(H, A) is
        computed HOST-side here with its slot's H (``sched.h_ints``)
        and injected into the fused kernel's first data row — the
        variable-length AAD never enters the fixed dispatch shape.

        Assembly is allocation-lean — it sits between every payload
        byte and the engine: requests pack contiguously, so padding
        exists only as a TAIL and only the tail is zeroed (a full
        ``np.zeros`` re-touched every cache line before the copy
        overwrote it); a ctr request exactly filling its rung skips
        the payload copy entirely (the request's own bytes viewed as
        words ARE the dispatch array — reads only downstream)."""
        if self.mode in GCM_MODES:
            self._materialise_gcm(sched)
            return
        if self.mode == "cbc":
            self._materialise_cbc()
            return
        if self.mode == "rc4":
            self._materialise_rc4()
            return
        runs = []
        spans = []
        off = 0
        for si, slot in enumerate(self.slots):
            for req in slot.requests:
                runs.append((si, off, req.nblocks, req.nonce))
                spans.append((off, req.nblocks))
                off += req.nblocks
        self.runs = runs
        self.req_spans = spans
        reqs = self.requests
        if len(reqs) == 1 and reqs[0].nblocks == self.bucket:
            req = reqs[0]
            self.words = packing.np_bytes_to_words(
                np.ascontiguousarray(req.payload, dtype=np.uint8))
            if counters:
                ctr = np.empty((self.bucket, 4), dtype=np.uint32)
                packing.np_ctr_le_blocks(req.nonce,
                                         _block_idx(self.bucket), out=ctr)
                self.ctr_words = ctr.reshape(-1)
                self.slot_index = np.zeros(self.bucket, dtype=np.uint32)
            return
        words = np.empty(4 * self.bucket, dtype=np.uint32)
        ctr = (np.empty((self.bucket, 4), dtype=np.uint32)
               if counters else None)
        slot_index = (np.zeros(self.bucket, dtype=np.uint32)
                      if counters else None)
        off = 0
        for si, slot in enumerate(self.slots):
            for req in slot.requests:
                n = req.nblocks
                words[4 * off:4 * (off + n)] = packing.np_bytes_to_words(
                    req.payload)
                if counters:
                    packing.np_ctr_le_blocks(req.nonce, _block_idx(n),
                                             out=ctr[off:off + n])
                    slot_index[off:off + n] = si
                off += n
        if off < self.bucket:  # the padding tail (zero contract above)
            words[4 * off:] = 0
            if counters:
                ctr[off:] = 0
        self.words = words
        if counters:
            self.ctr_words = ctr.reshape(-1)
            self.slot_index = slot_index

    def _materialise_gcm(self, sched) -> None:
        """The GCM batch layout (aead/gcm.py module docstring): per
        request, row 0 = J0 under a zero data word (its CTR output is
        E_K(J0)), rows 1..n = payload under inc32 counters; ``seg_keep``
        resets the Horner carry at each segment, ``inject_words`` seeds
        each segment with its host-computed AAD prefix state."""
        if sched is None or sched.h_ints is None:
            raise ValueError("GCM materialise needs the stacked "
                             "schedules' H (keycache.stacked mode=gcm)")
        n_rows = self.bucket
        words = np.zeros(4 * n_rows, dtype=np.uint32)
        ctr = np.zeros((n_rows, 4), dtype=np.uint32)
        slot_index = np.zeros(n_rows, dtype=np.uint32)
        inject = np.zeros((n_rows, 4), dtype=np.uint32)
        keep = np.ones(n_rows, dtype=np.uint32)
        spans = []
        off = 0
        for si, slot in enumerate(self.slots):
            h = sched.h_ints[si]
            for req in slot.requests:
                n = req.nblocks
                # Admission derived J0 (96-bit concat or the host
                # GHASH path for other IV lengths); the 12-byte concat
                # fallback keeps pre-admission callers (tests, tools)
                # working.
                j0 = (bytes(req.j0) if getattr(req, "j0", b"")
                      else bytes(req.iv) + b"\x00\x00\x00\x01")
                aead_ghash.np_gcm_ctr_blocks(
                    j0, _block_idx(n + 1), out=ctr[off:off + n + 1])
                words[4 * (off + 1):4 * (off + 1 + n)] = (
                    packing.np_bytes_to_words(req.payload))
                slot_index[off:off + n + 1] = si
                keep[off] = 0          # J0 row: GHASH lane discarded
                keep[off + 1] = 0      # first data row: fresh Horner chain
                y_aad = (aead_ghash.ghash_int(
                    h, aead_ghash.pad16(bytes(req.aad))) if req.aad else 0)
                if y_aad:
                    inject[off + 1] = packing.np_bytes_to_words(
                        np.frombuffer(gf.int_to_block(y_aad), np.uint8))
                spans.append((off + 1, n))
                off += n + 1
        self.words = words
        self.ctr_words = ctr.reshape(-1)
        self.slot_index = slot_index
        self.inject_words = inject.reshape(-1)
        self.seg_keep = keep
        self.req_spans = spans
        self.runs = None

    def _materialise_cbc(self) -> None:
        """The CBC-decrypt batch layout: ``ctr_words`` carries the PREV
        stream — each request's IV at its first block, then its own
        ciphertext shifted one block (P_i = D(C_i) ^ C_{i-1} reads only
        ciphertext, which is why decrypt batches at all)."""
        words = np.zeros(4 * self.bucket, dtype=np.uint32)
        prev = np.zeros(4 * self.bucket, dtype=np.uint32)
        slot_index = np.zeros(self.bucket, dtype=np.uint32)
        spans = []
        off = 0
        for si, slot in enumerate(self.slots):
            for req in slot.requests:
                n = req.nblocks
                w = packing.np_bytes_to_words(req.payload)
                words[4 * off:4 * (off + n)] = w
                prev[4 * off:4 * off + 4] = packing.np_bytes_to_words(
                    np.frombuffer(bytes(req.iv), np.uint8))
                if n > 1:
                    prev[4 * (off + 1):4 * (off + n)] = w[:4 * (n - 1)]
                slot_index[off:off + n] = si
                spans.append((off, n))
                off += n
        self.words = words
        self.ctr_words = prev
        self.slot_index = slot_index
        self.req_spans = spans
        self.runs = None

    def _materialise_rc4(self) -> None:
        """The RC4 batch layout: ``ctr_words`` carries each chunk's
        cached keystream slice (reserved at admission from the
        session's prefetched window, serve/session.py). The dispatch is
        one key-oblivious XOR — no schedules, no counters, no per-slot
        state — so the slot axis exists only for grouping/metrics and
        padding keystream is simply zero (zero XOR zero is discarded by
        ``req_spans`` like every other padding block)."""
        words = np.zeros(4 * self.bucket, dtype=np.uint32)
        ks = np.zeros(4 * self.bucket, dtype=np.uint32)
        slot_index = np.zeros(self.bucket, dtype=np.uint32)
        spans = []
        off = 0
        for si, slot in enumerate(self.slots):
            for req in slot.requests:
                n = req.nblocks
                words[4 * off:4 * (off + n)] = packing.np_bytes_to_words(
                    req.payload)
                ks[4 * off:4 * (off + n)] = packing.np_bytes_to_words(
                    np.ascontiguousarray(req.ks, dtype=np.uint8))
                slot_index[off:off + n] = si
                spans.append((off, n))
                off += n
        self.words = words
        self.ctr_words = ks
        self.slot_index = slot_index
        self.req_spans = spans
        self.runs = None

    def split_output(self, out_words: np.ndarray) -> list[np.ndarray]:
        """Per-request output bytes (slot order, then request order —
        the ``requests`` property's order) from the batch's output,
        using the ``req_spans`` offsets materialise built (GCM spans
        skip each request's J0 row).

        A request spanning the ENTIRE dispatch buffer (the big-payload
        fast path: one request exactly filling its rung) gets a
        zero-copy view when the buffer is writable (the native tier's
        numpy output) — it holds nothing but the request's own bytes.
        Every other case COPIES: a partial view's ``.base`` would pin
        the whole per-dispatch buffer alive and hand each tenant a
        window over the other slots' output (and, on the native runs
        path, the rung-padding region) — the cross-tenant boundary the
        key cache is built to preserve — and a jax-backed buffer views
        as READ-ONLY where response payloads have always been
        caller-mutable."""
        flat = np.asarray(out_words, dtype=np.uint32).reshape(-1)
        spans = self.req_spans
        if spans is None:
            # Pre-materialise callers (tests, tools): ctr's contiguous
            # layout derives straight from the request order.
            spans, off = [], 0
            for req in self.requests:
                spans.append((off, req.nblocks))
                off += req.nblocks
        outs = []
        for off, n in spans:
            w = flat[4 * off:4 * (off + n)]
            if 4 * n != flat.size or off != 0 or not flat.flags.writeable:
                w = w.copy()
            outs.append(packing.np_words_to_bytes(w))
        return outs


def form_batches(requests: list[Request],
                 rungs: tuple[int, ...],
                 key_digest,
                 key_slots: int = DEFAULT_KEY_SLOTS) -> list[Batch]:
    """The rung-packer: group by (mode, tenant, key digest) in arrival
    order, then pack up to ``key_slots`` groups per batch, filling to
    the ladder ceiling and padding to the smallest rung that holds what
    was packed. A batch is flushed when it runs out of block capacity,
    when an unstarted group finds all K slots taken, or when the next
    group's key length (round count) OR MODE differs — ``nr``, the
    GHASH direction, and the CBC core are all static compile arguments,
    so neither may vary inside one dispatch (batches never mix modes).
    Capacity counts ``span_blocks`` (GCM requests carry their J0 row).
    Array materialisation is deferred to the caller (the server times
    it under its ``batch-formed`` span).
    """
    if key_slots < 1:
        raise ValueError("key_slots must be >= 1")
    ceiling = rungs[-1]
    groups: dict[tuple, list[Request]] = {}
    order: list[tuple] = []
    for req in requests:
        # rc4 chunks group by SESSION, not key: data chunks carry no
        # key (the KSA ran at session open), and per-session slots are
        # what lets the coalesce stats tell sessions apart — the XOR
        # itself is key-oblivious, so any grouping is correct.
        ident = (f"s{req.sid}" if req.mode == "rc4"
                 else key_digest(req.key))
        k = (req.mode, req.tenant, ident)
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(req)

    batches: list[Batch] = []
    cur_slots: list[Slot] = []
    cur_blocks = 0     # payload blocks packed (the occupancy numerator)
    cur_span = 0       # batch rows used (payload + GCM J0 rows)
    cur_nr = None
    cur_mode = None

    def flush():
        nonlocal cur_slots, cur_blocks, cur_span, cur_nr, cur_mode
        if cur_slots:
            bucket = bucket_for(cur_span, rungs)
            batches.append(Batch(cur_slots, bucket,
                                 cur_blocks, cur_nr, key_slots,
                                 mode=cur_mode))
            # The rung-packer's live distributions (obs/metrics.py):
            # payload blocks per formed batch, labeled by its rung (the
            # per-rung occupancy the SERVE artifact histograms post-hoc,
            # now continuously on /metrics) and mode (the per-workload
            # split), and key slots packed per batch (the coalesce
            # shape — fragmentation regressions show up as this
            # histogram collapsing toward 1).
            metrics.observe("serve_batch_blocks", cur_blocks,
                            rung=bucket, mode=cur_mode)
            metrics.observe("serve_batch_slots", len(cur_slots))
        cur_slots, cur_blocks, cur_span = [], 0, 0
        cur_nr = cur_mode = None

    for mode, tenant, digest in order:
        pending = groups[(mode, tenant, digest)]
        # rc4 has no AES round count; 0 is its uniform nr sentinel, so
        # the nr-flush rule keeps rc4 and AES work in separate batches
        # for free (they could never share a program anyway).
        nr = 0 if mode == "rc4" else ROUNDS[len(pending[0].key) * 8]
        if cur_nr is not None and (nr != cur_nr or mode != cur_mode):
            flush()
        if len(cur_slots) >= key_slots:
            flush()
        slot = None
        for req in pending:
            if cur_slots and cur_span + req.span_blocks > ceiling:
                flush()
                slot = None
            if slot is None:
                slot = Slot(tenant, digest, req.key, [], 0)
                cur_slots.append(slot)
                cur_nr = nr
                cur_mode = mode
            slot.requests.append(req)
            slot.blocks += req.nblocks
            cur_blocks += req.nblocks
            cur_span += req.span_blocks
    flush()
    return batches
