"""ot-serve: the online request path over the offline engines.

Everything below this package batches by construction — a sweep hands the
engines device-shaped arrays. Serving has to MAKE those arrays out of
many small, independent, differently-sized requests arriving whenever
they like, without recompiling and without letting one bad batch take
the process down. The design is the paper's phase split run in reverse
(SURVEY.md §2): instead of splitting one large buffer into independent
chunks for parallel workers, coalesce many independent requests into one
device-shaped dispatch — the same throughput lever the multicore-AES
literature pulls with threads and the GPU-AES line pulls with kernel
batching.

Modules (docs/SERVING.md has the full architecture):

* ``queue``    — admission control + backpressure: bounded depth,
  per-request deadline (``resilience.policy.Budget``), shed-on-overload
  stamped through the ``degrade()`` ledger.
* ``batcher``  — shape-bucketed continuous batching: requests coalesce
  per (tenant, key) into power-of-two block buckets from a fixed ladder,
  so steady-state serving replays compiled programs (the shape-unroll /
  recompile-storm hazard ``analysis.jaxpr_audit`` flags, solved at the
  batching layer).
* ``keycache`` — multi-tenant LRU of expanded key schedules keyed by key
  digest: rekeying per request costs a lookup, not a key expansion.
* ``server``   — the dispatch loop: watchdog-guarded scattered-CTR engine
  calls through the ``models.aes`` seams, per-request / per-batch obs
  spans, RetryPolicy on transient dispatch failure, per-request error
  responses when a batch dies (the server stays up).
* ``loadgen``  — closed-loop load generator with mixed request sizes.
* ``bench``    — ``python -m our_tree_tpu.serve.bench``: drives the
  server, reports p50/p95/p99 latency, goodput GB/s, batch occupancy,
  asserts zero post-warmup recompiles, writes a ``SERVE_r*.json``.

Layering: ``queue`` is stdlib+numpy+resilience+obs only (admission
logic runs without a backend in sight); the device boundary lives
entirely in ``server``/``keycache`` (and ``batcher``'s packing
helpers), which is why a queue overload test never compiles anything.
"""

from .queue import Request, RequestQueue, Response, ServeError  # noqa: F401
