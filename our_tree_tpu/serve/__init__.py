"""ot-serve: the online request path over the offline engines.

Everything below this package batches by construction — a sweep hands the
engines device-shaped arrays. Serving has to MAKE those arrays out of
many small, independent, differently-sized requests arriving whenever
they like, without recompiling and without letting one bad batch take
the process down. The design is the paper's phase split run in reverse
(SURVEY.md §2): instead of splitting one large buffer into independent
chunks for parallel workers, coalesce many independent requests into one
device-shaped dispatch — the same throughput lever the multicore-AES
literature pulls with threads and the GPU-AES line pulls with kernel
batching.

Modules (docs/SERVING.md has the full architecture):

* ``queue``    — admission control + backpressure: bounded depth,
  per-request deadline (``resilience.policy.Budget``), shed-on-overload
  stamped through the ``degrade()`` ledger.
* ``batcher``  — shape-bucketed continuous batching: requests coalesce
  per (tenant, key) into power-of-two block buckets from a fixed ladder,
  so steady-state serving replays compiled programs (the shape-unroll /
  recompile-storm hazard ``analysis.jaxpr_audit`` flags, solved at the
  batching layer).
* ``keycache`` — multi-tenant LRU of expanded key schedules keyed by key
  digest: rekeying per request costs a lookup, not a key expansion.
* ``lanes``    — the fault domains: one dispatch lane per visible
  device, each with its own watchdog deadline, RetryPolicy, health
  state machine (healthy/suspect/quarantined/probation), bit-exact
  cross-lane failover, canary probation, and journal-persisted
  quarantine. The ONLY device contact in the package (otlint's
  ``serve-lane-seam`` rule).
* ``server``   — the dispatch loop: drain -> form -> place on the lane
  pool; per-request / per-batch / per-lane obs spans; per-request error
  responses only when EVERY lane failed (the server stays up);
  graceful drain on shutdown (zero lost requests).
* ``loadgen``  — closed-loop load generator with mixed request sizes.
* ``wire``     — the framed request/response wire protocol (JSON header
  line + raw payload) the worker frontend and the ot-route router
  speak; stdlib-only, bounded on both sides.
* ``worker``   — ``python -m our_tree_tpu.serve.worker``: one BACKEND
  process (a whole Server behind a TCP frontend) — the router's unit
  of horizontal scale; READY line with bound ports, SIGTERM graceful
  drain, zero-lost exit gate (docs/SERVING.md, ot-route).
* ``bench``    — ``python -m our_tree_tpu.serve.bench``: drives the
  server, reports p50/p95/p99 latency, goodput GB/s, batch occupancy,
  per-lane dispatch/health breakdown, asserts zero post-warmup
  recompiles AND zero lost requests, writes a ``SERVE_r*.json``; also
  the serve-side quarantine release (``--unquarantine lane:<i>``).

Layering: ``queue`` is stdlib+numpy+resilience+obs only (admission
logic runs without a backend in sight); the device boundary lives
entirely in ``lanes`` — ``server`` orchestrates, ``batcher``/
``keycache`` stay host-side — which is why a queue overload test never
compiles anything.
"""

from .queue import Request, RequestQueue, Response, ServeError  # noqa: F401
