"""our_tree_tpu — a TPU-native parallel symmetric-cryptography framework.

Built from scratch in JAX/XLA/Pallas with the capabilities of the reference
repo maleiwhat/Our-Tree (see SURVEY.md for the full component map):

- AES-128/192/256 in ECB/CBC/CFB128/CTR with byte-granular streaming resume
  (models/aes.py), bit-exact against the reference's portable C oracle.
- Three compute engines behind one registry: "jnp" T-table gathers
  (correctness core), "bitslice" bit-plane boolean circuit, and "pallas"
  VMEM-tiled TPU kernels (ops/).
- ARC4 with the reference's split keystream/XOR phases (models/arc4.py) and
  the fused single-pass variant (models/rc4.py).
- Multi-chip sharding over a 1-D mesh with per-shard CTR counter offsets
  (parallel/).
- A native C runtime with pthread-parallel bulk ops and ctypes bindings
  (runtime/), and a unified benchmark harness + hex CLI emitting the
  reference's CSV results format (harness/).
"""

__version__ = "0.2.0"

from .models.aes import (  # noqa: F401
    AES,
    AES_DECRYPT,
    AES_ENCRYPT,
    CORES,
    register_core,
    resolve_engine,
)
from .models.arc4 import ARC4  # noqa: F401
from .models.rc4 import RC4  # noqa: F401
