"""our_tree_tpu — a TPU-native parallel symmetric-cryptography framework.

Built from scratch in JAX/XLA/Pallas toward the capabilities of the reference
repo maleiwhat/Our-Tree (see SURVEY.md). Implemented so far: AES-128/192/256
in ECB/CBC/CFB128/CTR modes with byte-granular streaming resume, and the ARC4
stream cipher with its split keystream/XOR phases — all bit-exact against the
reference's portable C implementation. In progress (SURVEY.md §7): multi-chip
sharding (parallel/), native C++ CPU backend (runtime/), benchmark harness and
CSV-results surface (harness/), and the bitsliced/Pallas TPU fast paths (ops/).
"""

__version__ = "0.1.0"

from .models.aes import AES, AES_DECRYPT, AES_ENCRYPT  # noqa: F401
from .models.arc4 import ARC4  # noqa: F401
