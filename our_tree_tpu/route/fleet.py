"""Fleet elasticity: the autoscaler, rolling upgrades, and the
replicated router tier (docs/SERVING.md, fleet elasticity).

The routing tier (route/proxy.py) assumes a FIXED backend set; this
module is the control loop that changes that set safely while traffic
is in flight, plus the machinery that makes the router itself
replaceable:

* **FleetSupervisor** — the autoscale/upgrade loop a router owner runs
  next to its ``Router``. Scale decisions come from the fleet's own
  reconnaissance (each backend's /healthz queue depth and lane
  occupancy, already polled by gossip, mirrored into the metrics
  registry as ``route_fleet_*`` gauges) with a hysteresis band between
  the grow and shrink thresholds, a consecutive-tick settle count, and
  a cooldown after every scale event — load spikes grow the fleet,
  noise does not flap it. Growing spawns a fresh ``serve.worker``
  through the ``resilience.isolate`` seam and admits it only through
  ``Router.add_backend`` (the bit-exact startup canary). Shrinking is
  always drain-then-remove: mark the victim draining (placement drops
  it immediately, non-punitively), SIGTERM it, wait for its zero-lost
  exit line, and only then remove it from the ring — so the
  minimal-motion rebalance moves exactly the departing member's keys
  and no request ever targets a dead socket.

* **Rolling upgrades** — ``roll_one`` replaces workers one at a time:
  boot the successor, cross-check it against the live fleet with
  ``Router.canary_check`` (the pinned startup canary, bit-exactly,
  WITHOUT granting membership), and only on a byte-identical answer
  admit it and begin draining the predecessor. Any mismatch aborts the
  roll: the successor is killed, the old worker keeps serving, and the
  abort is a counted, traced event — an upgrade can be wrong, but it
  cannot corrupt placement.

* **RouterServer + FailoverClient + gossip** — the replicated front
  door. ``RouterServer`` exposes a ``Router`` on the SAME framed wire
  the backends speak (serve/wire.py), so N router processes are N
  interchangeable front doors; a ``{"g": 1}`` frame on that wire is the
  gossip exchange — the peer answers its epoch-stamped membership view
  (ring digest included), and a replica adopts any higher-epoch view
  (join/leave/draining, each join re-proving bit-exactness through its
  own canary). ``FailoverClient`` is the loadgen-compatible submit
  facade over the peer list: a dead or killed router costs one
  reconnect-and-resend on the next peer (CTR/AEAD dispatch is
  replay-exact, so the resend's bytes are identical), never a lost
  request. ``python -m our_tree_tpu.route.fleet`` is the replica
  process entry — READY line, SIGTERM drain, zero-lost exit line, the
  worker lifecycle contract one tier up.

Fault points ``worker_slow_start`` and ``scale_stall`` (both
``@backend=`` scoped, resilience/faults.py) are wired into the spawn
and retire seams so CI can rehearse a slow-booting worker and a stalled
scale event without either ever reaching a rider.

Process contact rules: every socket this module opens rides the framed
wire helpers (it is a ``route-backend-seam`` seam file next to
route/proxy.py), and every child process rides ``resilience.isolate``
(``subprocess-isolate``). No jax anywhere on this tier, by rule.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time
from dataclasses import dataclass, field

import numpy as np

from ..obs import metrics, trace
from ..resilience import faults, isolate
from ..serve import wire
from ..serve.queue import (ERR_BAD_REQUEST, ERR_DISPATCH, ERR_SHED,
                           ERR_SHUTDOWN, ERR_TOO_LARGE, Response)
from .health import QUARANTINED
from .proxy import BackendSpec, Router

#: READY-line kinds (the spawn contract, serve/worker.py one tier up).
REPLICA_KIND = "ot-route-replica"
REPLICA_EXIT_KIND = "ot-route-replica-exit"


# ---------------------------------------------------------------------------
# Worker handles: how the supervisor owns one backend's process.
# ---------------------------------------------------------------------------


class ProcessWorkerHandle:
    """One spawned ``serve.worker`` process, owned through the
    ``resilience.isolate.ServiceChild`` seam (never a raw subprocess).

    The supervisor's handle contract (tests substitute an in-process
    twin): ``start()`` spawns and returns the READY-line
    ``BackendSpec`` (None if the child died or never answered),
    ``drain()`` SIGTERMs and returns the exit-line accounting,
    ``kill()`` ends it now, ``alive()`` polls it. ``read_line`` and
    ``stop`` block on pipes/waitpid, so both run in the default
    executor — the supervisor shares the router's event loop and must
    never stall it.
    """

    def __init__(self, name: str, argv: list, *, env: dict | None = None,
                 ready_deadline_s: float = 180.0,
                 drain_deadline_s: float = 90.0):
        self.name = name
        self.argv = list(argv)
        if env is None:
            # The spawner strips OT_FAULTS (route/bench.py convention):
            # injected faults rehearse the SUPERVISOR's seams, not every
            # child's first dispatch.
            env = {k: v for k, v in os.environ.items() if k != "OT_FAULTS"}
        self.env = env
        self.ready_deadline_s = float(ready_deadline_s)
        self.drain_deadline_s = float(drain_deadline_s)
        self.child: isolate.ServiceChild | None = None
        self.ready: dict | None = None

    async def start(self) -> BackendSpec | None:
        # Popen (pipes, fork/exec) blocks; the supervisor shares the
        # router's event loop, so the spawn runs in the executor like
        # read_line/stop below.
        self.child = await asyncio.to_thread(
            isolate.spawn_service, self.argv, env=self.env,
            name=f"fleet:{self.name}")
        loop = asyncio.get_running_loop()
        line = await loop.run_in_executor(
            None, self.child.read_line, self.ready_deadline_s)
        if not line:
            return None
        try:
            doc = json.loads(line)
        except ValueError:
            return None
        if not isinstance(doc, dict) or not doc.get("port"):
            return None
        self.ready = doc
        return BackendSpec(self.name, "127.0.0.1", int(doc["port"]),
                           doc.get("status_port"), pid=doc.get("pid"))

    async def drain(self) -> dict:
        """SIGTERM -> graceful worker drain -> reap; returns the FULL
        exit-line accounting plus ``{"rc": ...}`` (``lost`` is None when
        the child never printed one — a crash, not a drain). The bench's
        zero-lost / zero-recompile gates read the same doc the classic
        teardown parses."""
        if self.child is None:
            return {"rc": None, "lost": None}
        loop = asyncio.get_running_loop()
        rc = await loop.run_in_executor(
            None, self.child.stop, self.drain_deadline_s)
        out, _err = self.child.drain_output()
        res: dict = {"lost": None}
        for raw in reversed(out.splitlines()):
            try:
                doc = json.loads(raw)
            except ValueError:
                continue
            if isinstance(doc, dict) and "lost" in doc:
                res.update(doc)
                break
        res["rc"] = rc
        return res

    async def kill(self) -> None:
        """End the child NOW (the abort path: a successor that failed
        its canary, a spawn that never went ready). stop(0) degrades
        SIGTERM straight into the group SIGKILL."""
        if self.child is None:
            return
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.child.stop, 0.0)

    def alive(self) -> bool:
        return self.child is not None and self.child.alive()


def worker_argv(*, engine: str = "auto", bucket_min: int = 32,
                bucket_max: int = 4096, queue_depth: int = 1024,
                tenant_depth_frac: float = 1.0,
                dispatch_deadline: float | None = None,
                modes: str = "ctr", lanes: int | None = None) -> list:
    """The ``serve.worker`` argv the fleet boots new backends with —
    one template per fleet, so every generation serves the same ladder
    (a scaled-up worker must be a bit-exact peer, not a variant)."""
    argv = ["-m", "our_tree_tpu.serve.worker", "--port", "0",
            "--status-port", "0", "--engine", engine,
            "--bucket-min", str(bucket_min),
            "--bucket-max", str(bucket_max),
            "--queue-depth", str(queue_depth),
            "--tenant-depth-frac", str(tenant_depth_frac),
            "--modes", modes]
    if dispatch_deadline is not None:
        argv += ["--dispatch-deadline", str(dispatch_deadline)]
    if lanes is not None:
        argv += ["--lanes", str(lanes)]
    return [sys.executable] + argv


# ---------------------------------------------------------------------------
# The supervisor.
# ---------------------------------------------------------------------------


@dataclass
class FleetConfig:
    #: fleet size floor/ceiling the autoscaler moves between
    min_workers: int = 1
    max_workers: int = 4
    #: hysteresis band (mean /healthz queue depth per placeable
    #: backend): grow above ``up_depth``, shrink below ``down_depth``
    #: — the gap between them is what keeps steady load from flapping
    up_depth: float = 8.0
    down_depth: float = 1.0
    #: lane-occupancy grow trigger (mean inflight / lanes): a fleet can
    #: be saturated with an empty queue when requests are large
    up_busy: float = 0.95
    #: consecutive out-of-band ticks before acting (settle count).
    #: ``down_settle_ticks`` defaults to the same, but a drive usually
    #: wants it much larger: pressure is bursty (grow on a short
    #: streak), idleness must be sustained (shrink only when the lull
    #: is real — a few calm polls mid-load are noise, not a signal).
    settle_ticks: int = 2
    down_settle_ticks: int | None = None
    #: minimum seconds between scale events (the cooldown)
    cooldown_s: float = 3.0
    #: supervisor poll period
    poll_every_s: float = 0.25
    #: refresh gossip each tick (off when the router's own gossip loop
    #: already polls — double-polling is harmless but noisy)
    refresh_gossip: bool = True
    #: spawned-worker name prefix (ring identity: ``<prefix><seq>``)
    name_prefix: str = "w"
    #: retained fleet-event ledger entries (the /fleetz tail)
    max_events: int = 256
    #: scaling policy: ``"static"`` (the depth/busy/shed thresholds
    #: above — the default until a TPU-measured capacity baseline
    #: exists) or ``"headroom"`` — grow when the measured offered load
    #: reaches ``headroom_frac`` of the fleet's MEASURED capacity (the
    #: per-worker blocks/s estimate each backend's pulse engine
    #: publishes on /healthz, summed over placeable members). The
    #: static triad stays active as the safety net in headroom mode:
    #: a fleet whose capacity estimate is missing or stale still grows
    #: on depth/busy/shed.
    policy: str = "static"
    headroom_frac: float = 0.80


class FleetSupervisor:
    """The fleet-lifecycle control loop over one ``Router``.

    Owns the worker handles it spawned (or adopted), decides scale
    events off the gossip reconnaissance, and is the membership
    AUTHORITY for the replicated router tier: every join/leave bumps
    ``epoch``, and ``view()`` is the epoch-stamped document gossip
    serves to replica routers.
    """

    def __init__(self, router: Router, factory, config: FleetConfig
                 | None = None, clock=time.monotonic):
        self.router = router
        self.factory = factory
        self.config = config or FleetConfig()
        self._clock = clock
        self.workers: dict[str, object] = {}
        self.epoch = 1
        self.scale_ups = 0
        self.scale_downs = 0
        self.rolled = 0
        self.roll_aborts = 0
        self.stalls = 0
        self.spawn_failures = 0
        self.drained_lost = 0
        #: every drained worker's full exit-line doc (+name) — the
        #: bench's "workers" artifact section when the supervisor owns
        #: the whole lifecycle (classic drives parse _teardown instead)
        self.exit_docs: list[dict] = []
        self.events: list[dict] = []
        self._seq = 0
        self._up_ticks = 0
        self._down_ticks = 0
        self._last_event_t: float | None = None
        self._last_sheds = 0
        #: offered-load watermarks: last signals() wall-clock and the
        #: fleet-wide dispatched-bytes total at that instant — the
        #: deltas are the measured offered blocks/s the headroom
        #: policy compares against the capacity estimate.
        self._last_signal_t: float | None = None
        self._last_bytes_out = 0.0
        self._last_signals: dict = {}
        self._task: asyncio.Task | None = None
        #: serializes scale EVENTS (up/down/roll): each one awaits a
        #: child boot or drain, and an interleaved tick() deciding off
        #: the mid-event membership could otherwise shrink a fleet the
        #: roll is about to shrink again — straight through the floor.
        self._resize = asyncio.Lock()
        self._gauges()

    # -- bookkeeping -------------------------------------------------------
    def _gauges(self) -> None:
        metrics.gauge("route_fleet_size", len(self.router.backends))

    def _event(self, kind: str, worker: str, **attrs) -> dict:
        ev = {"kind": kind, "worker": worker,
              "t_s": round(self._clock(), 3), "epoch": self.epoch,
              "size": len(self.router.backends), **attrs}
        self.events.append(ev)
        del self.events[:-self.config.max_events]
        metrics.counter("route_scale_events", kind=kind)
        trace.point("fleet-scale", kind=kind, worker=worker,
                    size=ev["size"], epoch=self.epoch)
        self._last_event_t = self._clock()
        return ev

    @property
    def resizing(self) -> bool:
        """True while a scale event (up/down/roll) is in flight — the
        bench's settle loop waits this out before reading the fleet
        size as final (a queued event may still move it)."""
        return self._resize.locked()

    def adopt(self, name: str, handle) -> None:
        """Take ownership of a pre-spawned worker already registered
        with the router (the drive boots the floor fleet itself, then
        hands the handles over so retire/roll own the full lifecycle)."""
        self.workers[name] = handle
        self._seq = max(self._seq, len(self.workers))
        self._gauges()

    def view(self) -> dict:
        """The epoch-stamped membership view gossip serves: enough for
        a replica to rebuild the SAME ring (names are the ring
        identity) and the same placement intent (draining flags ride
        along, non-punitively)."""
        members = []
        for name, b in sorted(self.router.backends.items()):
            members.append({
                "name": name, "host": b.spec.host, "port": b.spec.port,
                "status_port": b.spec.status_port,
                "state": b.health.state,
                "draining": b.health.draining,
            })
        return {"epoch": self.epoch, "members": members,
                "ring": self.router.ring.digest()}

    def fleetz(self) -> dict:
        """The /fleetz document (route/status.py serves it): live fleet
        size + thresholds + the recent scale-event tail — the operator's
        answer to "what has the autoscaler been doing"."""
        c = self.config
        return {
            "size": len(self.router.backends),
            "owned": sorted(self.workers),
            "min_workers": c.min_workers, "max_workers": c.max_workers,
            "up_depth": c.up_depth, "down_depth": c.down_depth,
            "cooldown_s": c.cooldown_s,
            "policy": c.policy,
            "headroom_frac": c.headroom_frac,
            "signals": dict(self._last_signals),
            "epoch": self.epoch,
            "scale_ups": self.scale_ups, "scale_downs": self.scale_downs,
            "rolled": self.rolled, "roll_aborts": self.roll_aborts,
            "stalls": self.stalls, "spawn_failures": self.spawn_failures,
            "drained_lost": self.drained_lost,
            "events": self.events[-32:],
        }

    # -- signals -----------------------------------------------------------
    def signals(self) -> dict:
        """The autoscale inputs off the gossip reconnaissance: mean
        /healthz queue depth and lane occupancy across polled placeable
        backends, plus the router-side shed delta since the last tick
        (backpressure that already reached the router). Mirrored into
        the registry as gauges — the same numbers an operator's scrape
        sees are the numbers the loop acted on."""
        depths, inflight, lanes = [], 0.0, 0.0
        capacity_bps = 0.0
        for b in self.router.backends.values():
            doc = b.last_healthz
            if not isinstance(doc, dict) or not b.health.placeable():
                continue
            q = doc.get("queue")
            ln = doc.get("lanes")
            if isinstance(q, dict):
                depths.append(float(q.get("depth", 0)))
            if isinstance(ln, dict):
                inflight += float(ln.get("inflight", 0))
                lanes += max(float(ln.get("count", 1)), 1.0)
            # The per-worker MEASURED capacity estimate (obs/pulse.py
            # via the worker's /healthz "capacity" section): summed
            # over placeable members = the fleet's live ceiling.
            cap = doc.get("capacity")
            if isinstance(cap, dict):
                try:
                    capacity_bps += float(
                        cap.get("total_blocks_per_s", 0) or 0)
                except (TypeError, ValueError):
                    pass
        sheds_now = self.router.shed_retries + self.router.router_sheds
        shed_delta = sheds_now - self._last_sheds
        self._last_sheds = sheds_now
        # Offered load, measured router-side: dispatched payload bytes
        # across ALL backends (16-byte blocks) over the tick interval.
        # At saturation dispatch tracks capacity, so offered/capacity
        # approaches 1.0 — exactly when headroom is gone.
        now = self._clock()
        bytes_now = sum(float(b.bytes_out)
                        for b in self.router.backends.values())
        dt = (now - self._last_signal_t
              if self._last_signal_t is not None else 0.0)
        offered_bps = (max(bytes_now - self._last_bytes_out, 0.0) / 16.0
                       / dt if dt > 0 else 0.0)
        shed_rate = (shed_delta / dt) if dt > 0 else 0.0
        self._last_signal_t = now
        self._last_bytes_out = bytes_now
        depth = sum(depths) / len(depths) if depths else 0.0
        busy = (inflight / lanes) if lanes else 0.0
        headroom = (offered_bps / capacity_bps) if capacity_bps > 0 else 0.0
        metrics.gauge("route_fleet_depth", depth)
        metrics.gauge("route_fleet_busy", busy)
        metrics.gauge("route_fleet_shed_rate", shed_rate)
        metrics.gauge("route_fleet_capacity_blocks", capacity_bps)
        metrics.gauge("route_fleet_offered_blocks", offered_bps)
        if shed_delta:
            metrics.counter("route_fleet_shed_seen", shed_delta)
        sig = {"depth": depth, "busy": busy, "shed": shed_delta,
               "shed_rate": round(shed_rate, 3),
               "capacity_bps": round(capacity_bps, 3),
               "offered_bps": round(offered_bps, 3),
               "headroom_used": round(headroom, 4),
               "polled": len(depths)}
        self._last_signals = sig
        return sig

    # -- the loop ----------------------------------------------------------
    async def tick(self) -> str:
        """One decision pass; returns what it did (the bench narrates
        it). Hysteresis: the up/down depth thresholds bound a dead band,
        a decision needs ``settle_ticks`` consecutive out-of-band
        observations, and any event starts the cooldown window."""
        c = self.config
        if c.refresh_gossip:
            await self.router.gossip_once()
        sig = self.signals()
        self._gauges()
        now = self._clock()
        if (self._last_event_t is not None
                and now - self._last_event_t < c.cooldown_s):
            return "cooldown"
        grow = (sig["depth"] >= c.up_depth or sig["busy"] >= c.up_busy
                or sig["shed"] > 0)
        if c.policy == "headroom":
            # Measured-capacity policy (the ROADMAP payoff): grow when
            # offered load eats into the headroom band of the fleet's
            # MEASURED capacity. The static triad above stays live as
            # the safety net — a missing/stale capacity estimate must
            # never make the fleet blind to pressure. Shrink/floor
            # behavior is deliberately unchanged.
            grow = grow or (sig["capacity_bps"] > 0
                            and sig["offered_bps"]
                            >= c.headroom_frac * sig["capacity_bps"])
        shrink = (sig["depth"] <= c.down_depth and sig["busy"] < c.up_busy
                  and sig["shed"] == 0)
        if grow:
            self._up_ticks += 1
            self._down_ticks = 0
            if (self._up_ticks >= c.settle_ticks
                    and len(self.router.backends) < c.max_workers):
                self._up_ticks = 0
                return ("scaled-up" if await self.scale_up() else "stalled")
            return "pressure"
        self._up_ticks = 0
        if shrink:
            self._down_ticks += 1
            down_ticks = (c.down_settle_ticks
                          if c.down_settle_ticks is not None
                          else c.settle_ticks)
            if (self._down_ticks >= down_ticks
                    and len(self.workers) > 0
                    and len(self.router.backends) > c.min_workers):
                self._down_ticks = 0
                return ("scaled-down" if await self.scale_down()
                        else "stalled")
            return "idle"
        self._down_ticks = 0
        return "steady"

    async def run(self, stop_ev: asyncio.Event) -> None:
        """The supervisor loop (the drive runs it as a task next to the
        load): tick until told to stop."""
        while not stop_ev.is_set():
            await self.tick()
            try:
                await asyncio.wait_for(stop_ev.wait(),
                                       timeout=self.config.poll_every_s)
            except asyncio.TimeoutError:
                pass

    # -- scale events ------------------------------------------------------
    async def _boot(self, name: str):
        """Spawn one worker through the handle factory and wait for its
        READY spec. The ``worker_slow_start`` fault point injects a
        boot delay HERE — the seam where a slow worker stalls the scale
        event (never a rider: the fleet keeps serving on the old set
        while the newcomer boots)."""
        handle = self.factory(name)
        if faults.fire_backend("worker_slow_start", self._seq - 1):
            # The async twin of faults.injected_slow: same OT_SLOW_S
            # knob, but awaited — the supervisor shares the router's
            # event loop and must not block it to simulate a slow boot.
            trace.point("fault-slow-start", worker=name)
            try:
                slow_s = max(float(os.environ.get("OT_SLOW_S", 0.05)), 0.0)
            except ValueError:
                slow_s = 0.05
            await asyncio.sleep(slow_s)
        spec = await handle.start()
        return handle, spec

    async def scale_up(self, kind: str = "up") -> str | None:
        """Grow by one: spawn, READY, canary-gated join. Returns the
        new member's name, or None when the event stalled, the spawn
        died, or the canary rejected the newcomer (each a counted
        event; the serving fleet is untouched in every abort path)."""
        async with self._resize:
            return await self._scale_up(kind)

    async def _scale_up(self, kind: str = "up") -> str | None:
        if (kind == "up"
                and len(self.router.backends) >= self.config.max_workers):
            # Re-checked under the lock: the tick that queued this
            # event read the pre-event membership.
            return None
        if faults.fire_backend("scale_stall", self._seq):
            self.stalls += 1
            self._event("stall", "", seam="spawn")
            return None
        name = f"{self.config.name_prefix}{self._seq}"
        self._seq += 1
        with trace.span("fleet-spawn", worker=name):
            handle, spec = await self._boot(name)
            if spec is None:
                self.spawn_failures += 1
                await handle.kill()
                self._event("spawn-failed", name)
                return None
            await self.router.add_backend(spec)
            b = self.router.backends[name]
            if b.health.state == QUARANTINED:
                # The join canary failed or mismatched: placement never
                # trusted it — undo the join and retire the child.
                self.router.remove_backend(name)
                await handle.kill()
                self.spawn_failures += 1
                self._event("join-rejected", name)
                return None
        self.workers[name] = handle
        self.epoch += 1
        if kind == "up":
            self.scale_ups += 1
        self._gauges()
        self._event(kind, name)
        return name

    async def scale_down(self, name: str | None = None,
                         kind: str = "down") -> bool:
        """Shrink by one, always drain-then-remove: mark the victim
        draining (placement drops it now), SIGTERM it and wait for the
        zero-lost exit line, THEN remove it from the ring — the
        minimal-motion rebalance happens once, after the member is
        truly gone, and moves only its keys."""
        async with self._resize:
            return await self._scale_down(name, kind)

    async def _scale_down(self, name: str | None = None,
                          kind: str = "down") -> bool:
        if (kind == "down"
                and len(self.router.backends) <= self.config.min_workers):
            # Re-checked under the lock: a roll or another shrink may
            # have moved the fleet while this event waited its turn —
            # the floor holds no matter how the decisions interleaved.
            return False
        if name is None:
            owned = [n for n in reversed(list(self.workers))
                     if n in self.router.backends]
            if not owned:
                return False
            name = owned[0]
        handle = self.workers.get(name)
        if handle is None:
            return False
        b = self.router.backends.get(name)
        if b is not None and faults.fire_backend("scale_stall", b.idx):
            self.stalls += 1
            self._event("stall", name, seam="retire")
            return False
        with trace.span("fleet-drain", worker=name):
            if b is not None:
                b.health.note_gossip("draining")
                # Publish the draining flag NOW (epoch bump before the
                # drain, not only after the removal): replica routers
                # adopt the view and stop placing on the victim while
                # it is still finishing its in-flight work.
                self.epoch += 1
                # Release the victim's PARKED pool sockets and stop
                # re-parking: the worker's frontend drain waits out a
                # grace window on every open connection, and an idle
                # pooled socket would wedge that wait for the full
                # grace. In-flight exchanges keep their conns and
                # discard them on completion (pool_size 0 = no park).
                b.pool_size = 0
                b.close_pool()
            res = await handle.drain()
            if name in self.router.backends:
                self.router.remove_backend(name)
            self.workers.pop(name, None)
        self.epoch += 1
        self.exit_docs.append({"name": name, **res})
        lost = res.get("lost")
        if lost:
            self.drained_lost += int(lost)
        if kind == "down":
            self.scale_downs += 1
        self._gauges()
        self._event(kind, name, rc=res.get("rc"), lost=lost)
        return True

    async def roll_one(self, name: str | None = None) -> bool:
        """Replace ONE worker: boot the successor, cross-check it
        against the live fleet with the pinned startup canary
        bit-exactly (``Router.canary_check`` — membership is NOT
        granted yet), and only on a byte-identical answer admit it and
        drain the predecessor. Any mismatch aborts the roll — the
        successor dies, the old worker keeps serving."""
        async with self._resize:
            return await self._roll_one(name)

    async def _roll_one(self, name: str | None = None) -> bool:
        if name is None:
            candidates = [n for n in self.workers
                          if n in self.router.backends]
            if not candidates:
                return False
            name = candidates[0]
        succ = f"{self.config.name_prefix}{self._seq}"
        self._seq += 1
        with trace.span("fleet-roll", worker=name, successor=succ):
            handle, spec = await self._boot(succ)
            if spec is None:
                self.spawn_failures += 1
                self.roll_aborts += 1
                await handle.kill()
                self._event("roll-abort", name, successor=succ,
                            why="spawn-failed")
                return False
            ok, why = await self.router.canary_check(spec)
            if not ok:
                # The bit-exact handoff gate: the successor answered
                # the pinned canary wrong (or not at all). Old worker
                # stays; the roll is a counted abort, not a downgrade.
                self.roll_aborts += 1
                await handle.kill()
                self._event("roll-abort", name, successor=succ, why=why)
                return False
            await self.router.add_backend(spec)
            b = self.router.backends[succ]
            if b.health.state == QUARANTINED:
                self.router.remove_backend(succ)
                self.roll_aborts += 1
                await handle.kill()
                self._event("roll-abort", name, successor=succ,
                            why="join-canary")
                return False
            self.workers[succ] = handle
            self.epoch += 1
            await self._scale_down(name, kind="roll-out")
        self.rolled += 1
        self._event("roll", name, successor=succ)
        return True

    async def close(self, drain: bool = True) -> None:
        """Retire every owned worker (teardown). ``drain=False`` kills
        them (the abandon path)."""
        async with self._resize:
            await self._close(drain)

    async def _close(self, drain: bool) -> None:
        for name in list(reversed(list(self.workers))):
            handle = self.workers.pop(name)
            if drain:
                res = await handle.drain()
                if res.get("lost"):
                    self.drained_lost += int(res["lost"])
                self.exit_docs.append({"name": name, **res})
            else:
                await handle.kill()
            if name in self.router.backends:
                self.router.remove_backend(name)
            self.epoch += 1
        self._gauges()


# ---------------------------------------------------------------------------
# The replicated router tier: wire frontend, gossip, failover client.
# ---------------------------------------------------------------------------


class RouterServer:
    """A ``Router`` behind the framed wire (serve/wire.py) — the same
    protocol the backends speak, one tier up, so N router processes
    are interchangeable front doors for the same fleet. A ``{"g": 1}``
    frame is the gossip exchange: the answer carries ``view_fn()``'s
    epoch-stamped membership document instead of payload bytes.
    ``view_fn`` is the membership authority hook — the owner serves its
    supervisor's view, a replica serves the view it last adopted."""

    def __init__(self, router: Router, port: int = 0,
                 host: str = "127.0.0.1", view_fn=None,
                 max_frame_bytes: int = wire.MAX_PAYLOAD):
        self.router = router
        self._host = host
        self._port = int(port)
        self._view_fn = view_fn
        self._max_len = int(max_frame_bytes)
        self._srv: asyncio.AbstractServer | None = None
        self._conns: set[asyncio.Task] = set()
        self.port: int | None = None
        self.frames = 0
        self.gossip_frames = 0
        self.protocol_errors = 0

    async def start(self) -> None:
        self._srv = await asyncio.start_server(
            self._on_conn, self._host, self._port)
        self.port = self._srv.sockets[0].getsockname()[1]

    async def stop(self, grace_s: float = 5.0) -> None:
        if self._srv is not None:
            self._srv.close()
            await self._srv.wait_closed()
            self._srv = None
        if self._conns:
            _done, pending = await asyncio.wait(
                list(self._conns), timeout=max(grace_s, 0.0))
            for task in pending:
                task.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)

    def abort(self) -> None:
        """Die NOW: close the listener and cancel every connection
        mid-frame — the in-process stand-in for SIGKILL (the CI drive
        kills a real replica process; tests kill this). Clients see a
        torn connection, exactly as they would from a dead process."""
        if self._srv is not None:
            self._srv.close()
            self._srv = None
        for task in list(self._conns):
            task.cancel()

    def _on_conn(self, reader, writer) -> None:
        task = asyncio.ensure_future(self._serve_conn(reader, writer))
        self._conns.add(task)
        task.add_done_callback(self._conns.discard)

    async def _serve_conn(self, reader, writer) -> None:
        try:
            while True:
                try:
                    frame = await wire.read_frame(reader, self._max_len)
                except wire.FrameTooLarge as e:
                    # The router frontend's half of the frame-bound
                    # hardening (serve/worker.py has the backend's): the
                    # declared length failed validation BEFORE any
                    # allocation, the header parsed, so answer a TYPED
                    # error frame — and when the declared payload is
                    # modest enough to drain, keep the connection.
                    self.protocol_errors += 1
                    try:
                        writer.write(wire.encode_frame(
                            {"ok": False, "error": ERR_TOO_LARGE,
                             "detail": f"wire: {e}"}))
                        await writer.drain()
                    except Exception:  # noqa: BLE001 - peer already gone
                        return
                    if 0 <= e.declared <= 4 * self._max_len and \
                            await wire.skip_payload(reader, e.declared):
                        continue
                    return
                except wire.WireError as e:
                    # A torn or unparseable frame leaves no boundary to
                    # trust: answer the typed error (best effort), then
                    # close — but never a silent reset.
                    self.protocol_errors += 1
                    try:
                        writer.write(wire.encode_frame(
                            {"ok": False, "error": ERR_BAD_REQUEST,
                             "detail": f"wire: {e}"}))
                        await writer.drain()
                    except Exception:  # noqa: BLE001 - peer already gone
                        pass
                    return
                if frame is None:
                    return
                header, payload = frame
                if header.get("g"):
                    self.gossip_frames += 1
                    epoch, view = (self._view_fn()
                                   if self._view_fn is not None
                                   else (0, {}))
                    writer.write(wire.encode_frame(
                        {"g": 1, "epoch": epoch},
                        json.dumps(view).encode("utf-8")))
                    await writer.drain()
                    continue
                self.frames += 1
                await self._answer(writer, header, payload)
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - peer already gone
                pass

    async def _answer(self, writer, header: dict, payload: bytes) -> None:
        """One request frame -> ``Router.submit`` -> one response frame
        (the ``serve.worker`` answer shape, so a client cannot tell a
        router from a backend — which is the point)."""
        try:
            key = bytes.fromhex(str(header.get("k", "")))
            nonce = bytes.fromhex(str(header.get("n", "")))
            iv = bytes.fromhex(str(header.get("iv", "")))
            aad = bytes.fromhex(str(header.get("a", "")))
            tag = bytes.fromhex(str(header.get("tg", "")))
        except ValueError:
            key = nonce = iv = aad = tag = b""
        try:
            deadline = header.get("deadline_s")
            deadline = float(deadline) if deadline is not None else None
        except (TypeError, ValueError):
            deadline = None
        resp = await self.router.submit(
            str(header.get("t", "")), key, nonce, payload,
            deadline_s=deadline, mode=str(header.get("m") or "ctr"),
            iv=iv, aad=aad, tag=tag)
        if resp.ok:
            out = {"ok": True, "batch": resp.batch}
            if resp.tag is not None:
                out["tg"] = resp.tag.hex()
            body = (resp.payload.tobytes()
                    if hasattr(resp.payload, "tobytes")
                    else bytes(resp.payload or b""))
        else:
            out = {"ok": False, "error": resp.error,
                   "detail": resp.detail, "batch": resp.batch}
            body = b""
        out["pid"] = os.getpid()
        if resp.ledger is not None:
            out["lg"] = resp.ledger
        writer.write(wire.encode_frame(out, body))
        await writer.drain()


async def gossip_exchange(host: str, port: int, epoch: int,
                          timeout_s: float = 2.0) -> dict | None:
    """One gossip round trip against a peer router's wire port:
    ``{"g": 1, "epoch": E}`` out, the peer's epoch-stamped view back.
    None on any failure — gossip is reconnaissance, never load-bearing
    for an in-flight request."""
    async def once():
        reader, writer = await asyncio.open_connection(host, port)
        try:
            writer.write(wire.encode_frame({"g": 1, "epoch": epoch}))
            await writer.drain()
            frame = await wire.read_frame(reader)
            if frame is None:
                return None
            header, payload = frame
            if not header.get("g"):
                return None
            doc = json.loads(payload) if payload else {}
            if isinstance(doc, dict):
                doc["epoch"] = int(header.get("epoch", doc.get("epoch", 0)))
                return doc
            return None
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - peer already gone
                pass
    try:
        return await asyncio.wait_for(once(), timeout=max(timeout_s, 0.001))
    except Exception:  # noqa: BLE001 - unreachable IS the data point
        return None


async def adopt_view(router: Router, doc: dict) -> dict:
    """Fold a higher-epoch membership view into ``router``: joins run
    through ``add_backend`` (each newcomer re-proves bit-exactness
    against THIS router's pinned canary), leaves through
    ``remove_backend`` (minimal motion), and draining flags land
    non-punitively. Returns {"joined": [...], "left": [...]} for the
    caller's ledger. A stale window between views is safe by design:
    any backend serves any key, so placement disagreement costs an
    affinity miss, never a wrong answer."""
    members = {m["name"]: m for m in doc.get("members", [])
               if isinstance(m, dict) and m.get("name")}
    joined, left = [], []
    for name in list(router.backends):
        if name not in members:
            router.remove_backend(name)
            left.append(name)
    for name, m in sorted(members.items()):
        if name not in router.backends:
            try:
                await router.add_backend(BackendSpec(
                    name, str(m.get("host", "127.0.0.1")),
                    int(m["port"]), m.get("status_port")))
                joined.append(name)
            except (KeyError, TypeError, ValueError):
                continue
        b = router.backends.get(name)
        if b is not None and m.get("draining"):
            b.health.note_gossip("draining")
    want = doc.get("ring")
    if want and router.ring.digest() != want:
        # Same members must mean the same ring (the hash is
        # deterministic); a digest mismatch is a vnodes/config skew —
        # loud evidence, not silent divergence.
        trace.point("fleet-ring-skew", want=want,
                    have=router.ring.digest())
    trace.point("fleet-view-adopted", epoch=doc.get("epoch", 0),
                members=len(members), joined=len(joined), left=len(left))
    return {"joined": joined, "left": left}


class FailoverClient:
    """The loadgen-compatible submit facade over N router peers.

    Holds the peer list; each request rides one framed exchange against
    the current peer, and ANY transport failure — refused connect, torn
    frame, attempt timeout — advances to the next peer and RESENDS
    (CTR/AEAD dispatch is a pure function of the request bytes, so the
    replay is bit-identical wherever it lands). A SIGKILLed router
    therefore costs its in-flight requests one failover each, never a
    loss; only a dead WHOLE tier answers an error, after every peer was
    tried against the request deadline.

    Answered backpressure — ``shed`` (a worker queue was full) and
    ``dispatch-failed`` (the ring was mid-churn: a member draining, a
    stale pooled socket discarded with nowhere to redispatch) — is
    retried here too, after ``retry_backoff_s``: both mean "not now",
    not "never", and the client's retry budget is the request deadline.
    Only a mismatch-class error (bad tag, bad frame) surfaces at once.
    """

    def __init__(self, peers: list, attempt_timeout_s: float = 5.0,
                 deadline_s: float = 30.0,
                 max_frame_bytes: int = wire.MAX_PAYLOAD,
                 retry_backoff_s: float = 0.02, clock=time.monotonic):
        self.peers = [(str(h), int(p)) for h, p in peers]
        if not self.peers:
            raise ValueError("FailoverClient needs at least one peer")
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.deadline_s = float(deadline_s)
        self.max_frame_bytes = int(max_frame_bytes)
        self.retry_backoff_s = float(retry_backoff_s)
        self._clock = clock
        self._cur = 0
        self.submitted = 0
        self.failovers = 0
        self.backpressure_retries = 0

    async def _exchange(self, host: str, port: int, header: dict,
                        data: bytes):
        reader, writer = await asyncio.open_connection(host, port)
        try:
            writer.write(wire.encode_frame(header, data))
            await writer.drain()
            frame = await wire.read_frame(reader, self.max_frame_bytes)
            if frame is None:
                raise ConnectionError(f"router {host}:{port} closed "
                                      "mid-exchange")
            return frame
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - peer already gone
                pass

    async def submit(self, tenant: str, key: bytes, nonce: bytes, payload,
                     deadline_s: float | None = None, mode: str = "ctr",
                     iv: bytes = b"", aad: bytes = b"",
                     tag: bytes = b"") -> Response:
        data = (payload.tobytes() if hasattr(payload, "tobytes")
                else bytes(payload))
        total_s = self.deadline_s if deadline_s is None else float(deadline_s)
        header = {"t": tenant, "k": bytes(key).hex(),
                  "n": bytes(nonce).hex(),
                  "deadline_s": round(total_s, 3) or None}
        if mode != "ctr":
            header["m"] = mode
            if iv:
                header["iv"] = bytes(iv).hex()
            if aad:
                header["a"] = bytes(aad).hex()
            if tag:
                header["tg"] = bytes(tag).hex()
        self.submitted += 1
        t0 = self._clock()
        last: Exception | None = None
        dead_streak = 0
        while dead_streak < 2 * len(self.peers):
            left = total_s - (self._clock() - t0)
            if left <= 0:
                break
            host, port = self.peers[self._cur % len(self.peers)]
            try:
                rh, body = await asyncio.wait_for(
                    self._exchange(host, port, header, data),
                    timeout=max(min(self.attempt_timeout_s, left), 0.001))
            except Exception as e:  # noqa: BLE001 - fail over, then resend
                last = e
                dead_streak += 1
                self._cur += 1
                self.failovers += 1
                metrics.counter("route_client_failover")
                trace.point("client-failover", peer=f"{host}:{port}",
                            why=type(e).__name__)
                continue
            # An ANSWER — whatever it says, this peer (and the tier) is
            # alive, so the whole-tier-dead streak resets.
            dead_streak = 0
            if not rh.get("ok") and rh.get("error") == ERR_SHUTDOWN:
                # This router is draining; the fleet behind the tier is
                # still fine — move to a peer like any other failover.
                last = ConnectionError("router draining")
                self._cur += 1
                self.failovers += 1
                metrics.counter("route_client_failover")
                continue
            if not rh.get("ok") and rh.get("error") in (ERR_SHED,
                                                        ERR_DISPATCH):
                # Backpressure, not verdict: a full worker queue or a
                # mid-churn ring. Back off and resend — same peer, same
                # bytes — against the request deadline.
                last = ConnectionError(f"backpressure: {rh.get('error')}")
                self.backpressure_retries += 1
                metrics.counter("route_client_backpressure_retry")
                await asyncio.sleep(min(self.retry_backoff_s,
                                        max(left, 0.0)))
                continue
            tg = rh.get("tg")
            try:
                resp_tag = (bytes.fromhex(str(tg))
                            if isinstance(tg, str) and tg else None)
            except ValueError:
                resp_tag = None
            if rh.get("ok"):
                return Response(ok=True,
                                payload=np.frombuffer(body, np.uint8),
                                batch=rh.get("batch"),
                                ledger=rh.get("lg"), tag=resp_tag)
            return Response(ok=False, error=rh.get("error"),
                            detail=str(rh.get("detail", "")),
                            batch=rh.get("batch"), ledger=rh.get("lg"))
        detail = (f"{type(last).__name__}: {last}" if last is not None
                  else "request deadline spent before any peer answered")
        return Response(ok=False, error=ERR_DISPATCH,
                        detail=f"no router peer answered ({detail})")


# ---------------------------------------------------------------------------
# The replica router process entry.
# ---------------------------------------------------------------------------


@dataclass
class _ReplicaState:
    epoch: int = 0
    view: dict = field(default_factory=dict)
    adopts: int = 0


async def _replica_amain(args) -> int:
    from .proxy import RouterConfig

    specs = [BackendSpec(m["name"], m.get("host", "127.0.0.1"),
                         int(m["port"]), m.get("status_port"))
             for m in json.loads(args.backends)]
    cfg = RouterConfig(attempt_timeout_s=args.attempt_timeout,
                       deadline_s=args.deadline,
                       gossip_every_s=args.gossip_every,
                       max_frame_bytes=args.max_frame_bytes)
    router = Router(specs, cfg)
    await router.start()
    st = _ReplicaState(view={"epoch": 0, "members": []})

    def view_fn():
        return st.epoch, st.view

    server = RouterServer(router, args.port, view_fn=view_fn,
                          max_frame_bytes=args.max_frame_bytes)
    await server.start()
    peer = None
    if args.peer:
        host, _, port = args.peer.rpartition(":")
        peer = (host or "127.0.0.1", int(port))

    stop_ev = asyncio.Event()
    loop = asyncio.get_running_loop()
    import signal
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop_ev.set)

    async def gossip_loop():
        while True:
            await asyncio.sleep(max(args.gossip_every, 0.05))
            if peer is None:
                continue
            doc = await gossip_exchange(peer[0], peer[1], st.epoch)
            if doc and int(doc.get("epoch", 0)) > st.epoch:
                await adopt_view(router, doc)
                st.epoch = int(doc["epoch"])
                st.view = doc
                st.adopts += 1

    gtask = asyncio.ensure_future(gossip_loop())
    print(json.dumps({"kind": REPLICA_KIND, "port": server.port,
                      "pid": os.getpid(),
                      "backends": len(router.backends)}), flush=True)
    trace.point("replica-ready", port=server.port,
                backends=len(router.backends))
    await stop_ev.wait()
    gtask.cancel()
    try:
        await gtask
    except (asyncio.CancelledError, Exception):  # noqa: BLE001
        pass
    await server.stop()
    await router.stop()
    stats = router.stats()
    lost = stats["lost"]
    print(json.dumps({"kind": REPLICA_EXIT_KIND, "lost": lost,
                      "accepted": stats["accepted"],
                      "answered": stats["answered"],
                      "routed_ok": stats["routed_ok"],
                      "adopts": st.adopts,
                      "frames": server.frames,
                      "gossip_frames": server.gossip_frames}), flush=True)
    return 1 if lost else 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m our_tree_tpu.route.fleet",
        description="one replica router process for the replicated "
                    "front-door tier (docs/SERVING.md, fleet "
                    "elasticity)")
    ap.add_argument("--port", type=int, default=0,
                    help="wire port (0 = ephemeral; rides the READY "
                         "line)")
    ap.add_argument("--backends", required=True, metavar="JSON",
                    help="initial membership: JSON list of "
                         '{"name","host","port","status_port"}')
    ap.add_argument("--peer", default=None, metavar="HOST:PORT",
                    help="membership authority to gossip with (the "
                         "owner router's wire port); absent = static "
                         "membership")
    ap.add_argument("--gossip-every", type=float, default=0.25)
    ap.add_argument("--attempt-timeout", type=float, default=5.0)
    ap.add_argument("--deadline", type=float, default=30.0)
    ap.add_argument("--max-frame-bytes", type=int,
                    default=wire.MAX_PAYLOAD)
    args = ap.parse_args(argv)
    trace.ensure_run()
    return asyncio.run(_replica_amain(args))


if __name__ == "__main__":
    sys.exit(main())
