"""Per-backend health: the LANE state machine lifted to the host level.

One quarantine model across the whole repo, third instance: sweep units
(harness), dispatch lanes (serve), and now router backends all run the
same states with the same evidence conventions —

    healthy ──failure──> suspect ──failure──> quarantined
       ^                    │ clean answer       │  canary ok
       │<───"recovered"─────┘                    v
       │                                     probation
       │<──"released" (probation served)────────┘
                         (a probation failure goes straight back to
                          quarantined; a TIMEOUT quarantines from any
                          state — a hung backend is never transient)

with the same persistence: a quarantine appends a failure row for unit
``backend:<name>`` to the router journal — the SAME record
``resilience.journal`` uses for sweep units and serve lanes, so
``route.bench --unquarantine backend:<name>`` is the same
``clear_failures`` release edit operators already know, and a router
restart adopts recorded quarantines instead of re-learning them from
live failures.

Two evidence sources feed the machine, and they deliberately rank
differently:

* **Dispatch outcomes** (route/proxy.py) are ground truth: a served
  request is a success, a refused/torn one a failure, a hung one a
  timeout. Only dispatch evidence can DEGRADE a placeable backend.
* **Gossip** (``/healthz`` polling) is reconnaissance: an unreachable
  or ``degraded`` poll makes a backend suspect WITHOUT burning a
  rider's latency on it; a ``draining`` poll removes it from placement
  non-punitively (drain is intent, not sickness); an ``ok`` poll on a
  QUARANTINED backend is the trigger to canary it — gossip alone never
  releases (release requires the canary's bit-exact answer through the
  data path, same as a lane), and gossip alone never quarantines a
  healthy backend (one flaky scrape must not cost placement; repeated
  ones walk it to suspect, where the next dispatch decides).

State literals match serve/lanes.py byte-for-byte (healthy/suspect/...)
— the shared vocabulary is what lets obs tooling and the journal treat
``lane:3`` and ``backend:b1`` as the same kind of thing. They are
REDECLARED here rather than imported because ``serve.lanes`` imports
jax and this package is device-free by rule (``route-backend-seam``).
"""

from __future__ import annotations

import time

from ..obs import metrics, trace
from ..resilience import degrade

#: The lane-model states (serve/lanes.py literals, one vocabulary).
HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
PROBATION = "probation"
RELEASED = "released"

#: States that may receive traffic (draining excluded separately —
#: drain is not a health state, it is intent).
PLACEABLE = (HEALTHY, SUSPECT, PROBATION)


def backend_unit(name: str) -> str:
    """The backend's name in the shared quarantine ledger (journal
    failure rows, quarantine/release trace points, degrade kinds) — the
    router twin of ``lane:<i>`` and a sweep unit name."""
    return f"backend:{name}"


class BackendHealth:
    """One backend's health state, transition log, and ledger hooks."""

    def __init__(self, idx: int, name: str, probation_batches: int = 2,
                 journal=None, clock=time.monotonic):
        self.idx = int(idx)
        self.name = name
        self.state = HEALTHY
        #: drain intent from gossip ("draining" /healthz) — orthogonal
        #: to health: a draining backend is unplaceable but not sick,
        #: and flips back the moment gossip stops saying so.
        self.draining = False
        self.probation_batches = max(int(probation_batches), 1)
        self.probation_left = 0
        self.journal = journal
        self.failures = 0
        self.timeouts = 0
        self.gossip_fails = 0
        self.transitions: list[dict] = []
        self._clock = clock
        self._t0 = clock()

    # -- placement view ----------------------------------------------------
    def placeable(self) -> bool:
        return self.state in PLACEABLE and not self.draining

    # -- transitions -------------------------------------------------------
    def _to(self, new: str, why: str) -> None:
        old = self.state
        if old == new:
            return
        self.state = new
        self.transitions.append({
            "prev": old, "to": new, "why": why,
            "t_s": round(self._clock() - self._t0, 3)})
        metrics.counter("route_backend_transitions", backend=self.idx,
                        state=new)
        metrics.gauge("route_backend_placeable",
                      1 if self.placeable() else 0, backend=self.idx)
        trace.point("backend-state", backend=self.idx, unit=backend_unit(
            self.name), prev=old, to=new, why=why)

    def _quarantine(self, why: str) -> None:
        came_from = self.state
        self._to(QUARANTINED, why)
        if came_from == QUARANTINED:
            return  # already there: one ledger event per episode
        trace.point("quarantine", unit=backend_unit(self.name),
                    backend=self.idx, reason=why)
        degrade.degrade(f"quarantined:{backend_unit(self.name)}",
                        f"backend {self.name}: {why}")
        if self.journal is not None:
            self.journal.record_failure(backend_unit(self.name), why)

    def adopt_journal_quarantine(self, fails: int) -> None:
        """Start quarantined from recorded journal rows (router restart:
        the evidence is already on file — no new row is appended; a
        canary releases it once it proves bit-exact again)."""
        self._to(QUARANTINED, f"journal:{fails}")
        trace.point("quarantine", unit=backend_unit(self.name),
                    backend=self.idx, reason=f"journal:{fails}")
        degrade.degrade(
            f"quarantined:{backend_unit(self.name)}",
            f"backend {self.name}: {fails} failure row(s) on the route "
            f"journal (release: canary probe or route.bench "
            f"--unquarantine {backend_unit(self.name)})")

    # -- dispatch evidence -------------------------------------------------
    def note_success(self) -> None:
        if self.state == SUSPECT:
            self._to(HEALTHY, "recovered")
        elif self.state == PROBATION:
            self.probation_left -= 1
            if self.probation_left <= 0:
                self._to(RELEASED,
                         f"probation-served:{self.probation_batches}")
                trace.point("quarantine-release",
                            unit=backend_unit(self.name),
                            backend=self.idx)
                self._to(HEALTHY, "released")

    def note_failure(self, exc: BaseException) -> None:
        self.failures += 1
        if self.state == HEALTHY:
            self._to(SUSPECT, type(exc).__name__)
        else:  # a suspect or probation backend gets no second failure
            self._quarantine(type(exc).__name__)

    def note_timeout(self) -> None:
        # A hang is never transient (the lane rule): a backend that ate
        # a full attempt deadline cannot be trusted with another rider's
        # budget until a canary proves it.
        self.timeouts += 1
        self._quarantine("dispatch-timeout")

    # -- gossip evidence ---------------------------------------------------
    def note_gossip(self, status: str | None) -> None:
        """Fold one /healthz poll outcome in. ``status`` is the doc's
        ``status`` field, or None when the poll failed entirely."""
        if status == "draining":
            if not self.draining:
                self.draining = True
                trace.point("backend-draining", backend=self.idx,
                            unit=backend_unit(self.name))
            return
        self.draining = False
        if status == "ok":
            # Reconnaissance only: an ok scrape clears SUSPICION raised
            # by gossip, but a quarantined/probation backend's path back
            # runs through the canary + served traffic, not a scrape.
            if self.state == SUSPECT:
                self._to(HEALTHY, "gossip-ok")
            return
        # Unreachable or degraded: evidence against, but never straight
        # to quarantine — gossip cannot tell a dead backend from a
        # dropped scrape, so it walks healthy -> suspect and leaves the
        # verdict to the next dispatch (or keeps a sick state sick).
        self.gossip_fails += 1
        why = "gossip-unreachable" if status is None else f"gossip-{status}"
        if self.state == HEALTHY:
            self._to(SUSPECT, why)

    # -- canary verdicts (proxy runs the probe; health records it) ---------
    def canary_ok(self) -> None:
        self.probation_left = self.probation_batches
        self._to(PROBATION, "canary-ok")
        trace.point("backend-probe-ok", backend=self.idx,
                    unit=backend_unit(self.name))

    def canary_failed(self, why: str) -> None:
        metrics.counter("route_canary", backend=self.idx, outcome=why)
        self._quarantine(f"canary-{why}")

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        return {"state": self.state, "draining": self.draining,
                "failures": self.failures, "timeouts": self.timeouts,
                "gossip_fails": self.gossip_fails,
                "transitions": list(self.transitions)}
