"""The Router: consistent-hash placement, bit-exact failover, gossip.

The lane pool's contract, lifted one fault domain (serve/lanes.py is
the per-DEVICE version of every rule below; this module is the
per-HOST one):

* **Placement is affinity-first.** A request's ring key is
  ``ring.affinity_key(tenant, key)``; the ring's clockwise owner is
  the backend whose keycache already holds that key's expanded
  schedule — so steady-state routing does ZERO per-request schedule
  work on the backend, and the A/B in ``route.bench`` (affinity vs
  seeded-random routing over fresh backend sets) measures exactly that
  difference as keycache hit ratio.
* **Failover before error.** A failed, hung, or unreachable backend's
  request re-dispatches on the next ring node — CTR with explicit
  counters is side-effect-free replay, so the bytes are identical
  wherever it runs — and only when EVERY backend has been tried does
  the rider see an error (coded ``deadline`` if the last cause was a
  hang, else ``dispatch-failed``: the LanesExhausted convention).
* **Hangs are bounded and leave evidence.** Every attempt runs under
  ``min(attempt deadline, the request Budget's remainder)`` via
  ``asyncio.wait_for``; expiry ABANDONS the ``route-dispatch`` span
  (the orphaned begin is the kill evidence — ``obs.report --check
  --expected-orphans route-dispatch``, the watchdog convention) and
  quarantines the backend: a hang is never transient.
* **Backpressure propagates, it does not amplify.** A backend's
  ``shed`` answer is not a failure — the backend is healthy, just
  full. The router retries the REPLICA ring with exponential backoff
  (spreading the hot tenant's overflow instead of hammering the home
  node), and only when every placeable backend shed does it shed at
  the router — stamped ``route->shed`` through the shared ``degrade()``
  ledger, so an overloaded fleet can never report a healthy run.
* **Membership changes are minimal-motion and observable.** join/leave
  rebalance only the moved arcs (route/ring.py); the router diffs the
  placement of its recently-seen affinity keys across the change and
  traces ``ring-rebalance`` with the moved count — the operator's
  answer to "what did that deploy do to my cache locality".
* **Release runs through the data path.** Quarantined backends are
  canary-probed (gossip ``ok`` triggers it; a no-placeable-backend
  rescue forces it): the pinned canary request — whose expected bytes
  every backend matched at STARTUP, the cross-backend bit-exactness
  invariant — must come back bit-exact to earn probation. Probation is
  served through real traffic, then released. One quarantine ledger:
  journal failure rows under ``backend:<name>``, released by the same
  ``clear_failures`` edit as lanes and sweep units.

This module is the ONLY direct backend contact in the package besides
the fleet tier (otlint's ``route-backend-seam``): every socket a
backend ever sees from the router — framed requests, /healthz gossip
polls, canaries — is opened here, inside the guarded seams with the
fault points (``backend_fail``/``backend_hang``/``pool_stale``,
``@backend=<i>`` scoped) that let CI kill one fault domain and assert
the rest kept serving. The request transport is POOLED: each backend
keeps a small stack of idle persistent connections, fresh dials run
the shared ``RetryPolicy`` (reconnect-and-backoff, off-loop), and a
request that lands on a stale half-closed pooled socket fails over
through the existing ring-retry path — a dead socket costs one
redispatch, never an error (the ROUTE_r02 -> r04 wire-stage delta
records what pooling buys).
"""

from __future__ import annotations

import asyncio
import json
import socket
import time
from dataclasses import dataclass

import numpy as np

from ..obs import metrics, pulse, trace
from ..resilience import degrade, faults
from ..resilience import journal as journal_mod
from ..resilience.policy import Budget, RetryPolicy
from ..serve import transfer as transfer_mod
from ..serve import wire
from ..serve.queue import (ERR_BAD_REQUEST, ERR_DEADLINE, ERR_DISPATCH,
                           ERR_SHED, ERR_SHUTDOWN, Response)
from . import ring as ring_mod
from .health import QUARANTINED, RELEASED, BackendHealth, backend_unit

#: The pinned canary request: zero key, zero nonce, 4 zero blocks —
#: tiny, ladder-shaped, and identical on every backend (the startup
#: cross-backend comparison pins its expected bytes; no reference
#: implementation is needed router-side, keeping the package jax-free).
CANARY_TENANT = "_canary"
CANARY_KEY = b"\x00" * 16
CANARY_NONCE = b"\x00" * 16
CANARY_PAYLOAD = b"\x00" * 64


class BackendsExhausted(RuntimeError):
    """Every backend failed this request (rescue canaries included).
    ``causes`` is [(backend_idx, exc), ...] in attempt order;
    ``timed_out`` reflects the LAST cause — the error code the rider
    sees matches what finally stopped the request (the LanesExhausted
    convention, one fault domain up)."""

    def __init__(self, label: str, causes: list):
        self.causes = causes
        last = causes[-1][1] if causes else None
        self.timed_out = isinstance(last, asyncio.TimeoutError)
        names = ",".join(f"b{i}:{type(e).__name__}" for i, e in causes)
        super().__init__(
            f"request {label}: no backend could serve it "
            f"({names or 'no backends'})")


@dataclass
class BackendSpec:
    """How to reach one ot-serve backend: the framed request port plus
    the /healthz status port (both on ``host``). ``name`` is the ring
    identity — keep it stable across restarts of the same backend slot
    or its keys re-home."""

    name: str
    host: str
    port: int
    status_port: int | None = None
    #: the backend's process id when the deployer knows it (the READY
    #: line carries it) — pre-seeds the clock-skew ledger's pid mapping
    pid: int | None = None


class Backend:
    """Client-side handle: spec + health + counters + the contact seams."""

    def __init__(self, idx: int, spec: BackendSpec,
                 probation_batches: int = 2, journal=None,
                 clock=time.monotonic,
                 max_frame_bytes: int = wire.MAX_PAYLOAD,
                 pool_size: int = 8, reconnect_attempts: int = 3,
                 reconnect_base_s: float = 0.02,
                 connect_timeout_s: float = 2.0):
        self.idx = idx
        self.spec = spec
        self.max_frame_bytes = int(max_frame_bytes)
        #: idle pooled connections to this backend (LIFO: the warmest
        #: socket serves next); 0 disables pooling — dial per exchange
        self.pool_size = int(pool_size)
        self.reconnect_attempts = int(reconnect_attempts)
        self.reconnect_base_s = float(reconnect_base_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self._pool: list = []
        self.pool_hits = 0
        self.pool_dials = 0
        self.pool_stale = 0
        self.health = BackendHealth(idx, spec.name,
                                    probation_batches=probation_batches,
                                    journal=journal, clock=clock)
        self.dispatches = 0
        self.bytes_out = 0
        self.failures = 0
        self.timeouts = 0
        self.redispatches_in = 0
        self.sheds_seen = 0
        self.canaries = 0
        self.last_healthz: dict | None = None
        #: the backend's process id, learned from response frames (the
        #: wire handshake) — keys the clock-skew estimate to the trace
        #: files that pid wrote
        self.pid: int | None = spec.pid
        #: estimated backend-clock minus router-clock offset (µs), from
        #: canary exchanges: skew = reply ts - exchange midpoint
        self.skew_us: int | None = None

    # -- the framed-request seam -------------------------------------------
    async def exchange(self, header: dict, payload: bytes,
                       timeout_s: float):
        """One framed request/response round trip with a hard wall
        deadline over the WHOLE exchange (connect included — a backend
        that stopped accepting is as hung as one that stopped
        answering). Returns (response header, response payload)."""
        return await asyncio.wait_for(
            self._exchange(header, payload), timeout=max(timeout_s, 0.001))

    async def _exchange(self, header: dict, payload: bytes):
        reader, writer = await self._acquire()
        try:
            if faults.fire_backend("pool_stale", self.idx):
                # The injected half-closed pooled socket: the acquire
                # liveness check passed but first use fails — the rider
                # must ride the ring-retry failover, never an error.
                trace.point("fault-pool-stale", backend=self.idx)
                raise ConnectionResetError(
                    "injected stale pooled connection")
            writer.write(wire.encode_frame(header, payload))
            await writer.drain()
            frame = await wire.read_frame(reader, self.max_frame_bytes)
            if frame is None:
                raise ConnectionError(
                    f"backend {self.spec.name} closed mid-exchange")
        except BaseException:
            # Any failure mid-exchange — a stale socket's reset, a torn
            # frame, or the attempt deadline's cancel — leaves the
            # stream untrustworthy (a half-written request or half-read
            # response may be in flight): close it, never pool it back.
            # The raised error flows into the router's existing
            # ring-retry failover, so a stale pooled socket costs one
            # redispatch, not an error.
            self._discard(writer)
            raise
        self._release(reader, writer)
        return frame

    # -- the connection pool -----------------------------------------------
    async def _acquire(self):
        """An idle pooled connection, or a fresh dial. Pooled sockets
        are liveness-checked (EOF/half-close seen by the transport) —
        visibly dead ones are dropped and counted; an INVISIBLY dead
        one (peer vanished without FIN reaching us yet) fails at first
        use, which ``_exchange`` converts into failover."""
        while self._pool:
            reader, writer = self._pool.pop()
            if reader.at_eof() or writer.is_closing():
                self.pool_stale += 1
                metrics.counter("route_pool", backend=self.idx,
                                outcome="stale")
                self._discard(writer)
                continue
            self.pool_hits += 1
            metrics.counter("route_pool", backend=self.idx, outcome="hit")
            return reader, writer
        return await self._dial()

    async def _dial(self):
        """One transport dial. With pooling on, the blocking connect
        runs off-loop under the shared ``RetryPolicy`` (attempts +
        exponential backoff — the reconnect-and-backoff seam): a
        backend mid-restart costs a bounded retry in an executor
        thread, never a stalled event loop; exhaustion raises into the
        ring-retry failover like any other backend failure."""
        self.pool_dials += 1
        metrics.counter("route_pool", backend=self.idx, outcome="dial")
        host, port = self.spec.host, self.spec.port
        if self.pool_size <= 0:
            # Pooling disabled: the pre-pool dial-per-exchange path.
            return await asyncio.open_connection(host, port)
        timeout = self.connect_timeout_s

        def dial_blocking():
            return RetryPolicy(
                attempts=max(self.reconnect_attempts, 1),
                base_delay_s=self.reconnect_base_s,
                retry_on=(OSError,),
                name=f"route-pool:{self.spec.name}",
            ).run(lambda _a: socket.create_connection((host, port),
                                                      timeout=timeout))

        loop = asyncio.get_running_loop()
        sock = await loop.run_in_executor(None, dial_blocking)
        return await asyncio.open_connection(sock=sock)

    def _release(self, reader, writer) -> None:
        if (len(self._pool) < self.pool_size and not writer.is_closing()
                and not reader.at_eof()):
            self._pool.append((reader, writer))
        else:
            self._discard(writer)

    def _discard(self, writer) -> None:
        try:
            writer.close()
        except Exception:  # noqa: BLE001 - peer already gone
            pass

    def close_pool(self) -> None:
        """Drop every idle pooled connection (teardown: the member left
        the ring or the router is stopping)."""
        while self._pool:
            _reader, writer = self._pool.pop()
            self._discard(writer)

    # -- the gossip seam ----------------------------------------------------
    async def poll_healthz(self, timeout_s: float = 2.0) -> dict | None:
        """GET /healthz off the backend's status port; None when the
        backend is unreachable, has no status port, or answers junk —
        gossip treats all three as the same reconnaissance failure."""
        if not self.spec.status_port:
            return None
        try:
            doc = await asyncio.wait_for(self._get_healthz(),
                                         timeout=max(timeout_s, 0.001))
        except Exception:  # noqa: BLE001 - unreachable IS the data point
            return None
        self.last_healthz = doc
        return doc

    async def _get_healthz(self) -> dict | None:
        body = await self._get_status("/healthz")
        if body is None:
            return None
        doc = json.loads(body)
        return doc if isinstance(doc, dict) else None

    async def poll_metrics_text(self, timeout_s: float = 2.0) -> str | None:
        """GET /metrics off the backend's status port — the federation
        scrape (route/status.py folds every backend's registry into one
        fleet /metrics document). None on any failure: a missing
        backend simply contributes no series, flagged by the federator."""
        if not self.spec.status_port:
            return None
        try:
            body = await asyncio.wait_for(self._get_status("/metrics"),
                                          timeout=max(timeout_s, 0.001))
        except Exception:  # noqa: BLE001 - unreachable IS the data point
            return None
        return body.decode("utf-8", "replace") if body is not None else None

    async def poll_alertz(self, timeout_s: float = 2.0) -> dict | None:
        """GET /alertz off the backend's status port — the federated
        alert view (route/status.py folds every backend's pulse rows
        into one fleet document). None when the backend is unreachable,
        runs no pulse engine (404), or answers junk."""
        if not self.spec.status_port:
            return None
        try:
            body = await asyncio.wait_for(self._get_status("/alertz"),
                                          timeout=max(timeout_s, 0.001))
        except Exception:  # noqa: BLE001 - unreachable IS the data point
            return None
        if body is None:
            return None
        try:
            doc = json.loads(body)
        except ValueError:
            return None
        return doc if isinstance(doc, dict) else None

    async def poll_profilez(self, seconds: float,
                            timeout_s: float | None = None) -> dict | None:
        """GET /profilez?seconds=N off the backend's status port — the
        federated capture arm (route/status.py): the backend itself
        enforces the one-window rule (409) and the tracing requirement
        (503); the router just relays. Returns {"code", "doc"} or None
        when the backend is unreachable / has no status port. The
        default relay deadline covers the backend's documented
        seconds-scale jax-tier arming cost (first start_trace init) —
        a 5 s gossip-style timeout would misreport an arming backend
        as unreachable while its window opened anyway."""
        if not self.spec.status_port:
            return None
        if timeout_s is None:
            timeout_s = float(seconds) + 60.0
        try:
            code, body = await asyncio.wait_for(
                self._get_status_raw(f"/profilez?seconds={seconds:g}"),
                timeout=max(timeout_s, 0.001))
        except Exception:  # noqa: BLE001 - unreachable IS the data point
            return None
        try:
            doc = json.loads(body) if body else {}
        except ValueError:
            doc = {}
        return {"code": code, "doc": doc if isinstance(doc, dict) else {}}

    async def _get_status(self, path: str) -> bytes | None:
        """One HTTP GET against the backend's status port (the gossip
        and federation scrapes share it); None on a non-200."""
        code, body = await self._get_status_raw(path)
        return body if code == 200 else None

    async def _get_status_raw(self, path: str) -> tuple[int, bytes]:
        """The raw (status code, body) GET behind ``_get_status`` and
        the profilez relay (which must distinguish 409/503 from
        unreachable). The response is read to EOF (the endpoint answers
        Connection: close), NOT with one read() — a /metrics body past
        one TCP segment would otherwise come back truncated mid-line —
        with a hard size cap so a misbehaving peer cannot balloon the
        router."""
        reader, writer = await asyncio.open_connection(
            self.spec.host, self.spec.status_port)
        try:
            writer.write(f"GET {path} HTTP/1.1\r\n".encode("latin-1")
                         + b"Host: backend\r\nConnection: close\r\n\r\n")
            await writer.drain()
            chunks: list[bytes] = []
            total = 0
            while total < (1 << 24):
                chunk = await reader.read(1 << 16)
                if not chunk:
                    break
                chunks.append(chunk)
                total += len(chunk)
            raw = b"".join(chunks)
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - peer already gone
                pass
        head, _, body = raw.partition(b"\r\n\r\n")
        try:
            code = int(head.split(None, 2)[1])
        except (IndexError, ValueError):
            code = 0
        return code, body

    def stats(self) -> dict:
        return {
            "backend": self.idx, "name": self.spec.name,
            "addr": f"{self.spec.host}:{self.spec.port}",
            "dispatches": self.dispatches, "bytes": self.bytes_out,
            "failures": self.failures, "timeouts": self.timeouts,
            "redispatches_in": self.redispatches_in,
            "sheds_seen": self.sheds_seen, "canaries": self.canaries,
            "pid": self.pid, "skew_us": self.skew_us,
            "pool": {"idle": len(self._pool), "hits": self.pool_hits,
                     "dials": self.pool_dials, "stale": self.pool_stale},
            **self.health.stats(),
        }


@dataclass
class RouterConfig:
    #: per-request end-to-end Budget (admission -> answer), seconds
    deadline_s: float = 30.0
    #: wall deadline per backend ATTEMPT (connect + serve + reply);
    #: clamped to the request Budget's remainder — the watchdog bound
    #: that turns a wedged backend into failover instead of a stall
    attempt_timeout_s: float = 5.0
    #: /healthz gossip poll period (0 disables polling; dispatch
    #: outcomes still drive health)
    gossip_every_s: float = 1.0
    #: clean answers a released backend serves before leaving probation
    probation_batches: int = 2
    #: base backoff before retrying a SHED answer on the next replica
    #: (exponential per extra shed in the same request)
    shed_backoff_s: float = 0.02
    #: virtual nodes per ring member
    vnodes: int = 64
    #: affinity routing (the production mode); False = seeded-random
    #: backend order per request (the A/B control arm)
    affinity: bool = True
    #: RNG seed for the random-routing control arm
    seed: int = 0
    #: router journal path (backend quarantine persistence, the shared
    #: --unquarantine edit); None = in-memory health only
    journal: str | None = None
    #: recently-seen affinity keys tracked for rebalance-motion
    #: accounting (bounded; 0 disables tracking)
    track_keys: int = 4096
    #: response-frame payload ceiling per backend exchange — size it to
    #: the fleet's bucket ladder (route.bench derives it from
    #: --bucket-max); a legitimate response above it would read as a
    #: backend failure on every replica
    max_frame_bytes: int = wire.MAX_PAYLOAD
    #: idle pooled connections kept per backend (0 restores the
    #: dial-per-exchange transport): pooling drops the per-request
    #: connect from the wire stage — the ROUTE_r02 -> r04 waterfall
    #: delta records what it buys
    pool_size: int = 8
    #: dial retry policy at the pool's reconnect seam
    #: (resilience.policy.RetryPolicy: attempts + exponential backoff)
    pool_reconnect_attempts: int = 3
    pool_reconnect_base_s: float = 0.02
    #: blocking connect() timeout per dial attempt (the attempt wall
    #: deadline still bounds the whole exchange above it)
    pool_connect_timeout_s: float = 2.0
    #: chunked transfers (serve/transfer.py) at the ROUTER: payloads
    #: above this many blocks decompose into rung-sized chunks that
    #: spray across the affinity replica ring (each chunk fails over
    #: bit-exactly like an ordinary request). The router cannot see the
    #: backends' ladder, so the rung is explicit — size it to the
    #: fleet's --bucket-max. None/0 disables (oversized requests flow
    #: to a backend and take its typed refusal).
    transfer_chunk_blocks: int | None = None
    #: concurrent transfers admitted before new ones shed
    max_transfers: int = 8
    #: in-flight chunks per transfer (the pipelining window)
    transfer_window: int = 8
    #: reassembly-buffer byte budget (backpressure, never a wedge)
    transfer_budget_bytes: int = 64 << 20
    #: per-transfer payload ceiling (too-large past it, pre-allocation)
    transfer_max_bytes: int = 1 << 30
    #: default per-transfer Budget, seconds
    transfer_deadline_s: float = 300.0
    #: durable acked-chunk ledger path (the resume contract); None =
    #: in-memory
    transfer_ledger: str | None = None


class Router:
    """The front-end routing tier over N ot-serve backends."""

    def __init__(self, specs: list[BackendSpec],
                 config: RouterConfig | None = None, clock=time.monotonic):
        self.config = config or RouterConfig()
        self._clock = clock
        self.ring = ring_mod.Ring(vnodes=self.config.vnodes)
        self.backends: dict[str, Backend] = {}
        self._journal = None
        self._next_idx = 0
        self._specs = list(specs)
        self._rng = np.random.default_rng(self.config.seed)
        self.accepted = 0
        self.answered = 0
        self.routed_ok = 0
        self.redispatches = 0
        self.shed_retries = 0
        #: pool counters of members that already LEFT the ring (an
        #: elastic fleet retires workers mid-drive; route.bench's pool
        #: aggregate must count their reuse too)
        self.pool_retired = {"hits": 0, "dials": 0, "stale": 0}
        self.router_sheds = 0
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.ring_changes = 0
        self._canary_expected: bytes | None = None
        self._gossip_task: asyncio.Task | None = None
        self._draining = False
        self._inflight = 0
        self._idle = asyncio.Event()
        self._idle.set()
        #: recently-seen affinity keys (insertion-ordered dict as LRU)
        #: — the rebalance-motion sample on membership changes
        self._seen_keys: dict[str, None] = {}
        #: (tenant, sid) -> backend name: where each rc4 session's
        #: server-side state LIVES (the backend whose open succeeded).
        #: Session frames are pinned there — cross-backend failover
        #: would find no state (the in-process lane pool owns the
        #: bit-exact failover story; docs/SERVING.md, sessions section)
        self._session_pins: dict[tuple, str] = {}
        self.sessions_opened = 0
        self.sessions_closed = 0
        self.session_chunks = 0
        self.session_pin_misses = 0
        #: the chunked-transfer engine (serve/transfer.py) — the SAME
        #: engine the server embeds, parameterized here by per-chunk
        #: ring placement instead of queue admission. None when the
        #: deployer set no chunk rung.
        #: the router-tier pulse analytics thread (obs/pulse.py),
        #: started at start(); None when OT_PULSE=0
        self.pulse: pulse.PulseThread | None = None
        self.transfers: transfer_mod.TransferManager | None = None
        if self.config.transfer_chunk_blocks:
            self.transfers = transfer_mod.TransferManager(
                self._transfer_chunk,
                chunk_blocks=self.config.transfer_chunk_blocks,
                max_transfers=self.config.max_transfers,
                window=self.config.transfer_window,
                reassembly_budget_bytes=self.config.transfer_budget_bytes,
                max_payload_bytes=self.config.transfer_max_bytes,
                deadline_s=self.config.transfer_deadline_s,
                ledger=transfer_mod.TransferLedger(
                    self.config.transfer_ledger),
                clock=self._clock)

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        """Open the journal, register the initial backends, adopt
        recorded quarantines, pin the canary across every backend (the
        cross-backend bit-exactness startup check), start gossip."""
        c = self.config
        if c.journal:
            self._journal = journal_mod.SweepJournal(
                c.journal, {"kind": "route-backends",
                            "members": sorted(s.name for s in self._specs)})
        for spec in self._specs:
            self._register(spec)
        if self._journal is not None:
            for b in self.backends.values():
                fails = self._journal.fail_count(backend_unit(b.spec.name))
                if fails > 0:
                    b.health.adopt_journal_quarantine(fails)
        await self._pin_canary()
        if c.gossip_every_s > 0:
            self._gossip_task = asyncio.ensure_future(self._gossip_loop())
        # The router-tier pulse engine (obs/pulse.py): consumes THIS
        # process's registry (route_* series — sheds, backend
        # transitions), so the quarantine-flap and burn-rate rules
        # watch the routing tier too. None when OT_PULSE=0.
        self.pulse = pulse.start_live("route")

    def _register(self, spec: BackendSpec) -> None:
        if spec.name in self.backends:
            raise ValueError(f"backend {spec.name!r} already registered")
        c = self.config
        b = Backend(self._next_idx, spec,
                    probation_batches=c.probation_batches,
                    journal=self._journal, clock=self._clock,
                    max_frame_bytes=c.max_frame_bytes,
                    pool_size=c.pool_size,
                    reconnect_attempts=c.pool_reconnect_attempts,
                    reconnect_base_s=c.pool_reconnect_base_s,
                    connect_timeout_s=c.pool_connect_timeout_s)
        self._next_idx += 1
        self.backends[spec.name] = b
        self.ring.add(spec.name)

    async def _pin_canary(self) -> None:
        """Send the pinned canary request to EVERY backend; the first
        bit-exact-capable answer pins the expectation, every other
        backend is compared against it — cross-backend bit-exactness is
        a startup invariant, not a hope (the serve warmup rule, one
        level up). A backend that fails or mismatches starts
        quarantined; a router with NO canary-able backend cannot serve
        and fails start() loudly."""
        for b in self.backends.values():
            if b.health.state == QUARANTINED:
                continue  # journal-adopted: never let it pin the oracle
            out = await self._canary_once(b)
            if out is None:
                b.health.canary_failed("failed")
            elif self._canary_expected is None:
                self._canary_expected = out
                trace.point("route-canary-pinned", backend=b.idx,
                            n=len(out))
            elif out != self._canary_expected:
                b.health.canary_failed("mismatch")
        if self._canary_expected is None:
            raise RuntimeError(
                f"route startup failed: none of the {len(self.backends)} "
                "backend(s) answered the canary request")

    async def _canary_once(self, b: Backend) -> bytes | None:
        """One canary exchange on ``b`` (startup pinning and quarantine
        probing share it); None on any failure or timeout. Doubles as
        the CLOCK-SKEW handshake: every response frame carries the
        backend's epoch-µs clock, and the canary's request/response
        midpoint estimates the offset between the two processes' clocks
        (traced as ``wire-skew`` — what ``obs.export`` aligns the
        merged Perfetto timeline with)."""
        b.canaries += 1
        with trace.detached_span("backend-probe", backend=b.idx) as _:
            t_send = trace.now_us()
            try:
                header, body = await b.exchange(
                    {"t": CANARY_TENANT, "k": CANARY_KEY.hex(),
                     "n": CANARY_NONCE.hex()},
                    CANARY_PAYLOAD, self.config.attempt_timeout_s)
            except Exception:  # noqa: BLE001 - a sick backend may do anything
                metrics.counter("route_canary", backend=b.idx,
                                outcome="failed")
                return None
            t_recv = trace.now_us()
        self._note_handshake(b, header, t_send, t_recv)
        if not header.get("ok"):
            metrics.counter("route_canary", backend=b.idx, outcome="refused")
            return None
        metrics.counter("route_canary", backend=b.idx, outcome="ok")
        return body

    def _note_handshake(self, b: Backend, header: dict,
                        t_send: int, t_recv: int) -> None:
        """Fold one response frame's clock stamps into the backend's
        skew estimate. With both the receive ("tr") and reply ("ts")
        stamps this is the NTP four-timestamp offset —
        ``((tr - send) + (ts - recv)) / 2`` — which cancels the
        backend's processing time; with only "ts" it degrades to the
        midpoint estimator (biased by half the service time, still
        bounded by the round trip)."""
        ts = header.get("ts")
        if not isinstance(ts, int):
            return
        pid = header.get("pid")
        if isinstance(pid, int):
            b.pid = pid
        tr = header.get("tr")
        if isinstance(tr, int):
            skew = int(((tr - t_send) + (ts - t_recv)) // 2)
        else:
            skew = int(ts - (t_send + t_recv) // 2)
        b.skew_us = skew
        trace.point("wire-skew", backend=b.idx, pid=b.pid,
                    skew_us=skew, rtt_us=int(t_recv - t_send))

    async def stop(self) -> None:
        """Graceful drain: stop gossip, close admission (new submits
        answer ``shutdown``), await every in-flight request, close the
        journal. The ``lost == 0`` gate (accepted == answered) is the
        serve drain contract at router level — route.bench exits 1 on
        violation."""
        self._draining = True
        if self._gossip_task is not None:
            self._gossip_task.cancel()
            try:
                await self._gossip_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._gossip_task = None
        await self._idle.wait()
        for b in self.backends.values():
            b.close_pool()
        trace.point("route-drained", accepted=self.accepted,
                    answered=self.answered,
                    lost=self.accepted - self.answered)
        if self.transfers is not None:
            self.transfers.ledger.close()
        if self.pulse is not None:
            self.pulse.stop()
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    # -- membership --------------------------------------------------------
    def _rebalance_motion(self, action: str, member: str, fn) -> None:
        """Apply the ring mutation ``fn`` and trace how many of the
        recently-seen affinity keys changed owner — the minimal-motion
        evidence (~K/N for one member among N) on the live key sample,
        not a synthetic one."""
        keys = list(self._seen_keys)
        # An empty ring has no placement (teardown removes the last
        # member; the fleet supervisor's close() walks through here):
        # every tracked key counts as moved then.
        before = self.ring.placement(keys) if keys and len(self.ring) else {}
        fn()
        after = self.ring.placement(keys) if keys and len(self.ring) else {}
        moved = ring_mod.moved_keys(before, after)
        self.ring_changes += 1
        metrics.counter("route_ring_changes")
        metrics.counter("route_ring_moved_keys", moved)
        trace.point("ring-rebalance", action=action, member=member,
                    moved=moved, tracked=len(keys),
                    members=len(self.ring))

    async def add_backend(self, spec: BackendSpec) -> None:
        """Join: register, canary against the PINNED expectation (a new
        backend must prove bit-exactness before placement trusts it),
        minimal-motion rebalance."""
        self._rebalance_motion("join", spec.name,
                               lambda: self._register(spec))
        b = self.backends[spec.name]
        if self._journal is not None:
            fails = self._journal.fail_count(backend_unit(spec.name))
            if fails > 0:
                b.health.adopt_journal_quarantine(fails)
                return
        out = await self._canary_once(b)
        if out is None:
            b.health.canary_failed("failed")
        elif self._canary_expected is not None and out != self._canary_expected:
            b.health.canary_failed("mismatch")
        elif self._canary_expected is None:
            self._canary_expected = out

    def remove_backend(self, name: str) -> None:
        """Leave: drop the member; its arcs return to the clockwise
        successors (minimal motion), in-flight requests to it finish or
        fail over like any other outcome. The departing member's pool
        counters fold into ``pool_retired`` — an elastic fleet retires
        members mid-drive, and the reuse evidence must outlive them."""
        if name not in self.backends:
            raise ValueError(f"backend {name!r} not registered")
        self._rebalance_motion("leave", name,
                               lambda: self.ring.remove(name))
        b = self.backends[name]
        self.pool_retired["hits"] += b.pool_hits
        self.pool_retired["dials"] += b.pool_dials
        self.pool_retired["stale"] += b.pool_stale
        b.close_pool()
        del self.backends[name]

    async def canary_check(self, spec: BackendSpec) -> tuple[bool, str]:
        """Probe a PROSPECTIVE backend with the pinned startup canary
        WITHOUT granting membership — the rolling upgrade's bit-exact
        handoff gate (route/fleet.py): a successor must answer the
        fleet's pinned bytes identically before the predecessor may
        begin draining. Returns (ok, why) with why one of
        ok/failed/mismatch/unpinned; the ring, health, and placement
        are untouched either way."""
        b = Backend(-1, spec, clock=self._clock,
                    max_frame_bytes=self.config.max_frame_bytes,
                    pool_size=0)
        try:
            out = await self._canary_once(b)
        finally:
            b.close_pool()
        if self._canary_expected is None:
            return False, "unpinned"
        if out is None:
            return False, "failed"
        if out != self._canary_expected:
            return False, "mismatch"
        return True, "ok"

    # -- gossip ------------------------------------------------------------
    async def _gossip_loop(self) -> None:
        period = max(self.config.gossip_every_s, 0.05)
        while True:
            await asyncio.sleep(period)
            await self.gossip_once()

    async def gossip_once(self) -> None:
        """One poll pass: fold every backend's /healthz into its health
        machine; an ``ok`` answer from a QUARANTINED backend triggers a
        canary (release still requires the bit-exact data-path answer).
        Backends with NO status port are skipped entirely — having no
        reconnaissance channel is a deployment shape, not evidence of
        unreachability, and suspecting them every period would defeat
        the two-strike model for the whole fleet."""
        for b in list(self.backends.values()):
            if not b.spec.status_port:
                continue
            doc = await b.poll_healthz()
            status = doc.get("status") if isinstance(doc, dict) else None
            b.health.note_gossip(status if isinstance(status, str) else None)
            if status == "ok" and b.health.state == QUARANTINED:
                await self._probe_quarantined(b)

    async def _probe_quarantined(self, b: Backend) -> bool:
        """Canary a quarantined backend; bit-exact releases it into
        probation, anything else keeps it quarantined."""
        out = await self._canary_once(b)
        if out is not None and out == self._canary_expected:
            b.health.canary_ok()
            return True
        b.health.canary_failed(
            "mismatch" if out is not None else "failed")
        return False

    # -- placement ---------------------------------------------------------
    def _order_for(self, aff: str) -> list[str]:
        """The request's backend attempt order: the ring's clockwise
        replica sequence under affinity, a seeded-random permutation in
        the control arm (same MEMBERS, no locality — the A/B's only
        difference)."""
        if self.config.affinity:
            return self.ring.nodes_for(aff)
        members = list(self.ring.members())
        return [members[i] for i in self._rng.permutation(len(members))]

    def _track(self, aff: str) -> None:
        cap = self.config.track_keys
        if cap <= 0:
            return
        self._seen_keys.pop(aff, None)
        self._seen_keys[aff] = None
        while len(self._seen_keys) > cap:
            self._seen_keys.pop(next(iter(self._seen_keys)))

    # -- the request path --------------------------------------------------
    async def submit(self, tenant: str, key: bytes, nonce: bytes, payload,
                     deadline_s: float | None = None, mode: str = "ctr",
                     iv: bytes = b"", aad: bytes = b"",
                     tag: bytes = b"", sid: int = -1) -> Response:
        """Route one request; always answers (payload or coded error)
        — the loadgen-compatible submit surface, so the serve load
        generator drives a router exactly as it drives a server.
        ``mode``/``iv``/``aad``/``tag`` are the served-mode fields
        (serve/queue.py MODES): they ride the wire's ``m``/``iv``/
        ``a``/``tg`` fields verbatim, the backend's admission owns the
        per-mode validation, and a ``gcm`` seal's tag rides back on
        the response — AEAD traffic gets the SAME affinity placement
        and bit-exact failover as ctr (every mode's dispatch is a pure
        function of its arrays, so replay on the next ring node is
        byte-identical)."""
        if mode == "rc4":
            # Session data chunk (serve/session.py): pinned-backend
            # routing with its own admission accounting — the loadgen-
            # compatible surface, same as the server's submit.
            return await self.submit_session(tenant, sid, payload,
                                             deadline_s=deadline_s)
        if self._draining:
            return Response(ok=False, error=ERR_SHUTDOWN,
                            detail="router is draining")
        self.accepted += 1
        self._inflight += 1
        self._idle.clear()
        try:
            data = (payload.tobytes() if hasattr(payload, "tobytes")
                    else bytes(payload))
            if (self.transfers is not None and data
                    and len(data) % 16 == 0
                    and len(data) // 16 > self.transfers.chunk_blocks):
                # Oversized: ONE accepted/answered request whose chunks
                # spray across the replica ring (serve/transfer.py) —
                # gcm lands here too, for the engine's typed refusal.
                resp = await self.transfers.run(
                    tenant, bytes(key), bytes(nonce),
                    np.frombuffer(data, np.uint8), mode=str(mode),
                    iv=bytes(iv), deadline_s=deadline_s)
            else:
                resp = await self._route(tenant, bytes(key), bytes(nonce),
                                         payload, deadline_s, str(mode),
                                         bytes(iv), bytes(aad), bytes(tag))
        except Exception as e:  # noqa: BLE001 - a router must always answer
            resp = Response(ok=False, error=ERR_DISPATCH,
                            detail=f"{type(e).__name__}: {e}")
        finally:
            self.answered += 1
            self._inflight -= 1
            if self._inflight == 0:
                self._idle.set()
        return resp

    async def submit_transfer(self, tenant: str, key: bytes, nonce: bytes,
                              payload, deadline_s: float | None = None,
                              mode: str = "ctr", iv: bytes = b"",
                              resume_token: str | None = None,
                              tails: dict | None = None,
                              on_chunk=None) -> Response:
        """The explicit chunked-transfer entry (what ``submit`` takes
        automatically for oversized payloads), with the resumable
        streaming hooks exposed — the serve frontend's ``tx``
        sub-protocol shape, one fault domain up."""
        if self.transfers is None:
            return Response(ok=False, error=ERR_DISPATCH,
                            detail="transfers disabled on this router "
                                   "(no transfer_chunk_blocks)")
        if self._draining:
            return Response(ok=False, error=ERR_SHUTDOWN,
                            detail="router is draining")
        self.accepted += 1
        self._inflight += 1
        self._idle.clear()
        try:
            resp = await self.transfers.run(
                tenant, bytes(key), bytes(nonce), payload, mode=str(mode),
                iv=bytes(iv), deadline_s=deadline_s,
                resume_token=resume_token, tails=tails, on_chunk=on_chunk)
        except Exception as e:  # noqa: BLE001 - a router must always answer
            resp = Response(ok=False, error=ERR_DISPATCH,
                            detail=f"{type(e).__name__}: {e}")
        finally:
            self.answered += 1
            self._inflight -= 1
            if self._inflight == 0:
                self._idle.set()
        return resp

    async def _transfer_chunk(self, tenant: str, key: bytes,
                              spec, piece, *, mode: str,
                              deadline_s: float | None, sampled: bool,
                              parent: str | None) -> Response:
        """The transfer engine's submit seam at router level: one chunk
        = one ordinary ring dispatch. ``rotate=spec.index`` starts each
        chunk's attempt order one replica further around the key's ring
        sequence — chunks keep the key's affinity (same replica SET)
        while spraying across the backends, so a 16-chunk transfer is
        never serialized behind one backend's queue and a single
        backend's death costs only the chunks in flight there."""
        data = (piece.tobytes() if hasattr(piece, "tobytes")
                else bytes(piece))
        return await self._route_attempts(
            tenant, key, spec.nonce or b"", data, deadline_s,
            bool(sampled), parent, mode, spec.iv, b"", b"",
            rotate=spec.index)

    # -- stateful sessions -------------------------------------------------
    def session_order(self, tenant: str, sid: int) -> list[str]:
        """A session's replica sequence: the ring order for the
        session's OWN affinity key (tenant + sid — sessions carry no
        shared placement key, and one tenant's sessions should spread
        across its replica set). UN-rotated, unlike transfer chunk
        spray: session frames need the ONE backend holding the state,
        not load spreading."""
        return self._order_for(
            ring_mod.affinity_key(tenant, f"ss:{int(sid)}".encode()))

    async def _session_exchange(self, name: str, header: dict,
                                payload: bytes,
                                deadline_s: float | None) -> tuple:
        """One ``ss`` frame exchange with one NAMED backend; returns
        (response header, body) or raises like any backend contact."""
        c = self.config
        b = self.backends.get(name)
        if b is None:
            raise ConnectionError(f"backend {name!r} left the fleet")
        attempt_s = min(c.attempt_timeout_s,
                        float(deadline_s) if deadline_s else
                        c.attempt_timeout_s)
        return await b.exchange(header, payload, attempt_s)

    async def open_session(self, tenant: str, sid: int, key: bytes,
                           deadline_s: float | None = None) -> Response:
        """Open an rc4 session on the session's affinity backend and
        PIN it there: every later frame of the session goes to the
        backend that ran the KSA and holds the carry state. A replica
        that sheds or fails at open costs nothing (no state was made) —
        the open walks the replica sequence like an ordinary request."""
        if self._draining:
            return Response(ok=False, error=ERR_SHUTDOWN,
                            detail="router is draining")
        header = {"ss": "open", "t": tenant, "sid": int(sid),
                  "k": bytes(key).hex()}
        causes = []
        for name in self.session_order(tenant, sid):
            b = self.backends[name]
            if b.health.state == QUARANTINED:
                continue
            try:
                rh, _body = await self._session_exchange(
                    name, header, b"", deadline_s)
            except Exception as e:  # noqa: BLE001 - walk the replicas
                causes.append((name, e))
                continue
            if rh.get("ok"):
                self._session_pins[(tenant, int(sid))] = name
                self.sessions_opened += 1
                metrics.counter("route_session", outcome="opened")
                return Response(ok=True, detail=str(rh.get("detail", "")))
            if rh.get("error") in (ERR_SHED, ERR_SHUTDOWN):
                causes.append((name, RuntimeError(rh.get("error"))))
                continue  # busy/draining replica: the next may admit
            return Response(ok=False, error=rh.get("error"),
                            detail=str(rh.get("detail", "")))
        metrics.counter("route_session", outcome="open-failed")
        return Response(ok=False, error=ERR_DISPATCH,
                        detail=f"session open failed on every replica "
                               f"({len(causes)} attempt(s))")

    async def submit_session(self, tenant: str, sid: int, payload,
                             deadline_s: float | None = None) -> Response:
        """One session data chunk to the session's PINNED backend. No
        cross-backend failover: the PRGA carry lives only where open
        landed, so a dead pinned backend is a typed error and the
        client's move is close + reopen (in-PROCESS lane failover on
        that backend is where bit-exact keystream replay happens —
        docs/SERVING.md). Counted in accepted/answered like every
        routed request."""
        pin = self._session_pins.get((tenant, int(sid)))
        if pin is None:
            return Response(ok=False, error=ERR_BAD_REQUEST,
                            detail=f"session {sid} is not open via this "
                                   f"router")
        if self._draining:
            return Response(ok=False, error=ERR_SHUTDOWN,
                            detail="router is draining")
        self.accepted += 1
        self._inflight += 1
        self._idle.clear()
        try:
            data = (payload.tobytes() if hasattr(payload, "tobytes")
                    else bytes(payload))
            header = {"ss": "data", "t": tenant, "sid": int(sid)}
            if deadline_s is not None:
                header["deadline_s"] = round(float(deadline_s), 3)
            try:
                rh, body = await self._session_exchange(
                    pin, header, data, deadline_s)
            except Exception as e:  # noqa: BLE001 - typed, no failover
                self.session_pin_misses += 1
                metrics.counter("route_session", outcome="pin-miss")
                return Response(
                    ok=False, error=ERR_DISPATCH,
                    detail=f"session backend {pin!r} unreachable "
                           f"({type(e).__name__}: {e}); close and "
                           f"reopen the session")
            if rh.get("ok"):
                self.session_chunks += 1
                metrics.counter("route_session", outcome="chunk")
                return Response(ok=True,
                                payload=np.frombuffer(body, np.uint8),
                                batch=rh.get("batch"))
            return Response(ok=False, error=rh.get("error"),
                            detail=str(rh.get("detail", "")),
                            batch=rh.get("batch"))
        finally:
            self.answered += 1
            self._inflight -= 1
            if self._inflight == 0:
                self._idle.set()

    async def close_session(self, tenant: str, sid: int,
                            deadline_s: float | None = None) -> Response:
        """Close an rc4 session on its pinned backend and drop the pin
        (dropped EITHER way — a close that failed because the backend
        died releases the router-side pin too; the backend's own drain
        force-closes its rows)."""
        pin = self._session_pins.pop((tenant, int(sid)), None)
        if pin is None:
            return Response(ok=False, error=ERR_BAD_REQUEST,
                            detail=f"session {sid} is not open via this "
                                   f"router")
        header = {"ss": "close", "t": tenant, "sid": int(sid)}
        try:
            rh, _body = await self._session_exchange(
                pin, header, b"", deadline_s)
        except Exception as e:  # noqa: BLE001 - pin already dropped
            metrics.counter("route_session", outcome="close-failed")
            return Response(ok=False, error=ERR_DISPATCH,
                            detail=f"{type(e).__name__}: {e}")
        self.sessions_closed += 1
        metrics.counter("route_session", outcome="closed")
        if rh.get("ok"):
            return Response(ok=True, detail=str(rh.get("detail", "")))
        return Response(ok=False, error=rh.get("error"),
                        detail=str(rh.get("detail", "")))

    async def _route(self, tenant: str, key: bytes, nonce: bytes, payload,
                     deadline_s: float | None, mode: str = "ctr",
                     iv: bytes = b"", aad: bytes = b"",
                     tag: bytes = b"") -> Response:
        """The per-request wrapper: one head-sampling decision at ROUTER
        admission governs the whole cross-process chain, and the
        ``route-request`` span minted here is the chain's ROOT — its id
        travels over the wire ("ps") so the backend's ``request-queued``
        span chains under it, which is what lets ``obs.report`` join one
        request's story across processes."""
        data = (payload.tobytes() if hasattr(payload, "tobytes")
                else bytes(payload))
        sampled = trace.sample()
        cm = trace.maybe_span(sampled, "route-request", tenant=tenant,
                              blocks=len(data) // 16)
        span = cm.__enter__()
        try:
            resp = await self._route_attempts(
                tenant, key, nonce, data, deadline_s, sampled,
                span.id if span is not None else None,
                mode, iv, aad, tag)
        except BaseException as e:
            cm.__exit__(type(e), e, None)
            raise
        if resp.ledger is not None:
            cm.note(total_us=resp.ledger.get("total_us"),
                    complete=resp.ledger.get("complete"))
        cm.__exit__(None, None, None)
        return resp

    async def _route_attempts(self, tenant: str, key: bytes, nonce: bytes,
                              data: bytes, deadline_s: float | None,
                              sampled: bool, ps: str | None,
                              mode: str = "ctr", iv: bytes = b"",
                              aad: bytes = b"", tag: bytes = b"",
                              rotate: int = 0) -> Response:
        c = self.config
        aff = ring_mod.affinity_key(tenant, key)
        self._track(aff)
        budget = Budget(c.deadline_s if deadline_s is None
                        else float(deadline_s), clock=self._clock)
        header = {"t": tenant, "k": key.hex(), "n": nonce.hex(),
                  "deadline_s": round(budget.total_s, 3) or None}
        if mode != "ctr":
            # The AEAD wire fields (serve/wire.py): absent = ctr, so a
            # ctr-only fleet's frames are byte-identical to pre-AEAD.
            header["m"] = mode
            if iv:
                header["iv"] = iv.hex()
            if aad:
                header["a"] = aad.hex()
            if tag:
                header["tg"] = tag.hex()
        if sampled:
            # Propagate the admission decision + span parentage + the
            # ledger request over the wire (serve/wire.py): the
            # backend's spans and its per-request time-attribution
            # ledger join THIS request's story.
            header["sm"] = True
            header["lg"] = True
            if ps:
                header["ps"] = ps
        else:
            header["sm"] = False
        label = aff[-6:]
        t_admit = self._clock()
        t_first: float | None = None
        order = self._order_for(aff)
        if rotate and order:
            # Chunk spray (serve/transfer.py riders): start this
            # chunk's attempt order ``rotate`` replicas around the
            # key's ring sequence — same affinity replica set, load
            # spread across it; failover still walks every member.
            r = rotate % len(order)
            order = order[r:] + order[:r]
        primary = order[0] if order else None
        causes: list = []
        tried: set[str] = set()
        sheds = 0
        while True:
            name = self._pick(order, tried)
            if name is None:
                b = await self._rescue(order, tried)
                if b is None:
                    if sheds and len(causes) == 0:
                        # Every placeable backend SHED (no failures):
                        # propagate the backpressure — shed at the
                        # router, stamped like every other demotion.
                        self.router_sheds += 1
                        metrics.counter("route_shed")
                        degrade.degrade(
                            "route->shed",
                            "every placeable backend shed; shedding at "
                            "the router")
                        return Response(
                            ok=False, error=ERR_SHED,
                            detail="all backends shedding")
                    e = BackendsExhausted(label, causes)
                    metrics.counter("route_exhausted")
                    return Response(
                        ok=False,
                        error=(ERR_DEADLINE if e.timed_out or
                               budget.exhausted() else ERR_DISPATCH),
                        detail=str(e))
                name = b.spec.name
            b = self.backends[name]
            if budget.exhausted():
                causes.append((b.idx, asyncio.TimeoutError(
                    f"request budget {budget.total_s:.3f}s exhausted")))
                metrics.counter("route_exhausted")
                return Response(ok=False, error=ERR_DEADLINE,
                                detail=f"budget spent after "
                                       f"{len(tried)} attempt(s)")
            attempt_s = min(c.attempt_timeout_s, budget.remaining())
            redispatch = bool(tried)
            # A redispatch is an incident: force-sample it (the serve
            # rule) — first attempts of unsampled requests ride a
            # deferred span, free when they complete clean.
            cm = trace.maybe_span(sampled or redispatch, "route-dispatch",
                                  parent=ps,
                                  backend=b.idx, bucket=len(data) // 16,
                                  redispatch=redispatch)
            cm.__enter__()
            t0 = self._clock()
            if t_first is None:
                # Router-queue stage closes at the FIRST attempt:
                # placement, tracking, and any pre-attempt rescue work
                # are what this request waited on inside the router.
                t_first = t0
                metrics.observe("route_stage_us",
                                (t_first - t_admit) * 1e6,
                                stage="router_queue",
                                exemplar=({"span": ps,
                                           "trace": trace.run_id(),
                                           "backend": b.idx}
                                          if ps else None))
            outcome = "ok"
            try:
                faults.check_backend("backend_fail", b.idx, label)
                if faults.fire_backend("backend_hang", b.idx):
                    # The injected wedged backend: an AWAITABLE sleep
                    # (the router is an event loop — a blocking sleep
                    # would hang every rider, not just this one), cut
                    # down by the attempt deadline exactly like a real
                    # backend that stopped answering.
                    trace.point("fault-hang", backend=b.idx)
                    await asyncio.wait_for(asyncio.sleep(attempt_s + 60.0),
                                           timeout=attempt_s)
                rh, body = await b.exchange(header, data, attempt_s)
            except asyncio.TimeoutError as e:
                # The exchange never ended: the span is ABANDONED, not
                # closed — its orphaned begin is the kill evidence
                # (obs.report --check --expected-orphans route-dispatch).
                cm.force()
                outcome = "timeout"
                b.timeouts += 1
                metrics.counter("route_backend_timeout", backend=b.idx)
                trace.counter("route_backend_timeout", backend=b.idx)
                b.health.note_timeout()
                causes.append((b.idx, e))
                tried.add(name)
                continue
            except Exception as e:  # noqa: BLE001 - fail over, then contain
                cm.__exit__(type(e), e, None)
                outcome = "failed"
                b.failures += 1
                metrics.counter("route_backend_failed", backend=b.idx)
                trace.counter("route_backend_failed", backend=b.idx)
                b.health.note_failure(e)
                causes.append((b.idx, e))
                tried.add(name)
                continue
            finally:
                dt_us = int((self._clock() - t0) * 1e6)
                metrics.observe("route_dispatch_us", dt_us,
                                backend=b.idx, outcome=outcome)
            t_att_end = self._clock()
            cm.__exit__(None, None, None)
            err = rh.get("error")
            if not rh.get("ok") and err == ERR_SHED:
                # Backpressure, not failure: the backend is healthy and
                # full. Back off, then try the next replica; health is
                # untouched (shedding a request is the queue doing its
                # job, and suspecting it would turn overload into
                # flapping).
                b.sheds_seen += 1
                sheds += 1
                self.shed_retries += 1
                metrics.counter("route_shed_retry", backend=b.idx)
                trace.counter("route_shed_retry", backend=b.idx)
                tried.add(name)
                await asyncio.sleep(
                    min(c.shed_backoff_s * (2 ** (sheds - 1)),
                        max(budget.remaining(), 0.0)))
                continue
            if not rh.get("ok") and err == ERR_SHUTDOWN:
                # The backend is draining: non-punitive removal from
                # placement (gossip will confirm), fail over.
                b.health.note_gossip("draining")
                causes.append((b.idx, ConnectionError("backend draining")))
                tried.add(name)
                continue
            # A definitive answer (payload or a request-level error like
            # bad-request/too-large/deadline): the rider gets it as-is —
            # re-dispatching a malformed request elsewhere would only
            # repeat the refusal.
            b.dispatches += 1
            b.health.note_success()
            if redispatch:
                b.redispatches_in += 1
                self.redispatches += 1
                metrics.counter("route_redispatch", backend=b.idx)
                trace.counter("route_redispatch", backend=b.idx,
                              after=len(tried))
            ledger = self._build_ledger(sampled, rh, b.idx, t_admit,
                                        t_first, t0, t_att_end, ps=ps)
            if rh.get("ok"):
                self.routed_ok += 1
                b.bytes_out += len(body)
                if name == primary:
                    self.affinity_hits += 1
                    metrics.counter("route_affinity", outcome="hit")
                else:
                    self.affinity_misses += 1
                    metrics.counter("route_affinity", outcome="miss")
                tg = rh.get("tg")
                try:
                    resp_tag = (bytes.fromhex(str(tg))
                                if isinstance(tg, str) and tg else None)
                except ValueError:
                    resp_tag = None
                return Response(ok=True,
                                payload=np.frombuffer(body, np.uint8),
                                batch=rh.get("batch"), ledger=ledger,
                                tag=resp_tag)
            return Response(ok=False, error=err,
                            detail=str(rh.get("detail", "")),
                            batch=rh.get("batch"), ledger=ledger)

    def _build_ledger(self, sampled: bool, rh: dict, backend: int,
                      t_admit: float, t_first: float,
                      t0: float, t_att_end: float,
                      ps: str | None = None) -> dict | None:
        """The request's cross-process time-attribution ledger (µs),
        assembled at answer time for SAMPLED requests: the router's own
        stages — ``router_queue`` (admission -> first attempt),
        ``retry`` (first attempt -> final attempt: failed walls, shed
        backoffs, rescue probes; 0 on the healthy path), ``wire``
        (final attempt wall minus the backend's measured residency:
        connect + frames both ways) — merged with the backend's stages
        shipped back in the response ("lg": backend_queue, pack,
        worker_wait, dispatch, device, reply). Stages are contiguous
        and disjoint by construction, so their sum tracks the router's
        measured end-to-end latency — ``route.bench`` gates the sum
        within tolerance and the fleet report renders the waterfall.
        ``complete`` says whether the backend half actually arrived."""
        if not sampled:
            return None
        att_wall = int((t_att_end - t0) * 1e6)
        stages = {"router_queue": int((t_first - t_admit) * 1e6),
                  "retry": int((t0 - t_first) * 1e6)}
        lg = rh.get("lg")
        complete = (isinstance(lg, dict)
                    and isinstance(lg.get("stages"), dict))
        if complete:
            backend_total = int(lg.get("total_us", 0))
            stages["wire"] = max(att_wall - backend_total, 0)
            for name, v in lg["stages"].items():
                stages[str(name)] = int(v)
        else:
            stages["wire"] = att_wall
        # The wire/retry stages carry a tail exemplar pointing at this
        # request's route-request root span: the slowest wire crossing
        # in the histogram resolves to one concrete request's full
        # cross-process chain (the exemplar -> trace walk-through,
        # docs/OBSERVABILITY.md).
        ex = ({"span": ps, "trace": trace.run_id(), "backend": backend}
              if ps else None)
        metrics.observe("route_stage_us", stages["wire"], stage="wire",
                        exemplar=ex)
        if stages["retry"]:
            metrics.observe("route_stage_us", stages["retry"],
                            stage="retry", exemplar=ex)
        # total closes at the exchange end — the boundary the stages
        # cover. The router's post-answer bookkeeping (span write,
        # counters) happens after every stage clock stopped; folding it
        # into total but no stage would charge the ledger a phantom
        # residue on every small request.
        return {"stages": stages,
                "total_us": int((t_att_end - t_admit) * 1e6),
                "complete": complete, "backend": backend}

    def _pick(self, order: list[str], tried: set[str]) -> str | None:
        """The next untried PLACEABLE backend in the request's order
        (None when none remain — the rescue/exhaustion path)."""
        for name in order:
            if name in tried:
                continue
            b = self.backends.get(name)
            if b is not None and b.health.placeable():
                return name
        return None

    async def _rescue(self, order: list[str], tried: set[str]):
        """Last resort when no placeable backend remains: canary the
        quarantined ones in ring order rather than fail the request — a
        single-backend deployment recovering from a transient hang
        re-proves itself here instead of answering errors forever."""
        for name in order:
            if name in tried:
                continue
            b = self.backends.get(name)
            if b is None or b.health.state != QUARANTINED:
                continue
            if await self._probe_quarantined(b):
                return b
        return None

    # -- introspection -----------------------------------------------------
    def quarantine_events(self) -> int:
        return sum(1 for b in self.backends.values()
                   for t in b.health.transitions if t["to"] == QUARANTINED)

    def release_events(self) -> int:
        return sum(1 for b in self.backends.values()
                   for t in b.health.transitions if t["to"] == RELEASED)

    def affinity_ratio(self) -> float:
        total = self.affinity_hits + self.affinity_misses
        return round(self.affinity_hits / total, 4) if total else 0.0

    def stats(self) -> dict:
        return {
            "backends": {name: b.stats()
                         for name, b in sorted(self.backends.items())},
            "ring": {"members": list(self.ring.members()),
                     "vnodes": self.config.vnodes,
                     "changes": self.ring_changes},
            "affinity": {"enabled": self.config.affinity,
                         "hits": self.affinity_hits,
                         "misses": self.affinity_misses,
                         "ratio": self.affinity_ratio()},
            "accepted": self.accepted, "answered": self.answered,
            "lost": self.accepted - self.answered,
            "routed_ok": self.routed_ok,
            "redispatches": self.redispatches,
            "shed_retries": self.shed_retries,
            "router_sheds": self.router_sheds,
            "pool_retired": dict(self.pool_retired),
            "quarantine_events": self.quarantine_events(),
            "transfers": (self.transfers.stats()
                          if self.transfers is not None else None),
            "sessions": {"opened": self.sessions_opened,
                         "closed": self.sessions_closed,
                         "chunks": self.session_chunks,
                         "pinned": len(self._session_pins),
                         "pin_misses": self.session_pin_misses},
        }
