"""Deterministic consistent-hash ring: key affinity + minimal motion.

Placement must satisfy three properties at once, and the classic
virtual-node hash ring gives all three structurally:

* **Affinity** — the same (tenant, key-digest) always maps to the same
  backend while membership holds, so that backend's ``keycache`` holds
  the expanded schedule and the stacked-memo entry: routing IS the
  cache policy (a routed-away request pays key expansion + stack
  assembly on a cold backend; docs/SERVING.md measures the difference).
* **Determinism across processes** — hashes are SHA-256 of stable
  strings, never Python ``hash()`` (which is per-process salted): two
  routers built over the same member list place every key identically,
  which is what makes a router restart (or an active/standby pair)
  placement-transparent. Pinned-value tests enforce this.
* **Minimal motion** — a join steals only the arc segments its virtual
  nodes land on (~K/N of the keyspace for N members); a leave returns
  only the leaver's arcs to the clockwise successors. Everything else
  KEEPS its placement — the property that makes membership changes
  cheap enough to do live (the rebalance-motion test pins the bound).

``nodes_for`` returns the distinct members in clockwise order from the
key's point: position 0 is the affinity home, positions 1.. are the
FAILOVER REPLICA SEQUENCE — the order the router re-dispatches in when
the home backend fails, hangs, or sheds. Every router in the fleet
computes the same sequence, so failover traffic from many routers
converges on the same replica instead of scattering.

stdlib-only (hashlib + bisect): the ring must import anywhere the
device-free router does.
"""

from __future__ import annotations

import bisect
import hashlib


def stable_hash(s: str) -> int:
    """64-bit point on the ring for ``s`` — SHA-256 based, so identical
    across processes, hosts, and Python hash-seed salts."""
    return int.from_bytes(
        hashlib.sha256(s.encode("utf-8")).digest()[:8], "big")


def affinity_key(tenant: str, key: bytes) -> str:
    """The ring identity of one tenant's key: tenant-scoped truncated
    SHA-256 of the key bytes — the same digest construction as
    ``serve.keycache.key_digest`` (the cache the affinity exists to
    hit), tenant-scoped because the keycache is (two tenants sharing
    key bytes are two cache entries, so they are two ring keys)."""
    digest = hashlib.sha256(bytes(key)).hexdigest()[:16]
    return f"{tenant}/{digest}"


class Ring:
    """A consistent-hash ring over named members with virtual nodes."""

    def __init__(self, members=(), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self._points: list[int] = []      # sorted vnode positions
        self._owner: dict[int, str] = {}  # position -> member
        self._members: list[str] = []
        for m in members:
            self.add(m)

    def __len__(self) -> int:
        return len(self._members)

    def members(self) -> tuple[str, ...]:
        """Members in join order (the stable display order; placement
        depends only on the SET — join order never changes hashes)."""
        return tuple(self._members)

    def _member_points(self, member: str) -> list[int]:
        return [stable_hash(f"{member}#{v}") for v in range(self.vnodes)]

    def add(self, member: str) -> None:
        if member in self._members:
            raise ValueError(f"member {member!r} already on the ring")
        for pt in self._member_points(member):
            # A 64-bit collision between two members' vnodes is ~never;
            # if it happens, first owner keeps the point (deterministic:
            # membership operations apply in one order per ring).
            if pt not in self._owner:
                self._owner[pt] = member
                bisect.insort(self._points, pt)
        self._members.append(member)

    def remove(self, member: str) -> None:
        if member not in self._members:
            raise ValueError(f"member {member!r} not on the ring")
        for pt in self._member_points(member):
            if self._owner.get(pt) == member:
                del self._owner[pt]
                i = bisect.bisect_left(self._points, pt)
                del self._points[i]
        self._members.remove(member)

    # -- placement ---------------------------------------------------------
    def node_for(self, key: str) -> str:
        """The member owning ``key``'s clockwise-next virtual node — the
        affinity home."""
        if not self._points:
            raise LookupError("empty ring")
        h = stable_hash(key)
        i = bisect.bisect_right(self._points, h) % len(self._points)
        return self._owner[self._points[i]]

    def nodes_for(self, key: str, n: int | None = None) -> list[str]:
        """The first ``n`` DISTINCT members clockwise from ``key``'s
        point (default: all members): ``[0]`` is the affinity home,
        ``[1:]`` the failover replica sequence."""
        if not self._points:
            raise LookupError("empty ring")
        want = len(self._members) if n is None else min(int(n),
                                                        len(self._members))
        h = stable_hash(key)
        start = bisect.bisect_right(self._points, h)
        out: list[str] = []
        seen: set[str] = set()
        for off in range(len(self._points)):
            owner = self._owner[self._points[(start + off)
                                             % len(self._points)]]
            if owner not in seen:
                seen.add(owner)
                out.append(owner)
                if len(out) >= want:
                    break
        return out

    def placement(self, keys) -> dict[str, str]:
        """key -> owning member for an iterable of keys (the motion
        accounting helper: diff two placements across a membership
        change to count moved keys)."""
        return {k: self.node_for(k) for k in keys}

    def digest(self) -> str:
        """A short stable fingerprint of this ring's VIEW — the member
        set plus vnode count, order-independent (placement depends only
        on the set). Two routers agreeing on the digest place every key
        identically; the fleet gossip (route/fleet.py) carries it so a
        replica can detect config skew loudly instead of diverging
        silently."""
        doc = ",".join(sorted(self._members)) + f"#v{self.vnodes}"
        return hashlib.sha256(doc.encode("utf-8")).hexdigest()[:16]


def moved_keys(before: dict[str, str], after: dict[str, str]) -> int:
    """How many keys changed owner between two ``placement`` maps over
    the same key set — the rebalance-motion number the minimal-motion
    test bounds (~K/N per single join/leave) and the router traces on
    every membership change."""
    return sum(1 for k, owner in before.items() if after.get(k) != owner)
