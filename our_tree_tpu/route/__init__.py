"""ot-route: the front-end routing tier over N ot-serve backends.

The serving arc made ONE process fault-tolerant (per-device lanes,
bit-exact failover, overlap, telemetry); this package is the same
treatment one fault domain up: lanes are the per-DEVICE fault domain,
the router's backends are the per-HOST one. The paper's decomposition
(split the work into independent side-effect-free chunks and run them
anywhere — CTR with explicit counters) is what makes the lift safe:
a request is a pure function of (tenant, key, nonce, payload), so a
failed or hung backend's request replays BIT-EXACTLY on the next ring
node before any rider is answered, exactly as a lane's batch does.

Modules (docs/SERVING.md has the architecture and cookbook):

* ``ring``   — deterministic consistent-hash ring with virtual nodes:
  a tenant's key digest maps to the backend whose ``keycache.stacked()``
  schedules are already warm (KEY AFFINITY — the difference between
  zero per-request schedule work and a rebuild), members join/leave
  with minimal placement motion (~K/N keys move), and the clockwise
  successor order IS the failover replica sequence.
* ``health`` — per-backend health reusing the LANE state machine
  (healthy/suspect/quarantined/probation/released; a timeout
  quarantines from any state), driven by dispatch outcomes plus
  ``/healthz`` gossip polling, quarantine persisted via the same
  journal failure rows as lanes and sweep units — ONE quarantine
  model, one ``--unquarantine`` release edit.
* ``proxy``  — the Router: consistent-hash placement, per-request
  ``Budget`` deadlines, bit-exact cross-backend failover
  (re-dispatch-before-error), canary probation (a pinned request whose
  expected bytes every backend matched at startup), backpressure
  propagation (a backend's ``shed`` becomes retry-with-backoff on the
  replica ring, then shed-at-router through the shared ``degrade()``
  ledger), and graceful membership changes + drain (``lost == 0``
  gated, like serve drain). The ONLY module that contacts a backend
  (otlint's ``route-backend-seam`` rule) — and the whole package is
  DEVICE-FREE: no jax import (the same rule), so the router runs on
  any box in front of any backend mix.
* ``status`` — the router's /metrics + /healthz (the shared
  ``HttpStatusEndpoint``), with the ring/backend MEMBERSHIP VIEW so
  operators see placement without reading traces.
* ``bench``  — ``python -m our_tree_tpu.route.bench``: spawns N
  ``serve.worker`` backend processes (via the isolate service spawner),
  drives the router with the serve loadgen, writes ``ROUTE_r*.json``
  (per-backend dispatch table, quarantine/redispatch ledger, affinity
  vs random-routing keycache A/B), and gates zero lost / zero
  recompiles / bit-exact probes — the horizontal-scaling artifact.

Wire format: ``serve/wire.py`` (framed JSON-header + raw payload);
error vocabulary: ``serve.queue``'s closed ERR_* set — the router adds
no new failure codes, it only decides WHERE a request goes next.
"""
