"""``python -m our_tree_tpu.route.bench`` — the routing-tier drive.

Spawns N REAL ot-serve backend processes (``serve.worker`` via the
isolate service spawner — each its own session, SIGTERM-drained,
group-SIGKILLed past the deadline), routes the serve load generator
through a ``route.proxy.Router`` over them, and writes the horizontal
scaling artifact ``ROUTE_r*.json`` next to the SERVE_r* series.

Hard contracts the run exits 1 on (the serve.bench set, one fault
domain up):

* **zero lost** — at the ROUTER (accepted == answered) and at EVERY
  backend (each worker's exit line carries its own drain ledger, and a
  nonzero worker rc is a failed drain);
* **bit-exact probes** — every ``verify_every``-th request replays a
  pinned reference THROUGH the router (failover included: a request
  that re-dispatched mid-probe must still return the same bytes);
* **zero post-warmup recompiles** — summed across backends from their
  exit lines (``--allow-recompiles`` waives);
* optional gates for the fault drives: ``--expect-quarantines N``
  (exactly N backend quarantine events — the backend-kill CI drive
  pins 1), ``--expect-releases N``, ``--min-redispatch N``, and
  ``--require-zero-errors``.

The AFFINITY A/B (``--ab``): the same drive runs twice over FRESH
backend sets — affinity routing, then seeded-random routing (same
members, same request sequence, no locality) — and the artifact
records both arms' aggregate backend keycache hit ratios.
``--min-affinity-gain`` (default 0 with ``--ab``: strictly greater)
gates that affinity actually bought cache locality, which is the whole
reason the ring exists.

Fault drives arm ``OT_FAULTS`` in THIS process only (the router owns
the ``backend_fail``/``backend_hang`` seams); the spawner strips
``OT_FAULTS`` from worker environments so a router-level fault spec
can never double-fire inside a backend's serve seams.

``--unquarantine backend:<name>`` (with ``--journal``) is the shared
release edit — the same ``resilience.journal.clear_failures`` behind
``harness.bench --unquarantine`` and ``serve.bench --unquarantine``.
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import random
import re
import sys
import time

import numpy as np

from ..obs import metrics, slo, trace
from ..resilience import degrade, faults, isolate
from ..resilience import journal as journal_mod
from ..serve import loadgen, wire
from ..serve.queue import ERR_TRANSFER_ABORT
from .fleet import (REPLICA_EXIT_KIND, REPLICA_KIND, FailoverClient,
                    FleetConfig, FleetSupervisor, ProcessWorkerHandle,
                    RouterServer, worker_argv)
from .proxy import BackendSpec, Router, RouterConfig
from .status import RouterStatus

#: How long one worker gets to import jax, build/resolve its engine,
#: warm every lane x rung, and print its READY line.
READY_DEADLINE_S = 180.0


def _repo_root() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _next_artifact(root: str, family: str = "ROUTE") -> str:
    """The next free ``<FAMILY>_r<NN>.json`` at the repo root (ROUTE
    for the plain drive, STREAM when the run mixes chunked transfers)."""
    taken = [0]
    for p in glob.glob(os.path.join(root, f"{family}_r*.json")):
        m = re.match(rf"{family}_r(\d+)\.json$", os.path.basename(p))
        if m:
            taken.append(int(m.group(1)))
    return os.path.join(root, f"{family}_r{max(taken) + 1:02d}.json")


def _spawn_backends(args, tag: str):
    """Spawn N serve.worker processes; returns (handles, specs).
    Raises after cleaning up if any worker fails to come ready."""
    env = dict(os.environ)
    # The router owns this drive's fault points; a backend re-parsing
    # the same spec would double-fire it inside the serve seams.
    env.pop("OT_FAULTS", None)
    handles, specs = [], []
    kill_last = getattr(args, "kill_backend_after", None) is not None
    try:
        for i in range(args.backends):
            name = f"b{i}"
            wenv = dict(env)
            if i == 0 and getattr(args, "worker_faults", None):
                # The hung-lane half of the mid-transfer chaos drive
                # lives in exactly ONE worker; the rest stay clean so
                # the blast radius is attributable.
                wenv["OT_FAULTS"] = args.worker_faults
            if kill_last and i == args.backends - 1:
                # The SIGKILL victim writes no trace files: a process
                # that vanishes mid-frame leaves torn spans behind, and
                # obs.report's orphan licensing is for EXPECTED shapes,
                # not collateral.
                wenv.pop("OT_TRACE_DIR", None)
            argv = [sys.executable, "-m", "our_tree_tpu.serve.worker",
                    "--port", "0", "--status-port", "0",
                    "--engine", args.engine,
                    "--bucket-min", str(args.bucket_min),
                    "--bucket-max", str(args.bucket_max),
                    "--queue-depth", str(args.worker_queue_depth),
                    "--tenant-depth-frac", str(args.tenant_depth_frac),
                    "--dispatch-deadline", str(args.dispatch_deadline),
                    "--modes", ",".join(args.mode_list)]
            if args.worker_lanes is not None:
                argv += ["--lanes", str(args.worker_lanes)]
            h = isolate.spawn_service(argv, env=wenv,
                                      name=f"{tag}:{name}")
            handles.append(h)
        for i, h in enumerate(handles):
            line = h.read_line(READY_DEADLINE_S)
            doc = None
            if line:
                try:
                    doc = json.loads(line)
                except ValueError:
                    doc = None
            if not (isinstance(doc, dict)
                    and doc.get("kind") == "ot-serve-worker"):
                raise RuntimeError(
                    f"backend b{i} (pid {h.pid}) never came ready "
                    f"within {READY_DEADLINE_S:.0f}s "
                    f"(got {line!r})")
            specs.append(BackendSpec(
                name=f"b{i}", host="127.0.0.1", port=int(doc["port"]),
                status_port=doc.get("status_port"),
                pid=doc.get("pid")))
            print(f"# backend b{i}: pid {h.pid} port {doc['port']} "
                  f"status {doc.get('status_port')} "
                  f"engine {doc.get('engine')} lanes {doc.get('lanes')}",
                  file=sys.stderr)
    except BaseException:
        for h in handles:
            h.stop(term_deadline_s=5.0)
        raise
    return handles, specs


def _teardown(handles, killed=frozenset()) -> tuple[list[dict], int]:
    """SIGTERM-drain every worker, collect their exit-line docs and the
    worst rc (a worker that lost work exits nonzero; one SIGKILLed past
    the drain deadline reports a negative rc). Indices in ``killed``
    were SIGKILLed ON PURPOSE mid-drive (the chaos arm): their rc is
    recorded in the doc but exempt from the drain verdict — the
    contract they prove is the ROUTER absorbing their loss, not their
    own drain."""
    docs, worst = [], 0
    for i, h in enumerate(handles):
        rc = h.stop(term_deadline_s=60.0)
        out, err = h.drain_output()
        doc = {}
        for line in reversed(out.splitlines()):
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if (isinstance(cand, dict)
                    and cand.get("kind") == "ot-serve-worker-exit"):
                doc = cand
                break
        if rc != 0 and i not in killed:
            tail = err.strip().splitlines()[-3:]
            print(f"# worker {h.name}: rc={rc}"
                  + (": " + " | ".join(tail) if tail else ""),
                  file=sys.stderr)
        row = {"rc": rc, **doc}
        if i in killed:
            row["killed"] = True
        docs.append(row)
        if i not in killed:
            worst = worst if rc == 0 else (rc if worst == 0 else worst)
    return docs, worst


#: Every stage a COMPLETE cross-process waterfall carries (router +
#: backend halves of the per-request ledger) — the shared vocabulary,
#: so this gate and the report's fleet table can never drift apart.
WATERFALL_STAGES = metrics.WATERFALL_STAGES


def waterfall_stats(ledgers: list, tolerance: float = 0.05) -> dict:
    """Aggregate the sampled requests' time-attribution ledgers: how
    many reconstruct a COMPLETE cross-process waterfall (backend half
    arrived and every stage present), how many of those have a stage
    sum within ``tolerance`` of the measured end-to-end latency, and
    per-stage p50/p95/p99 over the complete population (the artifact's
    ``stages`` section, which the SLO per-stage budgets gate).

    What the sum check can and cannot catch: the ``wire`` and host
    ``dispatch`` stages are RESIDUALS of the same clock readings that
    produce ``total_us``, so genuinely unmeasured work folds into them
    by design (that is what makes the stages exhaustive). The check
    therefore guards against OVERCOUNTING — a stage double-booked
    across the wire, clamp saturation when the backend reports more
    time than the router observed, µs-truncation drift — not against
    an unmeasured stage, which cannot exist by construction."""
    complete = [
        l for l in ledgers
        if l.get("complete")
        and all(s in l.get("stages", {}) for s in WATERFALL_STAGES)]
    sum_ok = 0
    per_stage: dict[str, list] = {s: [] for s in WATERFALL_STAGES}
    for l in complete:
        stages, total = l["stages"], l.get("total_us", 0)
        if total > 0 and abs(sum(stages.values()) - total) \
                <= tolerance * total:
            sum_ok += 1
        for s in WATERFALL_STAGES:
            per_stage[s].append(stages[s])
    stages_out = {}
    for s, vals in per_stage.items():
        vals.sort()
        stages_out[s] = {
            "p50_us": metrics.percentile_exact(vals, 50),
            "p95_us": metrics.percentile_exact(vals, 95),
            "p99_us": metrics.percentile_exact(vals, 99),
            "count": len(vals),
        }
    n, nc = len(ledgers), len(complete)
    return {
        "sampled": n,
        "complete": nc,
        "complete_frac": round(nc / n, 4) if n else 0.0,
        "sum_within_tol_frac": round(sum_ok / nc, 4) if nc else 0.0,
        "tolerance": tolerance,
        "stages": stages_out,
    }


def _keycache_ratio(exit_docs: list[dict]) -> float:
    """Aggregate backend keycache hit ratio: hits / (hits + misses)
    summed across every backend's exit ledger — the affinity A/B's
    measured quantity (affinity routes a tenant's key to the one
    backend that already expanded it; random routing re-expands it
    once per backend it wanders to)."""
    hits = sum(d.get("keycache", {}).get("hits", 0) for d in exit_docs)
    misses = sum(d.get("keycache", {}).get("misses", 0) for d in exit_docs)
    return round(hits / (hits + misses), 4) if hits + misses else 0.0


async def _resume_drill(args, router) -> dict:
    """Interrupt one oversized transfer mid-stream (a scoped
    ``transfer_abort`` shot at the LAST chunk's admission, so earlier
    chunks have already landed, been emitted in order, and been acked
    into the ledger), then resume it with the same token: only the
    unacked chunks may be re-sent and the spliced output must be
    byte-identical to an uninterrupted run — the artifact's ``resume``
    section (docs/SERVING.md, streaming transfers)."""
    size = max(args.transfer_sizes)
    step = router.transfers.chunk_blocks * 16
    chunks = (size + step - 1) // step
    rng = random.Random(args.seed ^ 0x51E4A11)
    key = bytes(rng.getrandbits(8) for _ in range(16))
    nonce = bytes(rng.getrandbits(8) for _ in range(16))
    payload = np.frombuffer(rng.randbytes(size), dtype=np.uint8)

    # The reference: the same bytes, uninterrupted, its own token.
    ref = await router.submit_transfer(
        "drill", key, nonce, payload, deadline_s=args.transfer_deadline)

    out = np.zeros(size, dtype=np.uint8)

    def collect(spec, resp):
        piece = np.asarray(resp.payload, dtype=np.uint8)
        out[spec.offset:spec.offset + spec.nbytes] = piece[:spec.nbytes]

    token = f"drill-{args.seed}"
    prev = os.environ.get("OT_FAULTS")
    os.environ["OT_FAULTS"] = f"transfer_abort:1@chunk={chunks - 1}"
    faults.reset()
    try:
        first = await router.submit_transfer(
            "drill", key, nonce, payload,
            deadline_s=args.transfer_deadline,
            resume_token=token, on_chunk=collect)
    finally:
        if prev is None:
            os.environ.pop("OT_FAULTS", None)
        else:
            os.environ["OT_FAULTS"] = prev
        faults.reset()
    second = await router.submit_transfer(
        "drill", key, nonce, payload,
        deadline_s=args.transfer_deadline,
        resume_token=token, on_chunk=collect)

    t2 = dict(second.transfer or {})
    doc = {
        "size": size,
        "chunks": chunks,
        "interrupted": bool(not first.ok
                            and first.error == ERR_TRANSFER_ABORT),
        "first": dict(first.transfer or {}),
        "second": t2,
        "completed": bool(second.ok),
        "byte_identical": bool(
            ref.ok and second.ok
            and out.tobytes()
            == np.asarray(ref.payload, dtype=np.uint8).tobytes()),
        "resent_only_unacked": bool(
            second.ok and t2.get("resumed")
            and t2.get("skipped", 0) > 0
            and t2.get("sent", chunks) < chunks),
    }
    print(f"# resume drill: size={size} chunks={chunks} "
          f"interrupted={doc['interrupted']} "
          f"acked_before_resume={t2.get('skipped')} "
          f"resent={t2.get('sent')} "
          f"byte_identical={doc['byte_identical']}", file=sys.stderr)
    return doc


def _pulse_section(pulse_t) -> dict | None:
    """The artifact's ``alerts`` section from the router's live pulse
    engine (same shape as serve/bench.py's): one final ``tick()`` so
    the tail of the drive sits inside the last window, then the
    engine's document. None when the engine never ran."""
    if pulse_t is None:
        return None
    try:
        pulse_t.tick()
        adoc = pulse_t.engine.alerts_doc()
    except Exception:
        return None
    return {"total": adoc["total"], "fired": adoc["fired"],
            "rows": adoc["alerts"], "frames": adoc["frames"]}


def _fleet_capacity(healthz) -> dict | None:
    """The artifact's ``capacity`` section: each worker's pulse engine
    publishes its live blocks/s estimate on /healthz, the router's
    gossip cached the documents — sum them into the fleet view the
    headroom autoscaler polices."""
    rows = {}
    total = 0.0
    for name, doc in sorted((healthz or {}).items()):
        cap = (doc or {}).get("capacity")
        if isinstance(cap, dict):
            rows[name] = cap
            try:
                total += float(cap.get("total_blocks_per_s") or 0.0)
            except (TypeError, ValueError):
                pass
    if not rows:
        return None
    return {"backends": rows, "total_blocks_per_s": round(total, 3)}


async def _drive(args, specs, affinity: bool, probes,
                 handles=None, drill: bool = False):
    transfers_on = bool(getattr(args, "transfer_sizes", ()))
    cfg = RouterConfig(
        deadline_s=args.deadline,
        attempt_timeout_s=args.attempt_timeout,
        gossip_every_s=args.gossip_every,
        probation_batches=args.probation_batches,
        vnodes=args.vnodes,
        affinity=affinity,
        seed=args.seed,
        journal=args.journal if affinity else None,
        # Response frames carry up to one full top-rung payload; size
        # the router's read ceiling to THIS fleet's ladder.
        max_frame_bytes=max(args.bucket_max * 16 * 2, wire.MAX_PAYLOAD),
        # The chunk rung IS the fleet's top rung: every chunk is an
        # ordinary ladder-shaped request to a backend.
        transfer_chunk_blocks=(args.bucket_max if transfers_on else None),
        transfer_deadline_s=(args.transfer_deadline if transfers_on
                             else 300.0),
        # Size the reassembly budget so the drive's own mix can never
        # shed itself (backpressure is exercised by tests, not here).
        transfer_budget_bytes=(max(64 << 20,
                                   2 * max(args.transfer_sizes))
                               if transfers_on else 64 << 20),
        transfer_ledger=(args.transfer_ledger
                         if transfers_on and affinity else None))
    router = Router(specs, cfg)
    await router.start()
    status = None
    if args.status_port is not None and affinity:
        status = RouterStatus(router, args.status_port,
                              federate=not args.no_federate)
        await status.start()
        print(f"# router status: 127.0.0.1:{status.port} "
              f"(federated /metrics: {not args.no_federate})",
              file=sys.stderr)
    killer = None
    if handles and getattr(args, "kill_backend_after", None) is not None:

        async def _kill():
            await asyncio.sleep(args.kill_backend_after)
            h = handles[-1]
            print(f"# chaos: SIGKILL backend {h.name} (pid {h.pid}) "
                  f"at +{args.kill_backend_after:g}s", file=sys.stderr)
            await asyncio.get_running_loop().run_in_executor(None, h.kill)

        killer = asyncio.create_task(_kill())
    report = await loadgen.run(
        router, args.requests, concurrency=args.concurrency,
        sizes=args.sizes, tenants=args.tenants,
        keys_per_tenant=args.keys_per_tenant, seed=args.seed,
        verify_every=args.verify_every, probes=probes,
        arrival_rate=args.arrival_rate, modes=args.mode_list,
        transfer_sizes=(args.transfer_sizes if transfers_on else ()),
        transfer_every=(getattr(args, "transfer_every", 0)
                        if transfers_on else 0))
    if killer is not None:
        killer.cancel()
        try:
            await killer
        except asyncio.CancelledError:
            pass
    resume = None
    if drill and router.transfers is not None:
        resume = await _resume_drill(args, router)
    # One final gossip pass so the artifact's backend view is current.
    await router.gossip_once()
    healthz = {name: b.last_healthz
               for name, b in router.backends.items()}
    if status is not None:
        await status.stop()
    await router.stop()
    return router, report, healthz, resume


async def _drive_fleet(args, probes) -> dict:
    """The ELASTICITY drive (``--autoscale``): the fleet supervisor owns
    every worker's lifecycle over one live open-loop drive — scale up
    against real pressure, roll one worker through the bit-exact canary
    handoff, lose one router replica to SIGKILL, scale back down to the
    floor once the load passes — while the zero-lost / bit-exact /
    zero-recompile contracts hold throughout. Returns everything
    ``_main_fleet`` folds into the artifact."""
    env = {k: v for k, v in os.environ.items() if k != "OT_FAULTS"}
    wargv = worker_argv(
        engine=args.engine, bucket_min=args.bucket_min,
        bucket_max=args.bucket_max, queue_depth=args.worker_queue_depth,
        tenant_depth_frac=args.tenant_depth_frac,
        dispatch_deadline=args.dispatch_deadline,
        modes=",".join(args.mode_list), lanes=args.worker_lanes)

    def factory(name: str) -> ProcessWorkerHandle:
        return ProcessWorkerHandle(name, wargv, env=dict(env),
                                   ready_deadline_s=READY_DEADLINE_S)

    loop = asyncio.get_running_loop()
    max_frame = max(args.bucket_max * 16 * 2, wire.MAX_PAYLOAD)

    # -- the floor fleet (b0..), booted concurrently through the SAME
    # handle/argv template the autoscaler will spawn with, then handed
    # to the supervisor so retire/roll own the full lifecycle.
    names = [f"b{i}" for i in range(args.backends)]
    handles = [factory(n) for n in names]
    replicas: list[dict] = []
    sup = None

    async def _abandon():
        for r in replicas:
            await loop.run_in_executor(None, r["handle"].kill)
        fleet = (list(sup.workers.values()) if sup is not None
                 else list(handles))
        for h in fleet:
            await h.kill()

    try:
        specs = []
        for n, spec in zip(names,
                           await asyncio.gather(*(h.start()
                                                  for h in handles))):
            if spec is None:
                raise RuntimeError(
                    f"fleet worker {n} never came ready within "
                    f"{READY_DEADLINE_S:.0f}s")
            specs.append(spec)
            print(f"# worker {n}: port {spec.port} "
                  f"status {spec.status_port} pid {spec.pid}",
                  file=sys.stderr)

        cfg = RouterConfig(
            deadline_s=args.deadline,
            attempt_timeout_s=args.attempt_timeout,
            gossip_every_s=args.gossip_every,
            probation_batches=args.probation_batches,
            vnodes=args.vnodes, affinity=True, seed=args.seed,
            journal=args.journal, max_frame_bytes=max_frame)
        router = Router(specs, cfg)
        await router.start()

        sup = FleetSupervisor(router, factory, FleetConfig(
            min_workers=args.backends, max_workers=args.fleet_max,
            up_depth=args.up_depth, down_depth=args.down_depth,
            up_busy=args.up_busy, settle_ticks=args.settle_ticks,
            down_settle_ticks=args.down_settle_ticks,
            cooldown_s=args.cooldown, poll_every_s=args.poll_every,
            policy=args.fleet_policy,
            headroom_frac=args.headroom_frac))
        for n, h in zip(names, handles):
            sup.adopt(n, h)

        status = None
        if args.status_port is not None:
            status = RouterStatus(router, args.status_port,
                                  federate=not args.no_federate,
                                  fleet=sup)
            await status.start()
            print(f"# router status: 127.0.0.1:{status.port} "
                  f"(/fleetz live)", file=sys.stderr)

        # -- the replicated router tier: the owner exposes its Router +
        # membership authority on the framed wire; each replica process
        # gossips with it and serves the same fleet. The failover
        # client leads with replica r0 (the one the chaos step kills)
        # and falls back to the owner, then the remaining replicas.
        owner_server = None
        client = router
        if args.routers > 0:
            owner_server = RouterServer(
                router, view_fn=lambda: (sup.epoch, sup.view()),
                max_frame_bytes=max_frame)
            await owner_server.start()
            member_json = json.dumps([
                {"name": s.name, "host": s.host, "port": s.port,
                 "status_port": s.status_port} for s in specs])
            for j in range(args.routers):
                argv = [sys.executable, "-m", "our_tree_tpu.route.fleet",
                        "--port", "0", "--backends", member_json,
                        "--peer", f"127.0.0.1:{owner_server.port}",
                        "--gossip-every",
                        str(min(args.gossip_every, 0.25)),
                        "--attempt-timeout", str(args.attempt_timeout),
                        "--deadline", str(args.deadline),
                        "--max-frame-bytes", str(max_frame)]
                h = isolate.spawn_service(argv, env=dict(env),
                                          name=f"route:r{j}")
                line = await loop.run_in_executor(
                    None, h.read_line, READY_DEADLINE_S)
                doc = None
                if line:
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        doc = None
                if not (isinstance(doc, dict)
                        and doc.get("kind") == REPLICA_KIND):
                    replicas.append({"name": f"r{j}", "handle": h,
                                     "killed": False})
                    raise RuntimeError(
                        f"router replica r{j} (pid {h.pid}) never came "
                        f"ready (got {line!r})")
                replicas.append({"name": f"r{j}", "handle": h,
                                 "port": int(doc["port"]),
                                 "killed": False})
                print(f"# router replica r{j}: pid {h.pid} "
                      f"port {doc['port']}", file=sys.stderr)
            peers = ([("127.0.0.1", replicas[0]["port"]),
                      ("127.0.0.1", owner_server.port)]
                     + [("127.0.0.1", r["port"]) for r in replicas[1:]])
            client = FailoverClient(
                peers, attempt_timeout_s=args.attempt_timeout,
                deadline_s=args.deadline, max_frame_bytes=max_frame)

        # -- the chaos timeline, next to the supervisor loop.
        stop_ev = asyncio.Event()
        sup_task = asyncio.ensure_future(sup.run(stop_ev))
        t0 = time.monotonic()
        chaos: list[asyncio.Task] = []

        async def arm_faults_later():
            # Armed AFTER the startup canaries (and optionally deep
            # into the drive): the injected fault rehearses the
            # steady-state seams — a stale pooled socket with a live
            # fleet to redispatch into — not the join checks, and not
            # a one-member ring with nowhere to go.
            await asyncio.sleep(args.drive_faults_after)
            os.environ["OT_FAULTS"] = args.drive_faults
            faults.reset()
            print(f"# faults armed at +{time.monotonic() - t0:.1f}s: "
                  f"{args.drive_faults}", file=sys.stderr)

        if args.drive_faults:
            chaos.append(asyncio.ensure_future(arm_faults_later()))

        async def roll_later():
            await asyncio.sleep(args.roll_after)
            ok = await sup.roll_one()
            print(f"# roll at +{time.monotonic() - t0:.1f}s: "
                  f"{'replaced' if ok else 'ABORTED'}", file=sys.stderr)

        async def kill_router_later():
            await asyncio.sleep(args.kill_router_after)
            r = replicas[0]
            r["killed"] = True
            await loop.run_in_executor(None, r["handle"].kill)
            trace.point("router-killed", replica=r["name"],
                        pid=r["handle"].pid)
            print(f"# router {r['name']} SIGKILLed at "
                  f"+{time.monotonic() - t0:.1f}s", file=sys.stderr)

        if args.roll_after is not None:
            chaos.append(asyncio.ensure_future(roll_later()))
        if args.kill_router_after is not None and replicas:
            chaos.append(asyncio.ensure_future(kill_router_later()))

        report = await loadgen.run(
            client, args.requests, concurrency=args.concurrency,
            sizes=args.sizes, tenants=args.tenants,
            keys_per_tenant=args.keys_per_tenant, seed=args.seed,
            verify_every=args.verify_every, probes=probes,
            arrival_rate=args.arrival_rate, modes=args.mode_list)
        for c in await asyncio.gather(*chaos, return_exceptions=True):
            if isinstance(c, BaseException):
                raise c

        # -- the settle window: load has passed, the supervisor keeps
        # ticking against an idle fleet until it has shrunk back to the
        # floor (the deterministic scale-down) or the window closes.
        # A held resize lock counts as "not settled": a queued scale
        # event may still move the size after we read it.
        t_end = time.monotonic() + args.settle_timeout
        while (time.monotonic() < t_end
               and (len(router.backends) > args.backends
                    or sup.resizing)):
            await asyncio.sleep(args.poll_every)
        stop_ev.set()
        await sup_task

        await router.gossip_once()
        healthz = {name: b.last_healthz
                   for name, b in router.backends.items()}
        rstats = router.stats()
        releases = router.release_events()
        fleet_doc = sup.fleetz()

        router_docs = []
        for r in replicas:
            h = r["handle"]
            rc = await loop.run_in_executor(None, h.stop, 30.0)
            out, _err = h.drain_output()
            doc = {}
            for raw in reversed(out.splitlines()):
                try:
                    cand = json.loads(raw)
                except ValueError:
                    continue
                if (isinstance(cand, dict)
                        and cand.get("kind") == REPLICA_EXIT_KIND):
                    doc = cand
                    break
            router_docs.append({"name": r["name"], "rc": rc,
                                "killed": r["killed"], **doc})

        if status is not None:
            await status.stop()
        if owner_server is not None:
            await owner_server.stop()
        await sup.close(drain=True)
        await router.stop()
        # The engine object outlives its thread: fold the router-tier
        # pulse verdict into the result before the router goes out of
        # scope (the fleet drive returns a dict, not the router).
        pulse_doc = _pulse_section(router.pulse)
    except BaseException:
        await _abandon()
        raise

    client_stats = None
    if isinstance(client, FailoverClient):
        client_stats = {"submitted": client.submitted,
                        "failovers": client.failovers,
                        "backpressure_retries": client.backpressure_retries,
                        "peers": len(client.peers)}
    return {"report": report, "router": rstats, "healthz": healthz,
            "releases": releases, "fleet": fleet_doc,
            "events": list(sup.events), "workers": sup.exit_docs,
            "routers": router_docs, "client": client_stats,
            "pulse": pulse_doc}


def _main_fleet(args, probes) -> int:
    """The ``--autoscale`` tail of ``main``: run the elasticity drive,
    narrate it, write the artifact, apply the fleet gates."""
    res = asyncio.run(_drive_fleet(args, probes))
    report, rstats = res["report"], res["router"]
    fleet, client = res["fleet"], res["client"]
    exit_docs = res["workers"]

    lost_workers = sum(int(d.get("lost") or 0) for d in exit_docs)
    crashed = [d for d in exit_docs if d.get("rc")]
    lost_replicas = sum(int(d.get("lost") or 0) for d in res["routers"]
                        if not d["killed"])
    replica_bad_rc = [d for d in res["routers"]
                      if not d["killed"] and d.get("rc")]
    lost_router = rstats["lost"]
    recompiles = sum(int(d.get("recompiles") or 0) for d in exit_docs)
    waterfall = waterfall_stats(report.ledgers)
    wire_p50 = (waterfall["stages"].get("wire") or {}).get("p50_us")
    pool = dict(rstats.get("pool_retired")
                or {"hits": 0, "dials": 0, "stale": 0})
    for b in rstats["backends"].values():
        for k in pool:
            pool[k] += int((b.get("pool") or {}).get(k, 0))
    # The before/after the pool satellite promises: the committed
    # pre-pool wire p50 (ROUTE_r02 pinned it) next to this run's.
    prepool_wire_p50 = None
    try:
        with open(os.path.join(_repo_root(), "ROUTE_r02.json"),
                  encoding="utf-8") as fh:
            prepool_wire_p50 = json.load(
                fh)["waterfall"]["stages"]["wire"]["p50_us"]
    except (OSError, ValueError, KeyError):
        pass

    print(f"# fleet: floor={args.backends} max={args.fleet_max} "
          f"policy={args.fleet_policy} "
          f"up_depth={args.up_depth:g} down_depth={args.down_depth:g} "
          f"cooldown={args.cooldown:g}s routers={args.routers}")
    print(f"# requests={report.requests} ok={report.ok} "
          f"errors={report.errors or '{}'} lost_router={lost_router} "
          f"lost_replicas={lost_replicas} lost_workers={lost_workers} "
          f"verified={report.verified} mismatches={report.mismatches}")
    print(f"# latency ms: p50={report.p50_ms} p95={report.p95_ms} "
          f"p99={report.p99_ms}  goodput={report.goodput_gbps:.4f} GB/s "
          f"wall={report.wall_s:.3f}s")
    print(f"# elasticity: ups={fleet['scale_ups']} "
          f"downs={fleet['scale_downs']} rolled={fleet['rolled']} "
          f"roll_aborts={fleet['roll_aborts']} stalls={fleet['stalls']} "
          f"spawn_failures={fleet['spawn_failures']} "
          f"drained_lost={fleet['drained_lost']}")
    for ev in res["events"]:
        print(f"#   event {ev['kind']:<12} worker={ev['worker'] or '-'} "
              f"size={ev['size']} epoch={ev['epoch']}"
              + (f" successor={ev['successor']}"
                 if "successor" in ev else ""))
    if client is not None:
        print(f"# router tier: peers={client['peers']} "
              f"client_failovers={client['failovers']} "
              f"backpressure_retries={client['backpressure_retries']} "
              + " ".join(f"{d['name']}:"
                         f"{'KILLED' if d['killed'] else d.get('rc')}"
                         f"/lost={d.get('lost')}"
                         for d in res["routers"]))
    print(f"# pool: hits={pool['hits']} dials={pool['dials']} "
          f"stale={pool['stale']}  wire_p50={wire_p50}µs "
          f"(pre-pool ROUTE_r02: {prepool_wire_p50}µs)  "
          f"redispatches={rstats['redispatches']}")
    if waterfall["sampled"]:
        print(f"# waterfall: {waterfall['complete']}/"
              f"{waterfall['sampled']} sampled requests complete "
              f"({waterfall['complete_frac']:.1%}), stage sum within "
              f"{waterfall['tolerance']:.0%} of e2e on "
              f"{waterfall['sum_within_tol_frac']:.1%} of them")
        for s in WATERFALL_STAGES:
            st = waterfall["stages"].get(s)
            if st and st["count"]:
                print(f"#   stage {s:<13} p50={st['p50_us']:>8.0f}µs "
                      f"p95={st['p95_us']:>8.0f}µs "
                      f"p99={st['p99_us']:>8.0f}µs  (n={st['count']})")
    pulse_doc = res["pulse"]
    capacity = _fleet_capacity(res["healthz"])
    if pulse_doc is not None:
        fired = (" ".join(f"{r}x{n}"
                          for r, n in pulse_doc["fired"].items())
                 or "none")
        print(f"# pulse: {pulse_doc['total']} alert(s) over "
              f"{pulse_doc['frames']} frame(s) (fired: {fired})")
    if capacity is not None:
        print(f"# capacity: fleet "
              f"{capacity['total_blocks_per_s']:g} blocks/s across "
              f"{len(capacity['backends'])} worker(s)")

    artifact = {
        "config": {
            "backends": args.backends, "requests": args.requests,
            "concurrency": args.concurrency, "sizes": list(args.sizes),
            "tenants": args.tenants,
            "keys_per_tenant": args.keys_per_tenant,
            "engine": args.engine, "vnodes": args.vnodes,
            "modes": list(args.mode_list),
            "affinity": True, "ab": False, "autoscale": True,
            "attempt_timeout_s": args.attempt_timeout,
            "gossip_every_s": args.gossip_every,
            "worker_lanes": args.worker_lanes,
            "arrival_rate": args.arrival_rate,
            "seed": args.seed,
            "fleet": {"max_workers": args.fleet_max,
                      "policy": args.fleet_policy,
                      "headroom_frac": args.headroom_frac,
                      "up_depth": args.up_depth,
                      "down_depth": args.down_depth,
                      "up_busy": args.up_busy,
                      "settle_ticks": args.settle_ticks,
                      "down_settle_ticks": args.down_settle_ticks,
                      "cooldown_s": args.cooldown,
                      "poll_every_s": args.poll_every,
                      "roll_after_s": args.roll_after,
                      "routers": args.routers,
                      "kill_router_after_s": args.kill_router_after,
                      "drive_faults": args.drive_faults,
                      "drive_faults_after_s": args.drive_faults_after},
        },
        "load": report.to_json(),
        "router": rstats,
        "queue": {"lost": lost_router + lost_replicas + lost_workers,
                  "lost_router": lost_router,
                  "lost_replicas": lost_replicas,
                  "lost_workers": lost_workers},
        "compiles": {"steady": recompiles},
        "workers": exit_docs,
        "fleet": {**fleet, "events": res["events"]},
        "routers": {"count": args.routers, "docs": res["routers"],
                    "client": client},
        "pool": {**pool, "wire_p50_us": wire_p50,
                 "wire_p50_us_prepool_r02": prepool_wire_p50},
        "waterfall": waterfall,
        "stages": waterfall["stages"],
        "healthz": res["healthz"],
        "alerts": pulse_doc,
        "capacity": capacity,
        "degraded": degrade.events(),
        "metrics": metrics.snapshot(),
    }
    if trace.enabled():
        artifact["obs"] = trace.metrics_snapshot()
        artifact["trace_sample"] = trace.sample_rate()
    path = args.artifact or _next_artifact(_repo_root())
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(f"# artifact: {path}", file=sys.stderr)

    slo_rc = 0
    if args.slo:
        try:
            slo_rc = slo.gate(args.slo, artifact, args.slo_tolerance)
        except (OSError, ValueError, KeyError) as e:
            print(f"# slo: gate unusable: {e}", file=sys.stderr)
            slo_rc = 1

    line = {"unit": "route-fleet", "backends": args.backends,
            "requests": report.requests, "ok": report.ok,
            "errors": dict(sorted(report.errors.items())),
            "lost": lost_router + lost_replicas + lost_workers,
            "p50_ms": report.p50_ms, "p95_ms": report.p95_ms,
            "p99_ms": report.p99_ms,
            "goodput_gbps": round(report.goodput_gbps, 4),
            "scale_ups": fleet["scale_ups"],
            "scale_downs": fleet["scale_downs"],
            "rolled": fleet["rolled"],
            "roll_aborts": fleet["roll_aborts"],
            "client_failovers": (client or {}).get("failovers", 0),
            "redispatches": rstats["redispatches"],
            "recompiles": recompiles,
            "mismatches": report.mismatches,
            "pool_hits": pool["hits"], "wire_p50_us": wire_p50,
            "waterfall_complete_frac": waterfall["complete_frac"],
            "waterfall_sum_ok_frac": waterfall["sum_within_tol_frac"]}
    if args.slo:
        line["slo"] = "fail" if slo_rc else "pass"
    if degrade.events():
        line["degraded"] = degrade.events()
    if pulse_doc is not None and pulse_doc["total"]:
        line["alerts"] = pulse_doc["fired"]
    print(json.dumps(line))

    rc = 0
    if report.mismatches:
        print(f"# FAIL: {report.mismatches} probe response(s) mismatched "
              "the byte-exact reference THROUGH the elastic fleet",
              file=sys.stderr)
        rc = 1
    if lost_router or lost_replicas or lost_workers:
        print(f"# FAIL: lost requests (router={lost_router}, "
              f"replicas={lost_replicas}, workers={lost_workers}) — the "
              "drain/failover contract is broken", file=sys.stderr)
        rc = 1
    if crashed:
        print(f"# FAIL: worker(s) exited nonzero: "
              + ", ".join(f"{d['name']}:rc={d['rc']}" for d in crashed),
              file=sys.stderr)
        rc = 1
    if replica_bad_rc:
        print(f"# FAIL: surviving router replica(s) exited nonzero: "
              + ", ".join(f"{d['name']}:rc={d['rc']}"
                          for d in replica_bad_rc), file=sys.stderr)
        rc = 1
    if recompiles and not args.allow_recompiles:
        print(f"# FAIL: {recompiles} post-warmup backend compile(s) "
              "across the fleet (--allow-recompiles to waive)",
              file=sys.stderr)
        rc = 1
    if args.require_zero_errors and report.errors:
        print(f"# FAIL: request errors {report.errors} — failover did "
              "not absorb the churn", file=sys.stderr)
        rc = 1
    if (args.min_scale_ups is not None
            and fleet["scale_ups"] < args.min_scale_ups):
        print(f"# FAIL: {fleet['scale_ups']} scale-up(s) < "
              f"{args.min_scale_ups} — the autoscaler never grew the "
              "fleet", file=sys.stderr)
        rc = 1
    if (args.min_scale_downs is not None
            and fleet["scale_downs"] < args.min_scale_downs):
        print(f"# FAIL: {fleet['scale_downs']} scale-down(s) < "
              f"{args.min_scale_downs} — the fleet never shrank back",
              file=sys.stderr)
        rc = 1
    if args.expect_rolls is not None:
        if fleet["rolled"] != args.expect_rolls:
            print(f"# FAIL: {fleet['rolled']} rolled worker(s), expected "
                  f"exactly {args.expect_rolls}", file=sys.stderr)
            rc = 1
        if fleet["roll_aborts"]:
            print(f"# FAIL: {fleet['roll_aborts']} roll abort(s) — the "
                  "canary handoff rejected a successor", file=sys.stderr)
            rc = 1
    if (args.min_client_failovers is not None
            and (client or {}).get("failovers", 0)
            < args.min_client_failovers):
        print(f"# FAIL: {(client or {}).get('failovers', 0)} client "
              f"failover(s) < {args.min_client_failovers} — the router "
              "kill never exercised the tier", file=sys.stderr)
        rc = 1
    if (args.min_redispatch is not None
            and rstats["redispatches"] < args.min_redispatch):
        print(f"# FAIL: redispatches {rstats['redispatches']} < "
              f"{args.min_redispatch} — the injected pool fault never "
              "rode the ring-retry failover", file=sys.stderr)
        rc = 1
    if args.max_wire_p50_us is not None:
        if wire_p50 is None or wire_p50 > args.max_wire_p50_us:
            print(f"# FAIL: wire stage p50 {wire_p50}µs not under "
                  f"{args.max_wire_p50_us:g}µs — pooling bought nothing "
                  f"(pre-pool ROUTE_r02: {prepool_wire_p50}µs)",
                  file=sys.stderr)
            rc = 1
    if (args.min_waterfall_complete is not None
            and waterfall["complete_frac"] < args.min_waterfall_complete):
        print(f"# FAIL: only {waterfall['complete_frac']:.1%} of sampled "
              f"requests reconstructed a complete cross-process "
              f"waterfall (< {args.min_waterfall_complete:.1%})",
              file=sys.stderr)
        rc = 1
    if (args.min_stage_sum_ok is not None
            and waterfall["sum_within_tol_frac"] < args.min_stage_sum_ok):
        print(f"# FAIL: stage sums match end-to-end latency on only "
              f"{waterfall['sum_within_tol_frac']:.1%} of complete "
              f"waterfalls (< {args.min_stage_sum_ok:.1%})",
              file=sys.stderr)
        rc = 1
    if slo_rc:
        print(f"# FAIL: SLO regression against {args.slo}",
              file=sys.stderr)
        rc = 1
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m our_tree_tpu.route.bench",
        description="routing-tier drive over N spawned ot-serve backend "
                    "processes (docs/SERVING.md)")
    ap.add_argument("--backends", type=int, default=3, metavar="N")
    ap.add_argument("--requests", type=int, default=500)
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--arrival-rate", type=float, default=None,
                    metavar="REQ_PER_S",
                    help="open-loop mode (serve.bench semantics)")
    ap.add_argument("--mixed-sizes", action="store_true")
    ap.add_argument("--sizes", default=None, metavar="B1,B2",
                    help="explicit request-size menu in bytes (comma "
                         "list; overrides --mixed-sizes/--size-bytes). "
                         "A gcm mix wants the top size one rung under "
                         "the bucket ceiling: the J0 row rides each "
                         "request (serve.bench's sizing note)")
    ap.add_argument("--size-bytes", type=int, default=4096)
    ap.add_argument("--modes", default="ctr", metavar="M1,M2",
                    help="served-mode MIX routed through the fleet "
                         "(serve/queue.py MODES): every worker enables "
                         "and warms exactly these ladders, the loadgen "
                         "draws each request's mode uniformly, and gcm "
                         "probes pin ciphertext AND tag bit-exactly "
                         "THROUGH the router (affinity + failover "
                         "included — docs/SERVING.md, AEAD section)")
    ap.add_argument("--tenants", type=int, default=8)
    ap.add_argument("--keys-per-tenant", type=int, default=2)
    ap.add_argument("--engine", default="auto",
                    help="backend serve engine tier (serve.worker "
                         "--engine; auto = native AESNI on CPU)")
    ap.add_argument("--worker-lanes", type=int, default=None, metavar="N")
    ap.add_argument("--worker-queue-depth", type=int, default=1024)
    ap.add_argument("--tenant-depth-frac", type=float, default=1.0,
                    metavar="FRAC")
    ap.add_argument("--bucket-min", type=int, default=32, metavar="BLOCKS")
    ap.add_argument("--bucket-max", type=int, default=4096,
                    metavar="BLOCKS")
    ap.add_argument("--deadline", type=float, default=30.0,
                    help="per-request end-to-end Budget, seconds")
    ap.add_argument("--attempt-timeout", type=float, default=5.0,
                    metavar="S",
                    help="wall deadline per backend attempt — the bound "
                         "that turns a hung backend into failover")
    ap.add_argument("--dispatch-deadline", type=float, default=10.0,
                    help="each BACKEND's per-lane watchdog deadline")
    ap.add_argument("--gossip-every", type=float, default=1.0, metavar="S")
    ap.add_argument("--probation-batches", type=int, default=2)
    ap.add_argument("--vnodes", type=int, default=64)
    ap.add_argument("--no-affinity", action="store_true",
                    help="random routing only (the control arm alone)")
    ap.add_argument("--ab", action="store_true",
                    help="run BOTH arms over fresh backend sets and "
                         "record the keycache hit-ratio comparison")
    ap.add_argument("--min-affinity-gain", type=float, default=None,
                    metavar="FRAC",
                    help="with --ab: fail unless affinity hit ratio "
                         "exceeds the random arm's by more than FRAC "
                         "(default 0: strictly greater)")
    ap.add_argument("--verify-every", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="router journal (backend quarantine "
                         "persistence; docs/RESILIENCE.md)")
    ap.add_argument("--unquarantine", action="append", default=None,
                    metavar="BACKEND",
                    help="release the named backend (e.g. backend:b1) by "
                         "dropping its failure rows from --journal, then "
                         "exit — the same clear_failures edit as "
                         "harness.bench/serve.bench")
    ap.add_argument("--status-port", type=int, default=None, metavar="PORT",
                    help="router /metrics + /healthz (with the "
                         "ring/backend membership view) for the drive's "
                         "duration (0 = ephemeral). /metrics is the "
                         "FEDERATED fleet scrape by default: the "
                         "router's registry plus every backend's, "
                         "relabeled backend=<name> (docs/SERVING.md)")
    ap.add_argument("--no-federate", action="store_true",
                    help="serve only the router's own /metrics (no "
                         "backend federation)")
    ap.add_argument("--min-waterfall-complete", type=float, default=None,
                    metavar="FRAC",
                    help="fail unless at least FRAC of the sampled "
                         "requests reconstructed a COMPLETE cross-"
                         "process waterfall (router + backend ledger "
                         "halves, every stage present)")
    ap.add_argument("--min-stage-sum-ok", type=float, default=None,
                    metavar="FRAC",
                    help="fail unless at least FRAC of the complete "
                         "waterfalls have a stage sum within 5%% of the "
                         "measured end-to-end latency (the attribution "
                         "consistency gate)")
    ap.add_argument("--slo", default=None, metavar="BASELINE.json",
                    help="gate this run against a committed "
                         "ROUTE_r*.json baseline (obs/slo.py)")
    ap.add_argument("--slo-tolerance", default=None, metavar="SPEC")
    ap.add_argument("--artifact", default=None, metavar="PATH")
    ap.add_argument("--allow-recompiles", action="store_true")
    ap.add_argument("--require-zero-errors", action="store_true",
                    help="fail on ANY per-request error response (the "
                         "backend-kill drive's 0-errors gate: failover "
                         "must absorb the fault)")
    ap.add_argument("--expect-quarantines", type=int, default=None,
                    metavar="N",
                    help="fail unless the run saw exactly N backend "
                         "quarantine events")
    ap.add_argument("--expect-releases", type=int, default=None,
                    metavar="N",
                    help="fail unless exactly N probation releases "
                         "completed")
    ap.add_argument("--min-redispatch", type=int, default=None, metavar="N",
                    help="fail unless redispatches >= N (the failover "
                         "actually happened)")
    st = ap.add_argument_group(
        "streaming transfers (ot-stream; docs/SERVING.md)")
    st.add_argument("--transfer-sizes", default=None, metavar="B1,B2",
                    help="oversized payload menu in bytes (comma list, "
                         "each a multiple of 16 ABOVE the top "
                         "--bucket-max rung): enables router-side "
                         "chunked transfers sized to this fleet's "
                         "ladder and mixes one ALWAYS-verified "
                         "transfer into the load every "
                         "--transfer-every requests. Names the "
                         "artifact family STREAM_r*")
    st.add_argument("--transfer-every", type=int, default=32,
                    metavar="N",
                    help="issue a transfer probe every N requests "
                         "(default 32)")
    st.add_argument("--transfer-deadline", type=float, default=300.0,
                    metavar="S",
                    help="per-TRANSFER end-to-end Budget, seconds "
                         "(each chunk dispatch gets the remainder)")
    st.add_argument("--transfer-ledger", default=None, metavar="PATH",
                    help="durable acked-chunk ledger (the resume "
                         "contract; docs/RESILIENCE.md)")
    st.add_argument("--kill-backend-after", type=float, default=None,
                    metavar="S",
                    help="SIGKILL the LAST backend this many seconds "
                         "in — mid-transfer chunks must fail over "
                         "bit-exactly; the victim's rc is exempt from "
                         "the drain gate")
    st.add_argument("--worker-faults", default=None, metavar="SPEC",
                    help="OT_FAULTS spec armed in worker b0 ONLY "
                         "(e.g. lane_hang:1 — the hung-lane half of "
                         "the mid-transfer chaos drive; the spawner "
                         "still strips the ROUTER's spec from every "
                         "worker)")
    st.add_argument("--resume-drill", action="store_true",
                    help="after the load: interrupt one transfer with "
                         "a transfer_abort shot, resume it by token, "
                         "and gate byte-identity + only-unacked-chunks"
                         "-resent")
    st.add_argument("--min-chunk-redispatch", type=int, default=None,
                    metavar="N",
                    help="fail unless the transfer engine re-sent at "
                         "least N chunks (chunk_lost discards + shed "
                         "retries)")
    fl = ap.add_argument_group(
        "fleet elasticity (--autoscale; docs/SERVING.md)")
    fl.add_argument("--autoscale", action="store_true",
                    help="hand the worker fleet to the FleetSupervisor: "
                         "--backends is the floor, the drive scales up "
                         "under pressure and drains back down once load "
                         "passes (route/fleet.py)")
    fl.add_argument("--fleet-max", type=int, default=4, metavar="N",
                    help="autoscaler ceiling (default 4)")
    fl.add_argument("--fleet-policy", choices=("static", "headroom"),
                    default="static",
                    help="grow policy: 'static' keeps the depth/busy "
                         "thresholds alone; 'headroom' ALSO grows when "
                         "measured offered load reaches --headroom-frac "
                         "of the fleet's live capacity estimate (the "
                         "workers' pulse engines publish blocks/s on "
                         "/healthz; route/fleet.py folds them)")
    fl.add_argument("--headroom-frac", type=float, default=0.80,
                    metavar="FRAC",
                    help="offered/capacity ratio that triggers headroom "
                         "growth (default 0.8)")
    fl.add_argument("--up-depth", type=float, default=8.0, metavar="D",
                    help="mean queue depth per worker that triggers a "
                         "scale-up (default 8)")
    fl.add_argument("--down-depth", type=float, default=1.0, metavar="D",
                    help="mean depth the fleet must idle UNDER before a "
                         "scale-down (default 1)")
    fl.add_argument("--up-busy", type=float, default=0.95, metavar="FRAC",
                    help="lane-busy fraction that also triggers growth")
    fl.add_argument("--settle-ticks", type=int, default=2, metavar="N",
                    help="consecutive out-of-band polls before a scale "
                         "event (hysteresis; default 2)")
    fl.add_argument("--down-settle-ticks", type=int, default=None,
                    metavar="N",
                    help="separate (usually much larger) settle count "
                         "for shrinking: pressure is bursty, idleness "
                         "must be sustained (default: --settle-ticks)")
    fl.add_argument("--cooldown", type=float, default=3.0, metavar="S",
                    help="minimum seconds between fleet resizes")
    fl.add_argument("--poll-every", type=float, default=0.25, metavar="S",
                    help="supervisor poll period")
    fl.add_argument("--roll-after", type=float, default=None, metavar="S",
                    help="start a rolling upgrade of ONE worker this many "
                         "seconds into the drive (bit-exact canary "
                         "handoff — the successor must answer the join "
                         "canaries byte-for-byte or the roll aborts)")
    fl.add_argument("--routers", type=int, default=0, metavar="N",
                    help="spawn N replicated router processes "
                         "(route.fleet replicas) gossiping with the "
                         "in-process owner; the loadgen drives the tier "
                         "through the failover client")
    fl.add_argument("--kill-router-after", type=float, default=None,
                    metavar="S",
                    help="SIGKILL replica r0 this many seconds in — the "
                         "failover client must carry every in-flight and "
                         "subsequent request to the surviving peers")
    fl.add_argument("--drive-faults", default=None, metavar="SPEC",
                    help="OT_FAULTS spec armed AFTER router start + "
                         "startup canaries (so join checks never absorb "
                         "the shots), e.g. pool_stale:1@backend=0")
    fl.add_argument("--drive-faults-after", type=float, default=0.0,
                    metavar="S",
                    help="arm --drive-faults this many seconds into the "
                         "drive (late enough that the fleet has already "
                         "scaled up: a stale-socket redispatch needs a "
                         "second member to land on)")
    fl.add_argument("--settle-timeout", type=float, default=30.0,
                    metavar="S",
                    help="post-load window for the fleet to drain back "
                         "to the floor before the drive stops waiting")
    fl.add_argument("--min-scale-ups", type=int, default=None, metavar="N",
                    help="fail unless the autoscaler grew the fleet at "
                         "least N times")
    fl.add_argument("--min-scale-downs", type=int, default=None,
                    metavar="N",
                    help="fail unless the fleet shrank at least N times")
    fl.add_argument("--expect-rolls", type=int, default=None, metavar="N",
                    help="fail unless exactly N workers rolled AND no "
                         "roll aborted")
    fl.add_argument("--min-client-failovers", type=int, default=None,
                    metavar="N",
                    help="fail unless the failover client rerouted at "
                         "least N times (the router kill was felt)")
    fl.add_argument("--max-wire-p50-us", type=float, default=None,
                    metavar="US",
                    help="fail unless the wire stage p50 lands under US "
                         "microseconds (the pooled-connection gate; "
                         "ROUTE_r02 pinned the pre-pool baseline)")
    args = ap.parse_args(argv)
    if args.autoscale:
        if args.ab:
            ap.error("--autoscale owns the worker fleet for one live "
                     "drive; --ab wants two disposable fleets — run the "
                     "A/B without the supervisor")
        if args.no_affinity:
            ap.error("--autoscale drives the affinity ring (rendezvous "
                     "handoff across resizes is the point)")
        if args.fleet_max < args.backends:
            ap.error(f"--fleet-max {args.fleet_max} < --backends "
                     f"{args.backends} (the floor)")
        if args.kill_router_after is not None and args.routers < 1:
            ap.error("--kill-router-after needs --routers >= 1")
    elif (args.roll_after is not None or args.routers
          or args.kill_router_after is not None or args.drive_faults
          or args.fleet_policy != "static"
          or args.min_scale_ups is not None
          or args.min_scale_downs is not None
          or args.expect_rolls is not None
          or args.min_client_failovers is not None):
        ap.error("fleet-elasticity flags require --autoscale")
    if args.autoscale and (args.transfer_sizes or args.resume_drill
                           or args.kill_backend_after is not None
                           or args.worker_faults):
        ap.error("streaming-transfer flags drive the plain (non-"
                 "autoscale) path; --autoscale owns its own chaos "
                 "schedule")
    if args.ab and args.no_affinity:
        ap.error("--ab compares affinity AGAINST random routing; with "
                 "--no-affinity both arms would be random and the "
                 "affinity-gain gate could only report a false verdict")
    if args.sizes:
        try:
            args.sizes = tuple(int(s) for s in args.sizes.split(",") if s)
        except ValueError:
            ap.error(f"--sizes wants a comma list of byte counts, "
                     f"got {args.sizes!r}")
    else:
        args.sizes = (loadgen.MIXED_SIZES if args.mixed_sizes
                      else (args.size_bytes,))
    if args.transfer_sizes:
        try:
            args.transfer_sizes = tuple(
                int(s) for s in args.transfer_sizes.split(",") if s)
        except ValueError:
            ap.error(f"--transfer-sizes wants a comma list of byte "
                     f"counts, got {args.transfer_sizes!r}")
        rung = args.bucket_max * 16
        for b in args.transfer_sizes:
            if b % 16 or b <= rung:
                ap.error(f"--transfer-sizes entries must be multiples "
                         f"of 16 ABOVE the top rung ({rung} bytes) — "
                         f"anything under it is an ordinary request; "
                         f"got {b}")
        if args.transfer_every <= 0:
            ap.error("--transfer-sizes needs --transfer-every > 0")
    else:
        args.transfer_sizes = ()
        if args.resume_drill or args.min_chunk_redispatch is not None:
            ap.error("--resume-drill/--min-chunk-redispatch need "
                     "--transfer-sizes (nothing would chunk)")
    args.mode_list = tuple(m.strip() for m in args.modes.split(",")
                           if m.strip()) or ("ctr",)
    if "gcm-open" in args.mode_list and not args.verify_every:
        ap.error("--modes gcm-open requires --verify-every > 0: open "
                 "traffic replays the per-size sealed probe pairs "
                 "(serve.bench's contract, one tier up)")

    if args.unquarantine:
        if not args.journal:
            ap.error("--unquarantine requires --journal "
                     "(the ledger being edited)")
        trace.ensure_run()
        cleared = journal_mod.clear_failures(args.journal,
                                             args.unquarantine)
        for unit, n in sorted(cleared.items()):
            if n:
                trace.point("quarantine-release", unit=unit, cleared=n)
            print(f"# unquarantine: {unit}: cleared {n} failure row(s)"
                  + ("" if n else " (none recorded)"))
        return 0

    trace.ensure_run()
    probes = (loadgen.make_probes(args.sizes, args.seed, args.mode_list)
              if args.verify_every else [])

    if args.autoscale:
        return _main_fleet(args, probes)

    affinity = not args.no_affinity
    handles, specs = _spawn_backends(args, "route")
    killed = ({len(handles) - 1}
              if args.kill_backend_after is not None else frozenset())
    try:
        router, report, healthz, resume = asyncio.run(
            _drive(args, specs, affinity, probes,
                   handles=handles, drill=args.resume_drill))
    except BaseException:
        _teardown(handles, killed=killed)
        raise
    exit_docs, worker_rc = _teardown(handles, killed=killed)

    control = None
    if args.ab:
        # The control arm: fresh backends (cold keycaches — the A/B is
        # meaningless over warm ones), same seed, random routing.
        c_handles, c_specs = _spawn_backends(args, "route-ctl")
        try:
            c_router, c_report, _, _ = asyncio.run(
                _drive(args, c_specs, False, probes))
        except BaseException:
            _teardown(c_handles)
            raise
        c_exit_docs, c_rc = _teardown(c_handles)
        worker_rc = worker_rc or c_rc
        control = {
            "load": c_report.to_json(),
            "router": c_router.stats(),
            "keycache_hit_ratio": _keycache_ratio(c_exit_docs),
            "workers": c_exit_docs,
        }

    rstats = router.stats()
    lost_router = rstats["lost"]
    lost_workers = sum(d.get("lost", 0) for d in exit_docs)
    recompiles = sum(d.get("recompiles", 0) for d in exit_docs)
    backend_quarantines = sum(d.get("quarantines", 0) for d in exit_docs)
    kc_ratio = _keycache_ratio(exit_docs)
    releases = router.release_events()
    waterfall = waterfall_stats(report.ledgers)
    pulse_doc = _pulse_section(router.pulse)
    capacity = _fleet_capacity(healthz)

    print(f"# route: backends={args.backends} affinity={affinity} "
          f"vnodes={args.vnodes} tenants={args.tenants} "
          f"attempt_timeout={args.attempt_timeout:g}s "
          f"gossip={args.gossip_every:g}s")
    print(f"# requests={report.requests} ok={report.ok} "
          f"errors={report.errors or '{}'} lost_router={lost_router} "
          f"lost_workers={lost_workers} verified={report.verified} "
          f"mismatches={report.mismatches}")
    print(f"# latency ms: p50={report.p50_ms} p95={report.p95_ms} "
          f"p99={report.p99_ms}  goodput={report.goodput_gbps:.4f} GB/s "
          f"wall={report.wall_s:.3f}s")
    print(f"# failover: redispatches={rstats['redispatches']} "
          f"quarantines={rstats['quarantine_events']} releases={releases} "
          f"shed_retries={rstats['shed_retries']} "
          f"router_sheds={rstats['router_sheds']}")
    tstats = rstats.get("transfers")
    if tstats:
        print(f"# transfers: started={tstats['started']} "
              f"completed={tstats['completed']} "
              f"resumed={tstats['resumed']} "
              f"aborted={tstats['aborted']} shed={tstats['shed']} "
              f"chunks_sent={tstats['chunks_sent']} "
              f"chunk_redispatches={tstats['chunk_redispatches']} "
              f"held_peak={tstats['held_peak_bytes']}B")
    print(f"# affinity: ratio={rstats['affinity']['ratio']:.4f} "
          f"(hits={rstats['affinity']['hits']} "
          f"misses={rstats['affinity']['misses']}) "
          f"backend_keycache_hit_ratio={kc_ratio:.4f}"
          + (f" vs random={control['keycache_hit_ratio']:.4f}"
             if control else ""))
    for name, b in sorted(rstats["backends"].items()):
        tr = "".join(f" [{t['prev']}->{t['to']}:{t['why']}]"
                     for t in b["transitions"])
        skew = (f" skew={b['skew_us']:+d}µs"
                if b.get("skew_us") is not None else "")
        print(f"#   backend {name} ({b['addr']}): "
              f"{b['dispatches']} dispatch(es), {b['bytes']} bytes, "
              f"state={b['state']}{skew}{tr}")
    if waterfall["sampled"]:
        print(f"# waterfall: {waterfall['complete']}/"
              f"{waterfall['sampled']} sampled requests complete "
              f"({waterfall['complete_frac']:.1%}), stage sum within "
              f"{waterfall['tolerance']:.0%} of e2e on "
              f"{waterfall['sum_within_tol_frac']:.1%} of them")
        for s in WATERFALL_STAGES:
            st = waterfall["stages"].get(s)
            if st and st["count"]:
                print(f"#   stage {s:<13} p50={st['p50_us']:>8.0f}µs "
                      f"p95={st['p95_us']:>8.0f}µs "
                      f"p99={st['p99_us']:>8.0f}µs  (n={st['count']})")
    if pulse_doc is not None:
        fired = (" ".join(f"{r}x{n}"
                          for r, n in pulse_doc["fired"].items())
                 or "none")
        print(f"# pulse: {pulse_doc['total']} alert(s) over "
              f"{pulse_doc['frames']} frame(s) (fired: {fired})")
    if capacity is not None:
        print(f"# capacity: fleet "
              f"{capacity['total_blocks_per_s']:g} blocks/s across "
              f"{len(capacity['backends'])} worker(s)")

    artifact = {
        "config": {
            "backends": args.backends, "requests": args.requests,
            "concurrency": args.concurrency, "sizes": list(args.sizes),
            "tenants": args.tenants,
            "keys_per_tenant": args.keys_per_tenant,
            "engine": args.engine, "vnodes": args.vnodes,
            "modes": list(args.mode_list),
            "affinity": affinity, "ab": bool(args.ab),
            "attempt_timeout_s": args.attempt_timeout,
            "gossip_every_s": args.gossip_every,
            "worker_lanes": args.worker_lanes,
            "seed": args.seed,
        },
        "load": report.to_json(),
        "router": rstats,
        "queue": {"lost": lost_router + lost_workers,
                  "lost_router": lost_router,
                  "lost_workers": lost_workers},
        "compiles": {"steady": recompiles},
        "workers": exit_docs,
        "backend_quarantines_internal": backend_quarantines,
        "affinity_ab": {
            "affinity_keycache_hit_ratio": kc_ratio,
            "random_keycache_hit_ratio": (
                control["keycache_hit_ratio"] if control else None),
        },
        # The cross-process time-attribution waterfall (sampled ledger
        # population) and its per-stage percentiles — the SLO gate's
        # "stages" section, so a regression names which stage moved.
        "waterfall": waterfall,
        "stages": waterfall["stages"],
        "control": control,
        "healthz": healthz,
        "alerts": pulse_doc,
        "capacity": capacity,
        "degraded": degrade.events(),
        "metrics": metrics.snapshot(),
    }
    if tstats:
        artifact["transfers"] = {
            "chunk_blocks": args.bucket_max,
            "sizes": list(args.transfer_sizes),
            "every": args.transfer_every,
            "router": tstats,
            "load": dict(report.transfers),
        }
    if resume is not None:
        artifact["resume"] = resume
    if args.kill_backend_after is not None:
        artifact["config"]["kill_backend_after_s"] = \
            args.kill_backend_after
        artifact["killed_backend"] = f"b{args.backends - 1}"
    if args.worker_faults:
        artifact["config"]["worker_faults"] = args.worker_faults
    if trace.enabled():
        artifact["obs"] = trace.metrics_snapshot()
        artifact["trace_sample"] = trace.sample_rate()
    path = args.artifact or _next_artifact(
        _repo_root(), "STREAM" if args.transfer_sizes else "ROUTE")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(f"# artifact: {path}", file=sys.stderr)

    slo_rc = 0
    if args.slo:
        try:
            slo_rc = slo.gate(args.slo, artifact, args.slo_tolerance)
        except (OSError, ValueError, KeyError) as e:
            print(f"# slo: gate unusable: {e}", file=sys.stderr)
            slo_rc = 1

    line = {"unit": "route", "backends": args.backends,
            "affinity": affinity,
            "requests": report.requests, "ok": report.ok,
            "errors": dict(sorted(report.errors.items())),
            "lost": lost_router + lost_workers,
            "p50_ms": report.p50_ms, "p95_ms": report.p95_ms,
            "p99_ms": report.p99_ms,
            "goodput_gbps": round(report.goodput_gbps, 4),
            "redispatches": rstats["redispatches"],
            "quarantines": rstats["quarantine_events"],
            "releases": releases,
            "recompiles": recompiles,
            "mismatches": report.mismatches,
            "affinity_ratio": rstats["affinity"]["ratio"],
            "keycache_hit_ratio": kc_ratio,
            "waterfall_complete_frac": waterfall["complete_frac"],
            "waterfall_sum_ok_frac": waterfall["sum_within_tol_frac"]}
    if control:
        line["keycache_hit_ratio_random"] = control["keycache_hit_ratio"]
    if tstats:
        line["transfers"] = dict(report.transfers)
        line["chunk_redispatches"] = tstats["chunk_redispatches"]
    if resume is not None:
        line["resume"] = ("pass" if resume["interrupted"]
                          and resume["completed"]
                          and resume["byte_identical"]
                          and resume["resent_only_unacked"] else "fail")
    if args.slo:
        line["slo"] = "fail" if slo_rc else "pass"
    if degrade.events():
        line["degraded"] = degrade.events()
    if pulse_doc is not None and pulse_doc["total"]:
        line["alerts"] = pulse_doc["fired"]
    print(json.dumps(line))

    rc = 0
    if report.mismatches:
        print(f"# FAIL: {report.mismatches} probe response(s) mismatched "
              "the byte-exact reference THROUGH the router",
              file=sys.stderr)
        rc = 1
    if lost_router or lost_workers:
        print(f"# FAIL: lost requests (router={lost_router}, "
              f"workers={lost_workers}) — the drain/failover contract is "
              "broken", file=sys.stderr)
        rc = 1
    if worker_rc:
        print(f"# FAIL: a worker exited rc={worker_rc} (failed drain or "
              "SIGKILL past the drain deadline)", file=sys.stderr)
        rc = 1
    if recompiles and not args.allow_recompiles:
        print(f"# FAIL: {recompiles} post-warmup backend compile(s) "
              "across the fleet (--allow-recompiles to waive)",
              file=sys.stderr)
        rc = 1
    if args.require_zero_errors and report.errors:
        print(f"# FAIL: request errors {report.errors} — failover did "
              "not absorb the fault", file=sys.stderr)
        rc = 1
    if (args.expect_quarantines is not None
            and rstats["quarantine_events"] != args.expect_quarantines):
        print(f"# FAIL: {rstats['quarantine_events']} quarantine "
              f"event(s), expected exactly {args.expect_quarantines}",
              file=sys.stderr)
        rc = 1
    if (args.expect_releases is not None
            and releases != args.expect_releases):
        print(f"# FAIL: {releases} probation release(s), expected "
              f"exactly {args.expect_releases}", file=sys.stderr)
        rc = 1
    if (args.min_redispatch is not None
            and rstats["redispatches"] < args.min_redispatch):
        print(f"# FAIL: redispatches {rstats['redispatches']} < "
              f"{args.min_redispatch} — the failover never happened",
              file=sys.stderr)
        rc = 1
    if args.transfer_sizes:
        t = report.transfers or {}
        if not t.get("requests") or t.get("ok", 0) != t.get("requests"):
            print(f"# FAIL: transfers {t or '{}'} — every oversized "
                  "payload in the mix must complete bit-exact",
                  file=sys.stderr)
            rc = 1
    if args.min_chunk_redispatch is not None:
        got = (tstats or {}).get("chunk_redispatches", 0)
        if got < args.min_chunk_redispatch:
            print(f"# FAIL: chunk redispatches {got} < "
                  f"{args.min_chunk_redispatch} — the per-chunk "
                  "failover never happened", file=sys.stderr)
            rc = 1
    if args.resume_drill:
        if not (resume and resume["interrupted"] and resume["completed"]
                and resume["byte_identical"]
                and resume["resent_only_unacked"]):
            print(f"# FAIL: resume drill {resume} — interrupted-then-"
                  "resumed output must be byte-identical with only the "
                  "unacked chunks re-sent", file=sys.stderr)
            rc = 1
    if control is not None:
        gain = kc_ratio - control["keycache_hit_ratio"]
        floor = args.min_affinity_gain if args.min_affinity_gain is not None else 0.0
        if gain <= floor:
            print(f"# FAIL: affinity keycache hit ratio {kc_ratio:.4f} "
                  f"not better than random "
                  f"{control['keycache_hit_ratio']:.4f} by more than "
                  f"{floor:g} — key affinity bought nothing",
                  file=sys.stderr)
            rc = 1
    if (args.min_waterfall_complete is not None
            and waterfall["complete_frac"] < args.min_waterfall_complete):
        print(f"# FAIL: only {waterfall['complete_frac']:.1%} of sampled "
              f"requests reconstructed a complete cross-process "
              f"waterfall (< {args.min_waterfall_complete:.1%}) — the "
              "ledger propagation broke somewhere on the wire",
              file=sys.stderr)
        rc = 1
    if (args.min_stage_sum_ok is not None
            and waterfall["sum_within_tol_frac"] < args.min_stage_sum_ok):
        print(f"# FAIL: stage sums match end-to-end latency on only "
              f"{waterfall['sum_within_tol_frac']:.1%} of complete "
              f"waterfalls (< {args.min_stage_sum_ok:.1%}) — a stage "
              "is being double-counted across the wire (or clamps are "
              "saturating: the backend reports more time than the "
              "router observed)", file=sys.stderr)
        rc = 1
    if slo_rc:
        print(f"# FAIL: SLO regression against {args.slo}",
              file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
