"""The router's operator endpoint: /metrics + /healthz with the
ring/backend MEMBERSHIP VIEW.

Same shared HTTP responder as the serve status endpoint
(``serve.status.HttpStatusEndpoint``) — one operator surface, two
fault domains — but the router's /healthz answers the questions a
fleet operator has that no single backend can: who is on the ring,
which backend owns what share of the tracked keyspace, what state is
each backend's health machine in, and is the router itself serving,
degraded (no placeable backend), or draining. Placement is readable
HERE, without reconstructing it from traces — the membership-view
satellite of the routing-tier ISSUE.

``status`` field semantics (a load balancer's readiness answer):
``"ok"`` while at least one placeable backend exists, ``"draining"``
once ``Router.stop()`` began (admission answers ``shutdown``), else
``"degraded"`` — the same three-valued contract as the serve
/healthz, so anything that can health-check a backend can health-check
the router above it.
"""

from __future__ import annotations

from ..serve.status import HttpStatusEndpoint


class RouterStatus(HttpStatusEndpoint):
    """/metrics + /healthz for a ``route.proxy.Router``."""

    def __init__(self, router, port: int, host: str = "127.0.0.1"):
        super().__init__(port, host)
        self._router = router

    def healthz(self) -> dict:
        r = self._router
        placeable = sum(1 for b in r.backends.values()
                        if b.health.placeable())
        if r._draining:
            status = "draining"
        elif placeable > 0:
            status = "ok"
        else:
            status = "degraded"
        # The placement view: how the TRACKED (recently routed) keys
        # distribute over members right now — affinity made visible.
        # Guarded for the empty ring (every member removed): the scrape
        # must answer the "degraded" document then, not a 500.
        keys = list(r._seen_keys) if len(r.ring) else []
        share: dict[str, int] = {m: 0 for m in r.ring.members()}
        for k in keys:
            owner = r.ring.node_for(k)
            share[owner] = share.get(owner, 0) + 1
        doc = r.stats()
        doc.update({
            "status": status,
            "placeable": placeable,
            "ring": {
                "members": list(r.ring.members()),
                "vnodes": r.config.vnodes,
                "changes": r.ring_changes,
                "tracked_keys": len(keys),
                "placement": share,
            },
        })
        return doc
