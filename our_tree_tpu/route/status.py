"""The router's operator endpoint: /metrics + /healthz with the
ring/backend MEMBERSHIP VIEW.

Same shared HTTP responder as the serve status endpoint
(``serve.status.HttpStatusEndpoint``) — one operator surface, two
fault domains — but the router's /healthz answers the questions a
fleet operator has that no single backend can: who is on the ring,
which backend owns what share of the tracked keyspace, what state is
each backend's health machine in, and is the router itself serving,
degraded (no placeable backend), or draining. Placement is readable
HERE, without reconstructing it from traces — the membership-view
satellite of the routing-tier ISSUE.

``status`` field semantics (a load balancer's readiness answer):
``"ok"`` while at least one placeable backend exists, ``"draining"``
once ``Router.stop()`` began (admission answers ``shutdown``), else
``"degraded"`` — the same three-valued contract as the serve
/healthz, so anything that can health-check a backend can health-check
the router above it.
"""

from __future__ import annotations

import asyncio
import re

from ..serve.status import HttpStatusEndpoint

#: One Prometheus sample line: name, optional {labels}, value tail.
_PROM_LINE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?( .*)$")


def relabel_prometheus(text: str, **labels) -> str:
    """Inject ``labels`` into every sample line of a Prometheus text
    document (comments/TYPE lines pass through) — the federation
    rewrite: a backend's ``serve_requests_total`` becomes
    ``serve_requests_total{backend="b1"}`` in the fleet scrape, so N
    backends' identical series stay distinguishable in one document."""
    extra = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            out.append(line)
            continue
        name, lab, tail = m.groups()
        if lab:
            out.append(f"{name}{{{lab[1:-1]},{extra}}}{tail}")
        else:
            out.append(f"{name}{{{extra}}}{tail}")
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")


class RouterStatus(HttpStatusEndpoint):
    """/metrics + /healthz for a ``route.proxy.Router``.

    With ``federate=True`` (the default), ``/metrics`` is the FLEET
    scrape: the router's own registry plus every backend's ``/metrics``
    — fetched concurrently through the proxy seam
    (``Backend.poll_metrics_text``, the one backend-contact module) and
    relabeled with ``backend="<name>"`` so per-backend series stay
    distinguishable. One scrape target observes the whole per-host
    fleet; a backend that fails its scrape contributes a
    ``route_federate_scrape{backend=...,outcome=failed}``-style marker
    line instead of silently vanishing."""

    def __init__(self, router, port: int, host: str = "127.0.0.1",
                 federate: bool = True, fleet=None):
        super().__init__(port, host)
        self._router = router
        self.federate = bool(federate)
        #: The fleet supervisor (route/fleet.py FleetSupervisor) when
        #: this router autoscales — /fleetz then serves its elasticity
        #: document; None keeps the shared endpoint's 404.
        self._fleet = fleet

    def fleetz(self) -> dict | None:
        return self._fleet.fleetz() if self._fleet is not None else None

    async def metrics_text_async(self, exemplars: bool = False) -> str:
        # The router's own registry honors the scraper's OpenMetrics
        # negotiation; backend documents are relayed as scraped (plain
        # 0.0.4 — the proxy's scrape does not negotiate), so the
        # federated body never mixes exemplar tails into lines a
        # classic parser will read.
        own = self.metrics_text(exemplars=exemplars)
        if not self.federate:
            return own
        backends = [(name, b)
                    for name, b in sorted(self._router.backends.items())
                    if b.spec.status_port]
        texts = await asyncio.gather(
            *(b.poll_metrics_text() for _, b in backends),
            return_exceptions=True)
        parts = [own.rstrip("\n")]
        up: list[str] = []
        for (name, _b), text in zip(backends, texts):
            ok = isinstance(text, str) and bool(text)
            up.append(f'ot_route_federate_up{{backend="{name}"}} '
                      f'{1 if ok else 0}')
            if not ok:
                continue
            parts.append(f'# federated from backend="{name}"')
            # Backend COMMENT lines are dropped: N backends' documents
            # each carry '# TYPE serve_*' headers, and a strict
            # Prometheus parser rejects a second TYPE line for a family
            # (and split, non-contiguous family groups). The federated
            # series ride untyped — legal, and unambiguous since every
            # sample line is relabeled backend="<name>".
            parts.append("\n".join(
                ln for ln in relabel_prometheus(text, backend=name)
                .splitlines() if ln and not ln.startswith("#")))
        # One contiguous family for the liveness markers (the text
        # format requires a family's samples in one group).
        parts.append("# TYPE ot_route_federate_up gauge")
        parts.extend(up)
        return "\n".join(parts) + "\n"

    async def profilez_async(self, seconds: float) -> tuple[int, dict]:
        """The FEDERATED /profilez: relay the capture arm to every
        backend with a status port, concurrently through the proxy seam
        (``Backend.poll_profilez``) — one operator request profiles the
        whole per-host fleet, each backend enforcing its own one-window
        rule. The router itself captures nothing (the routing tier is
        device-free; its latency story is the waterfall's wire/retry
        stages). 200 when any backend armed; else 409 if any refused as
        busy; else 503 (no backend could capture)."""
        backends = [(name, b)
                    for name, b in sorted(self._router.backends.items())
                    if b.spec.status_port]
        results = await asyncio.gather(
            *(b.poll_profilez(seconds) for _, b in backends),
            return_exceptions=True)
        doc: dict = {"federated": {}}
        codes: list[int] = []
        for (name, _b), res in zip(backends, results):
            if not isinstance(res, dict):
                doc["federated"][name] = {"error": "unreachable"}
                continue
            codes.append(res["code"])
            doc["federated"][name] = {"code": res["code"], **res["doc"]}
        if 200 in codes:
            code = 200
        elif 409 in codes:
            code = 409
        else:
            code = 503
        doc["armed"] = sum(1 for c in codes if c == 200)
        return code, doc

    async def alertz_async(self) -> dict | None:
        """The FEDERATED /alertz: the router's own pulse document plus
        every backend's, fetched concurrently through the proxy seam
        (``Backend.poll_alertz``) — one operator request reads the
        whole per-host fleet's live alert state, same pattern as the
        /metrics and /profilez federation. A backend without a pulse
        engine (or unreachable) contributes an error marker instead of
        silently vanishing."""
        own = (self._router.pulse.engine.alerts_doc()
               if self._router.pulse is not None else None)
        backends = [(name, b)
                    for name, b in sorted(self._router.backends.items())
                    if b.spec.status_port]
        results = await asyncio.gather(
            *(b.poll_alertz() for _, b in backends),
            return_exceptions=True)
        doc: dict = {"router": own, "federated": {}}
        fired: dict[str, int] = {}
        total = 0
        for rule, n in ((own or {}).get("fired") or {}).items():
            fired[rule] = fired.get(rule, 0) + int(n)
        for (name, _b), res in zip(backends, results):
            if not isinstance(res, dict):
                doc["federated"][name] = {"error": "unreachable"}
                continue
            doc["federated"][name] = res
            for rule, n in (res.get("fired") or {}).items():
                fired[rule] = fired.get(rule, 0) + int(n)
        total = sum(fired.values())
        doc["fired"] = dict(sorted(fired.items()))
        doc["total"] = total
        return doc

    def healthz(self) -> dict:
        r = self._router
        placeable = sum(1 for b in r.backends.values()
                        if b.health.placeable())
        if r._draining:
            status = "draining"
        elif placeable > 0:
            status = "ok"
        else:
            status = "degraded"
        # The placement view: how the TRACKED (recently routed) keys
        # distribute over members right now — affinity made visible.
        # Guarded for the empty ring (every member removed): the scrape
        # must answer the "degraded" document then, not a 500.
        keys = list(r._seen_keys) if len(r.ring) else []
        share: dict[str, int] = {m: 0 for m in r.ring.members()}
        for k in keys:
            owner = r.ring.node_for(k)
            share[owner] = share.get(owner, 0) + 1
        doc = r.stats()
        doc.update({
            "status": status,
            "placeable": placeable,
            "ring": {
                "members": list(r.ring.members()),
                "digest": r.ring.digest(),
                "vnodes": r.config.vnodes,
                "changes": r.ring_changes,
                "tracked_keys": len(keys),
                "placement": share,
            },
        })
        return doc
