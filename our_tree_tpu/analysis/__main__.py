"""``python -m our_tree_tpu.analysis`` — the otlint CLI (driver.main).

CPU is pinned BEFORE any jax import: the jaxpr audit is structural and
must never initialize a (possibly wedged) accelerator tunnel just to
read graphs.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from .driver import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
